"""The reference's headline workflow, end to end on this framework.

Mirrors the upstream README example (SURVEY.md §0): read images into a
DataFrame, featurize with a pre-trained named CNN, train a logistic
regression on the features — as ONE Pipeline — then serve the model as
a SQL UDF over a temp view.

Run (CPU works; a TPU chip makes featurize fast):
    python examples/flagship_pipeline.py
"""

import os
import sys
import tempfile

import numpy as np
from PIL import Image

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sparkdl_tpu import DataFrame, readImages, registerImageUDF, sql
from sparkdl_tpu.ml import (
    DeepImageFeaturizer,
    LogisticRegression,
    Pipeline,
    load,
)
from sparkdl_tpu.models import registry


def make_dataset(directory: str, n: int = 32):
    """Tiny two-class image set: class c brightens channel c."""
    rng = np.random.default_rng(0)
    labels = {}
    for i in range(n):
        label = i % 2
        arr = rng.integers(0, 40, size=(64, 64, 3), dtype=np.uint8)
        arr[..., label] += 150
        path = os.path.join(directory, f"img_{i:03d}.png")
        Image.fromarray(arr).save(path)
        labels[path] = label
    return labels


def main() -> None:
    with tempfile.TemporaryDirectory() as d:
        labels = make_dataset(d)

        # 1. images -> DataFrame (Spark ImageSchema struct column;
        #    origin carries the Spark-style "file:" scheme)
        df = readImages(d, numPartition=4)
        df = df.withColumn(
            "label",
            lambda image: labels[image["origin"].removeprefix("file:")],
            inputCols=["image"])

        # 2. featurize + classify as ONE pipeline (TestNet keeps the
        #    example fast; swap modelName="InceptionV3" for the real zoo)
        pipeline = Pipeline(stages=[
            DeepImageFeaturizer(inputCol="image", outputCol="features",
                                modelName="TestNet", batchSize=16),
            LogisticRegression(maxIter=200),
        ])
        model = pipeline.fit(df)
        scored = model.transform(df).collect()
        acc = np.mean([r["prediction"] == r["label"] for r in scored])
        print(f"train accuracy: {acc:.3f}")

        # 3. persistence round-trip
        save_dir = os.path.join(d, "fitted_pipeline")
        model.save(save_dir)
        reloaded = load(save_dir)
        assert [r["prediction"] for r in reloaded.transform(df).collect()] \
            == [r["prediction"] for r in scored]
        print("save/load round-trip OK")

        # 4. model-as-SQL-UDF serving (the reference's §3.4 path)
        mf = registry.build_featurizer("TestNet", weights="random")
        registerImageUDF("featurize", mf, batchSize=16)
        df.createOrReplaceTempView("images")
        served = sql("SELECT featurize(image) AS features, label "
                     "FROM images WHERE label = 1").collect()
        print(f"SQL serving: {len(served)} rows, "
              f"{len(served[0]['features'])}-dim features")

        # 5. cluster inference plane (docs/DISTRIBUTED.md "Cluster
        #    inference"): the same transform fanned across 2 worker
        #    processes — bit-identical output, one merged report
        from sparkdl_tpu.cluster import router as cluster_router
        from sparkdl_tpu.engine import EngineConfig

        EngineConfig.cluster_workers = 2
        try:
            fanned = model.transform(df).collect()
        finally:
            EngineConfig.cluster_workers = 0
            cluster_router.shutdown()  # workers ship their snapshots here
        assert [r["prediction"] for r in fanned] \
            == [r["prediction"] for r in scored]
        report = cluster_router.last_cluster_report()
        print(f"cluster: {report['worker_count']} workers, "
              f"rows/worker {report['rows_per_worker']}, "
              f"health_consistent={report['health_consistent']}")


if __name__ == "__main__":
    main()
