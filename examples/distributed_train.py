"""Multi-host data-parallel training + inference through the public API.

The HorovodRunner-parity path (docs/DISTRIBUTED.md): launch ONE copy of
this script per host with the SPARKDL_* env triple set, and the
estimator/transformers handle partition assignment, per-host batch
shards, lockstep, and gradient all-reduce (XLA collectives) themselves.

Single-machine demo with 2 simulated hosts (4 virtual CPU devices each):

    python examples/distributed_train.py --launch

Real deployment: same script, one process per host,
SPARKDL_COORDINATOR=<host0>:<port> SPARKDL_NUM_PROCESSES=<n>
SPARKDL_PROCESS_ID=<rank>, and a mesh over the global TPU devices.
"""

import os
import sys

if __name__ == "__main__" and "--launch" not in sys.argv:
    # worker processes: simulate 4 chips per host on CPU
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=4")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def worker() -> None:
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from sparkdl_tpu.core.mesh import MeshConfig, make_mesh
    from sparkdl_tpu.engine.dataframe import DataFrame
    from sparkdl_tpu.ml import DeepImageFeaturizer
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.train.runner import maybe_initialize_distributed

    import pyarrow as pa

    assert maybe_initialize_distributed(), "SPARKDL_* env triple not set"
    pid, n = jax.process_index(), jax.process_count()
    # a global mesh drives multi-host TRAINING (estimator.fit); inference
    # below runs host-local, so none is needed here
    _ = make_mesh(MeshConfig(data=jax.device_count()))
    print(f"[host {pid}] joined: {n} processes, "
          f"{jax.device_count()} global devices")

    # identical frame on every host (real jobs read shared storage)
    rng = np.random.default_rng(0)
    rows = [{"image": imageIO.imageArrayToStruct(
        rng.integers(0, 255, size=(32, 32, 3), dtype=np.uint8)), "idx": i}
        for i in range(16)]
    schema = pa.schema([pa.field("image", imageIO.imageSchema),
                        pa.field("idx", pa.int64())])
    df = DataFrame.fromRows(rows, schema=schema, numPartitions=4)

    # transform auto-shards: this host featurizes ONLY its partitions
    out = DeepImageFeaturizer(inputCol="image", outputCol="f",
                              modelName="TestNet", batchSize=8).transform(df)
    print(f"[host {pid}] local shard: {out.count()} of {df.count()} rows")

    # opt-in gather: the FULL output frame, original order, on every host
    full = out.gatherProcesses()
    print(f"[host {pid}] gathered: {full.count()} rows")


def launch() -> None:
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({"SPARKDL_COORDINATOR": f"127.0.0.1:{port}",
                    "SPARKDL_NUM_PROCESSES": "2",
                    "SPARKDL_PROCESS_ID": str(pid)})
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env))
    for p in procs:
        assert p.wait(timeout=300) == 0
    print("both hosts finished")


if __name__ == "__main__":
    launch() if "--launch" in sys.argv else worker()
