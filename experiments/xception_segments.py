"""Xception per-segment attribution: stem / entry / middle / exit (TPU).

Uses the real Flax module with init'd params, but applies truncated
forward passes (stop after segment K) via flax module subclassing; segment
time = difference of successive slope measurements at b128 bf16.
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
from bench import make_slope_measurer  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import flax.linen as nn  # noqa: E402

from sparkdl_tpu.models.layers import (  # noqa: E402
    KERAS_BN_EPS, SeparableConvBN, global_avg_pool,
)

B = 128


class XceptionTrunc(nn.Module):
    """Xception featurize forward, stopping after ``stop`` segment:
    1=stem(block1), 2=entry(blocks2-4), 3=middle(5-12), 4=exit(13-14)+gap.

    Mirrors models/xception.py exactly so segment times are the real ones.
    """

    stop: int = 4
    dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, epsilon=KERAS_BN_EPS,
            momentum=0.99, dtype=self.dtype, name=name)

        def sep(h, features, name):
            return SeparableConvBN(features, dtype=self.dtype, name=name)(
                h, train)

        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="VALID",
                    use_bias=False, dtype=self.dtype, name="block1_conv1")(x)
        x = nn.relu(bn("block1_conv1_bn")(x))
        x = nn.Conv(64, (3, 3), padding="VALID", use_bias=False,
                    dtype=self.dtype, name="block1_conv2")(x)
        x = nn.relu(bn("block1_conv2_bn")(x))
        if self.stop == 1:
            return global_avg_pool(x)

        for i, features in zip((2, 3, 4), (128, 256, 728)):
            residual = nn.Conv(features, (1, 1), strides=(2, 2),
                               padding="SAME", use_bias=False,
                               dtype=self.dtype, name=f"block{i}_res_conv")(x)
            residual = bn(f"block{i}_res_bn")(residual)
            if i > 2:
                x = nn.relu(x)
            x = sep(x, features, f"block{i}_sepconv1")
            x = nn.relu(x)
            x = sep(x, features, f"block{i}_sepconv2")
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            x = x + residual
        if self.stop == 2:
            return global_avg_pool(x)

        for i in range(5, 13):
            residual = x
            x = nn.relu(x)
            x = sep(x, 728, f"block{i}_sepconv1")
            x = nn.relu(x)
            x = sep(x, 728, f"block{i}_sepconv2")
            x = nn.relu(x)
            x = sep(x, 728, f"block{i}_sepconv3")
            x = x + residual
        if self.stop == 3:
            return global_avg_pool(x)

        residual = nn.Conv(1024, (1, 1), strides=(2, 2), padding="SAME",
                           use_bias=False, dtype=self.dtype,
                           name="block13_res_conv")(x)
        residual = bn("block13_res_bn")(residual)
        x = nn.relu(x)
        x = sep(x, 728, "block13_sepconv1")
        x = nn.relu(x)
        x = sep(x, 1024, "block13_sepconv2")
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = x + residual
        x = sep(x, 1536, "block14_sepconv1")
        x = nn.relu(x)
        x = sep(x, 2048, "block14_sepconv2")
        x = nn.relu(x)
        return global_avg_pool(x)


def main():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(B, 299, 299, 3)).astype(np.float32) * 50
    full = XceptionTrunc(stop=4)
    variables = jax.jit(full.init)(jax.random.PRNGKey(0),
                                   jnp.zeros((1, 299, 299, 3), jnp.float32))
    times = {}
    for stop, label in ((1, "stem"), (2, "entry"), (3, "middle"),
                        (4, "full")):
        m = XceptionTrunc(stop=stop)

        def apply_fn(v, xx):
            return m.apply(v, xx.astype(jnp.bfloat16), train=False)

        meas = make_slope_measurer(apply_fn, variables, x)
        ips = max(meas()[0] for _ in range(3))
        times[label] = B / ips * 1e3
        print(f"stop={label:7s} {ips:9.1f} img/s  cum={times[label]:.2f} ms",
              flush=True)
    prev = 0.0
    for label in ("stem", "entry", "middle", "full"):
        seg = times[label] - prev
        print(f"segment {label:7s} {seg:6.2f} ms/batch128")
        prev = times[label]


if __name__ == "__main__":
    t0 = time.time()
    main()
    print(f"total {time.time() - t0:.0f}s")
