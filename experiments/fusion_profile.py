"""Capture + parse a device trace of a zoo model featurize → top-N fusions.

Produces the per-fusion cost table VERDICT r3 #1 asks for: which XLA
fusions the 32 ms Xception batch actually spends time in, so the ceiling
argument (depthwise = VPU-bound, pointwise = near-MXU-peak) is checkable
against the compiler's own schedule rather than asserted.

Run: python experiments/fusion_profile.py [trace_dir] [model] [size]
"""

import glob
import gzip
import json
import os
import sys
import tempfile
import time
from collections import defaultdict

import numpy as np

sys.path.insert(0, ".")


def capture(trace_dir: str, batches: int = 8, model: str = "Xception",
            size: int = 299) -> None:
    import jax
    import jax.numpy as jnp

    from sparkdl_tpu.models import registry

    mf = registry.build_featurizer(model, weights="random",
                                   dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 255, size=(128, size, size, 3)).astype(np.float32)
    xd = jax.device_put(x)
    fn = jax.jit(lambda v, xx: mf.apply_fn(v, xx))
    jax.device_get(fn(mf.variables, xd))  # compile outside the trace
    with jax.profiler.trace(trace_dir):
        for _ in range(batches):
            out = fn(mf.variables, xd)
        jax.device_get(out)


def parse(trace_dir: str, top: int = 20):
    """Roofline table per HLO fusion: duration, achieved TFLOP/s (the
    trace records model_flops) and achieved GB/s (bytes_accessed), grouped
    by the model layer (tf_op) the fusion implements."""
    paths = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    assert paths, f"no trace under {trace_dir}"
    with gzip.open(sorted(paths)[-1], "rt") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", [])
    agg = defaultdict(lambda: [0.0, 0, 0.0, 0.0, "", ""])
    wall = 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        if "hlo_category" not in args:
            continue  # parent jit span / host events: no double counting
        name = e.get("name", "?")
        dur = float(e.get("dur", 0.0))
        row = agg[name]
        row[0] += dur
        row[1] += 1
        row[2] = float(args.get("model_flops", 0) or 0)
        row[3] = float(args.get("raw_bytes_accessed",
                                args.get("bytes_accessed", 0)) or 0)
        op = args.get("tf_op", "")
        row[4] = "/".join(op.split("/")[1:3]) if "/" in op else op
        row[5] = args.get("hlo_category", "")
        wall += dur
    rows = sorted(agg.items(), key=lambda kv: -kv[1][0])[:top]
    print(f"device fusion time total {wall / 1e3:.1f} ms "
          f"({len(agg)} fusions)")
    print(f"{'layer (tf_op)':34s} {'category':20s} {'ms/b':>6s} {'%':>5s} "
          f"{'TF/s':>6s} {'GB/s':>6s}")
    for name, (tot, n, flops, bts, op, cat) in rows:
        per = tot / n  # us per batch execution
        tfs = flops / (per * 1e-6) / 1e12 if per else 0.0
        gbs = bts / (per * 1e-6) / 1e9 if per else 0.0
        print(f"{(op or name)[:34]:34s} {cat[:20]:20s} {per / 1e3:6.2f} "
              f"{100 * tot / wall:5.1f} {tfs:6.1f} {gbs:6.0f}")
    return rows, wall


if __name__ == "__main__":
    target = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(
        prefix="xc_trace_")
    model = sys.argv[2] if len(sys.argv) > 2 else "Xception"
    size = int(sys.argv[3]) if len(sys.argv) > 3 else 299
    t0 = time.time()
    capture(target, model=model, size=size)
    parse(target)
    print(f"total {time.time() - t0:.0f}s (trace in {target})")
