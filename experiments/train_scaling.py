"""ResNet50 training-step scaling study (VERDICT r3 #5, BASELINE config 5).

Sweeps batch size x donation for the mixed-precision jitted train step
and reports ms/step, img/s and training MFU (fwd+bwd ~= 3x fwd FLOPs).
r3 measured only b64/donate=False (27.4 ms, ~27% MFU); the HorovodRunner
north star is a *training* config, so the envelope matters.

Remat is deliberately NOT in the sweep: no batch size up to 256
approaches HBM capacity here, and remat only trades FLOPs for memory —
on a backward pass measured HBM-bandwidth-bound (docs/PERF.md) it can
only lose. The Trainer docstring records the same rationale.

Run: python experiments/train_scaling.py
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

FLOPS_FWD_IMG = 7.75e9      # ResNet50 224², 2*MACs
PEAK = 197e12


def step_time(batch_size, donate, compute_dtype="bfloat16", steps=10):
    from sparkdl_tpu.models import registry
    from sparkdl_tpu.train import Trainer

    spec = registry.get_model_spec("ResNet50")
    module = spec.builder(include_top=True, classes=spec.classes)
    h, w = spec.input_size
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, size=(batch_size, h, w, 3)).astype(np.float32)
    y = np.eye(spec.classes, dtype=np.float32)[
        rng.integers(0, spec.classes, size=batch_size)]
    variables = jax.jit(module.init)(jax.random.PRNGKey(0),
                                     jnp.zeros((1, h, w, 3), jnp.float32))
    trainer, state = Trainer.from_flax(module, variables, optimizer="sgd",
                                       learning_rate=0.01,
                                       compute_dtype=compute_dtype)
    step = trainer.make_train_step(donate=donate)
    xd, yd = jax.device_put(x), jax.device_put(y)
    state, m = step(state, xd, yd)
    jax.device_get(m["loss"])

    def run_k(k):
        nonlocal state
        t0 = time.perf_counter()
        last = None
        for _ in range(k):
            state, last = step(state, xd, yd)
        jax.device_get(last["loss"])
        return time.perf_counter() - t0

    run_k(2)
    t_small = min(run_k(2) for _ in range(3))
    t_large = min(run_k(steps) for _ in range(3))
    return (t_large - t_small) / (steps - 2)


def main():
    print(f"{'config':34s} {'ms/step':>8s} {'img/s':>8s} {'trainMFU':>9s}",
          flush=True)
    for bs in (64, 128, 256):
        for donate in (False, True):
            try:
                t = step_time(bs, donate)
            except Exception as e:  # OOM at large batch is a finding
                print(f"b{bs} donate={int(donate)}: {type(e).__name__}: "
                      f"{str(e)[:90]}", flush=True)
                continue
            mfu = 3 * FLOPS_FWD_IMG * bs / t / PEAK
            print(f"b{bs} donate={int(donate)}                  "
                  f"{t * 1e3:8.2f} {bs / t:8.1f} {mfu:9.3f}", flush=True)


if __name__ == "__main__":
    t0 = time.time()
    main()
    print(f"total {time.time() - t0:.0f}s")
