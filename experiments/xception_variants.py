"""Xception perf attribution + variant shootout (r4, VERDICT #1).

Measures, on the real chip with the slope method (bench.py), where the
middle-flow time goes and whether alternative depthwise lowerings beat
XLA's grouped-conv path:

  micro (one middle-flow block, b128 19x19x728 bf16):
    pw-only   : 3x (relu + 1x1 conv + bias)        — MXU upper bound
    dw-only   : 3x (relu + grouped depthwise)      — current dw cost
    dwshift   : 3x (relu + 9-shift elementwise dw) — VPU lowering
    block-grp : full sepconv block, grouped dw     — current
    block-sft : full sepconv block, 9-shift dw
  full model:
    module    : Xception flax module (current prod path)

Run: python experiments/xception_variants.py [micro|full]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
from bench import PEAK_TFLOPS_BF16, make_slope_measurer  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

B, H, W, C = 128, 19, 19, 728
DIMS = ("NHWC", "HWIO", "NHWC")


def measure(name, apply_fn, variables, x_np, flops_per_img=None):
    m = make_slope_measurer(apply_fn, variables, x_np)
    runs = [m() for _ in range(3)]
    ips = max(r[0] for r in runs)
    line = f"{name:12s} {ips:10.1f} img/s"
    if flops_per_img:
        line += f"  mfu={ips * flops_per_img / 1e12 / PEAK_TFLOPS_BF16:.3f}"
    print(line, flush=True)
    return ips


def dw_grouped(x, w):
    # w: (3,3,1,C) — flax depthwise form
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=DIMS, feature_group_count=C)


def dw_shift(x, w):
    # w: (3,3,1,C); nine shifted multiply-adds — pure VPU elementwise
    h, wd = x.shape[1], x.shape[2]
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    out = None
    for dy in range(3):
        for dx in range(3):
            t = xp[:, dy:dy + h, dx:dx + wd, :] * w[dy, dx, 0]
            out = t if out is None else out + t
    return out


def pw(x, k, b):
    y = jax.lax.conv_general_dilated(x, k, (1, 1), "SAME",
                                     dimension_numbers=DIMS)
    return y + b


def make_params(rng):
    p = {}
    for i in range(3):
        p[f"dw{i}"] = rng.normal(size=(3, 3, 1, C)).astype(np.float32) * 0.1
        p[f"pw{i}"] = rng.normal(size=(1, 1, C, C)).astype(np.float32) * 0.03
        p[f"b{i}"] = rng.normal(size=(C,)).astype(np.float32) * 0.01
    return jax.tree.map(lambda a: jnp.asarray(a, jnp.bfloat16), p)


def block(variables, x, dw_fn):
    res = x
    for i in range(3):
        x = jax.nn.relu(x)
        x = dw_fn(x, variables[f"dw{i}"])
        x = pw(x, variables[f"pw{i}"], variables[f"b{i}"])
    return x + res


def pw_only(variables, x):
    res = x
    for i in range(3):
        x = jax.nn.relu(x)
        x = pw(x, variables[f"pw{i}"], variables[f"b{i}"])
    return x + res


def dw_only(variables, x, dw_fn):
    res = x
    for i in range(3):
        x = jax.nn.relu(x)
        x = dw_fn(x, variables[f"dw{i}"])
    return x + res


# per-image flops for one middle block (2*MACs)
PW_FLOPS = 3 * H * W * C * C * 2
DW_FLOPS = 3 * H * W * C * 9 * 2
BLOCK_FLOPS = PW_FLOPS + DW_FLOPS


def micro():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(B, H, W, C)).astype(np.float32)
    variables = make_params(rng)

    def cast(fn):
        return lambda v, xx: fn(v, xx.astype(jnp.bfloat16))

    measure("pw-only", cast(pw_only), variables, x, PW_FLOPS)
    measure("dw-only-grp", cast(lambda v, xx: dw_only(v, xx, dw_grouped)), variables, x, DW_FLOPS)
    measure("dw-only-sft", cast(lambda v, xx: dw_only(v, xx, dw_shift)), variables, x, DW_FLOPS)
    measure("block-grp", cast(lambda v, xx: block(v, xx, dw_grouped)), variables, x, BLOCK_FLOPS)
    measure("block-sft", cast(lambda v, xx: block(v, xx, dw_shift)), variables, x, BLOCK_FLOPS)


def pallas():
    """Pallas kernels vs XLA at the same shapes — delegates to
    ``experiments/pallas_probe.py`` (r4). Measured outcome: XLA's grouped
    depthwise beats the Pallas formulations 3-6x and the fused Pallas
    sepconv loses 1.6x to XLA's dw+pw pair; no sparkdl_tpu.ops module
    ships (the ceiling analysis is in docs/PERF.md)."""
    from experiments import pallas_probe

    pallas_probe.main()


def full():
    from sparkdl_tpu.models import registry

    mf = registry.build_featurizer("Xception", weights="random",
                                   dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 255, size=(B, 299, 299, 3)).astype(np.float32)
    measure("module", mf.apply_fn, mf.variables, x, 16.8e9)


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "micro"
    t0 = time.time()
    if mode in ("micro", "all"):
        micro()
    if mode in ("pallas", "all"):
        pallas()
    if mode in ("full", "all"):
        full()
    print(f"total {time.time() - t0:.0f}s")
