"""Aggregate a fusion trace by HLO category: where does the batch go?

Companion to fusion_profile.py (which prints the top-20 individual
fusions): sums duration / FLOPs / bytes over ALL fusions per category,
giving the one-line roofline attribution per model the BASELINE.md zoo
footnote needs (VERDICT r4 #2).

Run: python experiments/category_profile.py <trace_dir> [batches=8]
"""

import glob
import gzip
import json
import sys
from collections import defaultdict


def aggregate(trace_dir: str, batches: int = 8):
    paths = glob.glob(trace_dir + "/**/*.trace.json.gz", recursive=True)
    assert paths, f"no trace under {trace_dir}"
    with gzip.open(sorted(paths)[-1], "rt") as f:
        doc = json.load(f)
    agg = defaultdict(lambda: [0.0, 0.0, 0.0, 0])  # us, flops*execs, bytes*execs, n
    wall = 0.0
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        if "hlo_category" not in args:
            continue
        cat = args["hlo_category"]
        dur = float(e.get("dur", 0.0))
        row = agg[cat]
        row[0] += dur
        row[1] += float(args.get("model_flops", 0) or 0)
        row[2] += float(args.get("raw_bytes_accessed",
                                 args.get("bytes_accessed", 0)) or 0)
        row[3] += 1
        wall += dur
    print(f"{'category':28s} {'ms/b':>7s} {'%':>6s} {'TF/s':>6s} {'GB/s':>6s}")
    for cat, (us, flops, bts, n) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        per_batch_s = us / batches / 1e6
        tfs = (flops / batches) / per_batch_s / 1e12 if per_batch_s else 0
        gbs = (bts / batches) / per_batch_s / 1e9 if per_batch_s else 0
        print(f"{cat:28s} {us / batches / 1e3:7.2f} {100 * us / wall:6.1f} "
              f"{tfs:6.1f} {gbs:6.0f}")
    print(f"total {wall / batches / 1e3:.2f} ms/batch")


if __name__ == "__main__":
    aggregate(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else 8)
