"""Pallas depthwise/sepconv probes vs XLA grouped conv (r4, VERDICT #1).

Xception's cost is depthwise-separable convs: the r4 micro shootout
(xception_variants.py) measured the pointwise 1x1s at 84.7% MFU and the
3x3 depthwise at ~1.93 TFLOP/s effective VPU rate, with block time ~=
dw time + pw time. The depthwise can't use the MXU (9-tap per-channel
stencil), so the only kernel-level questions are:

  1. What is the VPU's actual ceiling? (`fma9` — nine masked FMAs on a
     resident bf16 tile, no shifts: an upper bound for any 3x3 stencil)
  2. Do the row shifts (sublane relayouts) eat the gain? (`dw2d` — the
     real depthwise on a 2D (B*H*W, C) layout: w-shifts are +-1-row
     rolls, h-shifts +-19-row rolls, masks kill cross-image rows)
  3. Does fusing dw into the pw matmul (one VMEM residency, one HBM
     round trip) beat XLA's dw-then-pw? (`sep2d`)

Shapes: Xception middle flow, b128 19x19x728 bf16 (the flagship's worst
segment: 15.2 of 32.1 ms, xception_segments.py).

Run: python experiments/pallas_probe.py
"""

import functools
import sys
import time

import numpy as np

sys.path.insert(0, ".")


import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402

B, H, W, C = 128, 19, 19, 728
P = H * W                 # 361 positions per image
P_PAD = 368               # rows per image, padded %8 (Mosaic wants
                          # sublane-divisible block rows; 7 dead rows/img)
BT = 2                    # images per grid step (block ~1 MB: VMEM-safe
                          # with Mosaic's double buffering)
R = BT * P_PAD            # rows per block
GRID = B // BT

DW_FLOPS_APP = P * C * 9 * 2          # one dw application, per image
PW_FLOPS_APP = P * C * C * 2          # one pw application, per image


def _row_coords(r):
    rows = jax.lax.broadcasted_iota(jnp.int32, (r, 1), 0)
    p = rows % P_PAD
    return p // W, p % W  # h, w per row (p >= 361: dead pad rows)


def pad_rows(x):
    """(B, H, W, C) -> (B*P_PAD, C): image positions row-major, each
    image padded to P_PAD rows so any BT block is sublane-aligned."""
    b = x.shape[0]
    flat = x.reshape(b, P, C)
    out = np.zeros((b, P_PAD, C), flat.dtype)
    out[:, :P] = flat
    return out.reshape(b * P_PAD, C)


def unpad_rows(x2, b):
    return np.asarray(x2).reshape(b, P_PAD, C)[:, :P].reshape(b, H, W, C)


# -- probe 1: VPU ceiling (9 FMAs, no shifts) --------------------------------

def _fma9_kernel(x_ref, k_ref, o_ref):
    x = x_ref[:]
    acc = x * k_ref[0:1, :]
    for i in range(1, 9):
        acc += x * k_ref[i:i + 1, :]
    o_ref[:] = acc


def fma9(x2d, k9):
    return pl.pallas_call(
        _fma9_kernel,
        grid=(GRID,),
        in_specs=[
            pl.BlockSpec((R, C), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((9, C), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, C), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
    )(x2d, k9)


# -- probe 2: real depthwise on the 2D layout --------------------------------

def _dw_rows(x, k_ref, relu_in=False):
    """3x3 SAME depthwise on a (R, C) block holding BT images of (19,19)
    positions row-major. Shifts are circular rolls; masks (computed from
    the row index) zero rows whose source crossed an image/W/H edge —
    circular wrap rows are exactly the masked ones."""
    if relu_in:
        x = jnp.maximum(x, 0)
    rows = x.shape[0]
    h, w = _row_coords(rows)
    zero = jnp.zeros((), x.dtype)

    def shift_rows(a, s):
        """a[r] <- a[r+s], zero-filled (Mosaic bf16 has no rotate; static
        slice+concat lowers to sublane relayout copies)."""
        if s == 0:
            return a
        pad = jnp.zeros((abs(s), a.shape[1]), a.dtype)
        if s > 0:
            return jnp.concatenate([a[s:], pad], axis=0)
        return jnp.concatenate([pad, a[:s]], axis=0)

    # One combined row shift per tap (19*dy + dx): row-major positions make
    # the (dy, dx) neighbor a fixed row offset; masks kill rows whose
    # source crossed an image/H/W edge (incl. the dead pad rows — a source
    # in p>=361 only reaches dests with h==18 or itself dead, both
    # masked). Keeps live VMEM to ~3 tiles.
    acc = None
    for j, (dy, dx) in enumerate(
            (dy, dx) for dy in (-1, 0, 1) for dx in (-1, 0, 1)):
        valid = ((h + dy >= 0) & (h + dy <= H - 1)
                 & (w + dx >= 0) & (w + dx <= W - 1))
        t = jnp.where(valid, shift_rows(x, W * dy + dx),
                      zero) * k_ref[j:j + 1, :]
        acc = t if acc is None else acc + t
    return acc


def _dw2d_kernel(x_ref, k_ref, o_ref):
    o_ref[:] = _dw_rows(x_ref[:], k_ref)


def dw2d(x2d, k9):
    return pl.pallas_call(
        _dw2d_kernel,
        grid=(GRID,),
        in_specs=[
            pl.BlockSpec((R, C), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((9, C), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, C), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
    )(x2d, k9)


# -- probe 3: fused relu->dw->pw->scale/shift --------------------------------

def _sep2d_kernel(x_ref, k_ref, pw_ref, sc_ref, sh_ref, o_ref):
    t = _dw_rows(x_ref[:], k_ref, relu_in=True)
    y = jnp.dot(t, pw_ref[:], preferred_element_type=jnp.float32)
    y = y * sc_ref[0:1, :] + sh_ref[0:1, :]
    o_ref[:] = y.astype(o_ref.dtype)


def sep2d(x2d, k9, pwk, scale, shift):
    return pl.pallas_call(
        _sep2d_kernel,
        grid=(GRID,),
        in_specs=[
            pl.BlockSpec((R, C), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((9, C), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((C, C), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, C), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, C), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, C), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        cost_estimate=pl.CostEstimate(
            flops=BT * (DW_FLOPS_APP + PW_FLOPS_APP) * GRID,
            bytes_accessed=2 * x2d.size * 2,
            transcendentals=0,
        ),
    )(x2d, k9, pwk, scale, shift)


# -- XLA references at the same shapes ---------------------------------------

DIMS = ("NHWC", "HWIO", "NHWC")


def xla_dw(x4d, k4):
    return jax.lax.conv_general_dilated(
        x4d, k4, (1, 1), "SAME", dimension_numbers=DIMS,
        feature_group_count=C)


def xla_sep(x4d, k4, pwk4, scale, shift):
    t = xla_dw(jnp.maximum(x4d, 0), k4)
    y = jax.lax.conv_general_dilated(t, pwk4, (1, 1), "SAME",
                                     dimension_numbers=DIMS)
    return y * scale[0] + shift[0]


# -- correctness + timing ----------------------------------------------------

def check_correct():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, H, W, C)).astype(np.float32)
    k = rng.normal(size=(3, 3, C)).astype(np.float32) * 0.2
    x2d = jnp.asarray(pad_rows(x), jnp.bfloat16)
    k9 = jnp.asarray(k.reshape(9, C), jnp.bfloat16)

    global GRID
    g0 = GRID
    GRID = 1
    try:
        got = unpad_rows(np.asarray(dw2d(x2d, k9), np.float32), 2)
    finally:
        GRID = g0
    want = np.asarray(xla_dw(
        jnp.asarray(x, jnp.bfloat16),
        jnp.asarray(k.reshape(3, 3, 1, C), jnp.bfloat16)), np.float32)
    err = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    print(f"dw2d vs XLA grouped conv: rel err {err:.4f}", flush=True)
    assert err < 0.02, err


def make_chain_measurer(fn, x0, ks=(2, 34), repeats=4):
    """Time `fn` by CHAINING it on its own output inside one XLA program
    (shape-preserving fns only): a loop-carried array dependence with zero
    harness overhead — make_slope_measurer's f32 perturbation add+cast
    costs ~1 ms/iter at this operand size, swamping sub-ms kernels."""
    xd = jax.device_put(x0)

    @functools.partial(jax.jit, static_argnums=1)
    def chain(a, k):
        a = jax.lax.fori_loop(0, k, lambda i, t: fn(t), a)
        return jnp.sum(a[:1, :8].astype(jnp.float32))

    for k in ks:
        jax.device_get(chain(xd, k))

    def measure():
        res = {}
        for k in ks:
            ts = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                jax.device_get(chain(xd, k))
                ts.append(time.perf_counter() - t0)
            res[k] = min(ts)
        return (res[ks[1]] - res[ks[0]]) / (ks[1] - ks[0])

    return measure


def measure(name, fn, x0, flops_app, apps=1):
    m = make_chain_measurer(fn, x0)
    per_iter = min(m() for _ in range(3))
    ips = B / per_iter
    us_app = per_iter / apps * 1e6
    print(f"{name:10s} {ips:10.1f} img/s  {us_app:7.1f} us/app  "
          f"{flops_app * ips / 1e12:6.2f} TFLOP/s", flush=True)
    return us_app


def main():
    check_correct()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(B, H, W, C)).astype(np.float32)
    x2 = pad_rows(x)
    k = (rng.normal(size=(9, C)).astype(np.float32) * 0.2)
    pwk = rng.normal(size=(C, C)).astype(np.float32) * 0.03
    sc = np.abs(rng.normal(size=(1, C)).astype(np.float32))
    sh = rng.normal(size=(1, C)).astype(np.float32) * 0.01
    bf = functools.partial(jnp.asarray, dtype=jnp.bfloat16)
    v = {"k9": bf(k), "pw": bf(pwk), "sc": bf(sc), "sh": bf(sh),
         "k4": bf(k.reshape(3, 3, 1, C)), "pw4": bf(pwk.reshape(1, 1, C, C))}
    x2b = np.asarray(x2, np.float32).astype(jnp.bfloat16)
    x4b = x.astype(jnp.bfloat16)

    measure("fma9", lambda xx: fma9(xx, v["k9"]), x2b, DW_FLOPS_APP)
    measure("dw2d", lambda xx: dw2d(xx, v["k9"]), x2b, DW_FLOPS_APP)
    measure("xla-dw", lambda xx: xla_dw(xx, v["k4"]), x4b, DW_FLOPS_APP)
    measure("sep2d", lambda xx: sep2d(xx, v["k9"], v["pw"], v["sc"],
                                      v["sh"]), x2b,
            DW_FLOPS_APP + PW_FLOPS_APP)
    measure("xla-sep", lambda xx: xla_sep(xx, v["k4"], v["pw4"], v["sc"],
                                          v["sh"]), x4b,
            DW_FLOPS_APP + PW_FLOPS_APP)


if __name__ == "__main__":
    t0 = time.time()
    main()
    print(f"total {time.time() - t0:.0f}s")
