"""Can a dense block beat the walker's concat-per-layer program? (r5)

DenseNet121's measured profile (category_profile.py on the ingested
model) attributes 43% of batch time to pure ``concatenate`` fusions at
~383 GB/s — each dense layer materializes the whole growing feature
buffer again, O(L^2) channel-copies per block. This probe measures one
representative block (28x28, 128->512 channels, 12 layers, the b128
shapes of DenseNet121's block 2) under three formulations:

A) **concat** — the keras walker's program: per layer,
   ``concat(prev, new)`` then BN+relu+1x1conv+BN+relu+3x3conv.
B) **segments** — never materialize the concat: keep per-layer outputs
   as a list; each 1x1 conv over the concat becomes a SUM of per-segment
   1x1 convs (BN+relu fold into each segment — exact same math).
C) **buffer** — preallocate the block's final width once and
   ``dynamic_update_slice`` each layer's 32 channels in; convs read the
   written prefix via ``lax.slice``.

Timing: self-chained iterations inside one jit (in-program slope method;
cross-dispatch timing is unreliable over the remote PJRT tunnel).

Result (2026-07-30, 1x v5e chip, bf16, b128):

    concat (walker)       4.09 ms/block
    segment-sum           4.78 ms/block   (1.17x SLOWER than concat)
    buffer+dus           13.94 ms/block   (3.4x slower; strided channel
                                           slices force layout copies)

The walker's concat program WINS: splitting the 1x1 convs into
per-segment convs loses more MXU efficiency (C_in=32 slivers) than the
eliminated concat writes save, and the preallocated-buffer form pays
layout copies on every strided channel slice. DenseNet's O(L^2)
re-reads are architectural; XLA's concat is already the best available
formulation. See docs/PERF.md "DenseNet121" for the ceiling write-up.
"""

import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

H = W = 28
C0 = 128
GROWTH = 32
LAYERS = 12
BATCH = 128
DTYPE = jnp.bfloat16


def make_params(rng):
    params = []
    c = C0
    for _ in range(LAYERS):
        k1 = rng.normal(size=(1, 1, c, 4 * GROWTH)).astype(np.float32) * 0.05
        k3 = rng.normal(size=(3, 3, 4 * GROWTH, GROWTH)).astype(np.float32) * 0.05
        scale = rng.normal(size=(c,)).astype(np.float32) * 0.1 + 1.0
        bias = rng.normal(size=(c,)).astype(np.float32) * 0.1
        params.append((jnp.asarray(k1, DTYPE), jnp.asarray(k3, DTYPE),
                       jnp.asarray(scale, DTYPE), jnp.asarray(bias, DTYPE)))
        c += GROWTH
    return params


def conv(x, k, window=1):
    pad = "SAME" if window == 3 else "VALID"
    return lax.conv_general_dilated(
        x, k, (1, 1), pad, dimension_numbers=("NHWC", "HWIO", "NHWC"))


def layer_tail(y, k3):
    return conv(jax.nn.relu(y), k3, window=3)


def block_concat(x, params):
    for k1, k3, scale, bias in params:
        y = conv(jax.nn.relu(x * scale + bias), k1)
        new = layer_tail(y, k3)
        x = jnp.concatenate([x, new], axis=-1)
    return x


def block_segments(x, params):
    segs = [x]
    for k1, k3, scale, bias in params:
        y = None
        off = 0
        for seg in segs:
            c = seg.shape[-1]
            s, b = scale[off:off + c], bias[off:off + c]
            part = conv(jax.nn.relu(seg * s + b), k1[:, :, off:off + c, :])
            y = part if y is None else y + part
            off += c
        segs.append(layer_tail(y, k3))
    return jnp.concatenate(segs, axis=-1)


def block_buffer(x, params):
    c_final = C0 + GROWTH * LAYERS
    buf = jnp.zeros((x.shape[0], H, W, c_final), DTYPE)
    buf = lax.dynamic_update_slice(buf, x, (0, 0, 0, 0))
    c = C0
    for k1, k3, scale, bias in params:
        cur = lax.slice(buf, (0, 0, 0, 0), (x.shape[0], H, W, c))
        y = conv(jax.nn.relu(cur * scale[:c] + bias[:c]), k1)
        new = layer_tail(y, k3)
        buf = lax.dynamic_update_slice(buf, new, (0, 0, 0, c))
        c += GROWTH
    return buf


def measure(fn, params, iters=20):
    """Self-chained block iterations inside one jit -> ms per block."""

    @jax.jit
    def run(x0):
        def body(_, x):
            out = fn(x, params)
            # feed a scalar of the output back in: forces sequential
            # execution without shape growth across iterations
            return x0 + out[..., :1].mean() * 1e-6

        return lax.fori_loop(0, iters, body, x0)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(BATCH, H, W, C0)), DTYPE)
    jax.block_until_ready(run(x))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(run(x))
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e3


def main():
    rng = np.random.default_rng(1)
    params = make_params(rng)
    # equivalence check (bf16 tolerance)
    x = jnp.asarray(rng.normal(size=(2, H, W, C0)), DTYPE)
    a = np.asarray(block_concat(x, params), np.float32)
    b = np.asarray(block_segments(x, params), np.float32)
    c = np.asarray(block_buffer(x, params), np.float32)
    np.testing.assert_allclose(a, b, rtol=0.15, atol=0.15)
    np.testing.assert_allclose(a, c, rtol=0.15, atol=0.15)
    for name, fn in [("concat (walker)", block_concat),
                     ("segment-sum", block_segments),
                     ("buffer+dus", block_buffer)]:
        ms = measure(fn, params)
        print(f"{name:18s} {ms:7.2f} ms/block (b{BATCH})")


if __name__ == "__main__":
    main()
