"""Multi-model HBM residency: byte-accounted budget, eviction, pinning.

The executor's idle-state retirement (now the
``EngineConfig.executor_idle_retire_s`` knob) drops a model's coalescing
state when traffic stops; this module extends that into real policy for
MANY models registered concurrently (docs/SERVING.md "Residency"):

- every registered (model, version) carries a zero-arg ``loader``; the
  weights materialize lazily on :meth:`ResidencyManager.acquire`, under
  a ``sparkdl.model_load`` span (the cold-start cost of an eviction is
  a visible span, not a mystery latency spike);
- resident bytes are accounted with
  :meth:`~sparkdl_tpu.core.model_function.ModelFunction.weight_bytes`;
  when a load would exceed the budget, unpinned victims are evicted —
  ``"lru"`` (default) evicts the least-recently-used first,
  ``"weighted"`` evicts by ``bytes x idle-age`` (biggest-coldest
  first);
- eviction drops the ledger's model reference, clears the model's jit
  caches (``release_device_state``) and retires its executor coalescing
  states (``DeviceExecutor.retire_model``) so the weights and compiled
  executables actually become collectible;
- PINNED versions (the registry pins every active version) are never
  victims; if the pinned set alone cannot fit beside a new load,
  :class:`ResidencyExhausted` is raised instead of silently thrashing.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from sparkdl_tpu.core import executor, health, telemetry

_POLICIES = ("lru", "weighted")


class ResidencyExhausted(RuntimeError):
    """The HBM budget cannot hold this model beside the pinned set —
    raised instead of evicting a pinned (actively-deployed) version."""


class _Resident:
    """Ledger row for one (model, version); guarded by the manager's
    lock except ``loader`` (immutable)."""

    def __init__(self, name: str, version: str,
                 loader: Callable[[], Any], pinned: bool) -> None:
        self.name = name
        self.version = version
        self.loader = loader
        self.pinned = pinned
        self.model: Optional[Any] = None
        self.bytes = 0
        self.last_used = 0  # logical clock tick of the last acquire
        self.loading = False  # a thread is running the loader


class ResidencyManager:
    """Thread-safe byte-budgeted model cache. One instance per serving
    plane, attached to the :class:`~sparkdl_tpu.serving.registry.
    ModelRegistry` that routes materialization through it."""

    def __init__(self, budget_bytes: int, policy: str = "lru") -> None:
        if budget_bytes <= 0:
            raise ValueError(
                f"budget_bytes must be > 0, got {budget_bytes!r}")
        if policy not in _POLICIES:
            raise ValueError(
                f"policy must be one of {_POLICIES}, got {policy!r}")
        self._budget = int(budget_bytes)
        self._policy = policy
        # ONE Condition guards the whole ledger (its lock IS the
        # manager's lock; cold-load waiters park on it)
        self._cond = threading.Condition()
        self._residents: Dict[Tuple[str, str], _Resident] = {}
        self._clock = 0  # logical LRU clock (acquire order, not wall time)
        self._evictions = 0
        self._cold_starts = 0

    # -- registration / pinning ----------------------------------------------

    def register(self, name: str, version: str,
                 loader: Callable[[], Any], pinned: bool = False) -> None:
        """Add a (model, version) to the ledger — cheap; no load
        happens until :meth:`acquire`. Idempotent for the same key (the
        pin flag is NOT overwritten; use :meth:`pin`)."""
        key = (name, version)
        with self._cond:
            if key not in self._residents:
                self._residents[key] = _Resident(name, version, loader,
                                                 pinned)

    def pin(self, name: str, version: str, pinned: bool = True) -> None:
        """(Un)pin a version. Pinned versions are never eviction
        victims — the registry pins the active version of every model
        and moves the pin on cutover/rollback."""
        with self._cond:
            self._require_locked(name, version).pinned = bool(pinned)

    # -- the request path ----------------------------------------------------

    def acquire(self, name: str, version: str) -> Any:
        """The materialized ModelFunction for (name, version), loading
        it (cold start) and evicting victims to fit the budget when
        needed. Concurrent acquires of a cold model run ONE loader; the
        rest wait on it."""
        key = (name, version)
        with self._cond:
            resident = self._require_locked(name, version)
            while resident.loading:
                self._cond.wait()
                resident = self._require_locked(name, version)
            if resident.model is not None:
                self._clock += 1
                resident.last_used = self._clock
                return resident.model
            resident.loading = True
        # The load runs OUTSIDE the lock: loaders deserialize weights /
        # touch disk, and a slow cold start must not block acquires of
        # models that are already resident.
        t0 = time.monotonic()
        try:
            with telemetry.span(telemetry.SPAN_MODEL_LOAD, model=name,
                                version=version):
                model = resident.loader()
            nbytes = int(model.weight_bytes()) if hasattr(
                model, "weight_bytes") else 0
        except BaseException:
            with self._cond:
                resident.loading = False
                self._cond.notify_all()
            raise
        load_s = time.monotonic() - t0
        with self._cond:
            victims = self._plan_evictions_locked(nbytes, exclude=key)
            if victims is None:
                resident.loading = False
                self._cond.notify_all()
                pinned = sum(r.bytes for r in self._residents.values()
                             if r.pinned and r.model is not None)
                raise ResidencyExhausted(
                    f"cannot admit {name!r} v{version} ({nbytes} B): "
                    f"budget {self._budget} B cannot hold it beside "
                    f"{pinned} B of pinned residents")
            resident.model = model
            resident.bytes = nbytes
            self._clock += 1
            resident.last_used = self._clock
            resident.loading = False
            self._cold_starts += 1
            self._cond.notify_all()
        health.record(health.SERVING_COLD_START, model=name,
                      version=version, bytes=nbytes, seconds=load_s)
        for victim_key, victim_model, victim_bytes in victims:
            self._release(victim_key, victim_model, victim_bytes)
        return model

    # -- eviction ------------------------------------------------------------

    def evict(self, name: str, version: str) -> bool:
        """Force-evict one version (False if cold or pinned)."""
        key = (name, version)
        with self._cond:
            resident = self._require_locked(name, version)
            if resident.pinned or resident.model is None:
                return False
            model, nbytes = resident.model, resident.bytes
            resident.model = None
            resident.bytes = 0
        self._release(key, model, nbytes)
        return True

    def _plan_evictions_locked(self, incoming: int, exclude: Tuple
                               ) -> Optional[List[Tuple]]:
        """Pick victims so ``incoming`` fits the budget; clears them
        from the ledger and returns ``[(key, model, bytes), ...]`` for
        the caller to release OUTSIDE the lock. ``None`` = impossible
        (the pinned set + incoming exceed the budget)."""
        resident_total = sum(r.bytes for r in self._residents.values()
                             if r.model is not None)
        need = resident_total + incoming - self._budget
        if need <= 0:
            return []
        candidates = [r for key, r in self._residents.items()
                      if r.model is not None and not r.pinned
                      and key != exclude]
        if self._policy == "lru":
            candidates.sort(key=lambda r: r.last_used)
        else:  # weighted: biggest-coldest first
            candidates.sort(key=lambda r: r.bytes
                            * (self._clock - r.last_used + 1),
                            reverse=True)
        victims: List[Tuple] = []
        for r in candidates:
            if need <= 0:
                break
            victims.append(((r.name, r.version), r.model, r.bytes))
            need -= r.bytes
            r.model = None
            r.bytes = 0
        if need > 0:
            # roll the plan back: nothing is evicted on a failed admit
            for (name, version), model, nbytes in victims:
                row = self._residents[(name, version)]
                row.model = model
                row.bytes = nbytes
            return None
        return victims

    def _release(self, key: Tuple[str, str], model: Any,
                 nbytes: int) -> None:
        """Actually free an evicted model: jit caches, executor
        coalescing states, telemetry. Runs WITHOUT the ledger lock (it
        takes the model's jit lock and the executor's state locks)."""
        variants = (model.device_variants()
                    if hasattr(model, "device_variants") else [model])
        executor.service().retire_model(model, variants=variants)
        if hasattr(model, "release_device_state"):
            model.release_device_state()
        with self._cond:
            self._evictions += 1
        telemetry.count(telemetry.M_SERVING_EVICTIONS)
        health.record(health.SERVING_EVICTED, model=key[0],
                      version=key[1], bytes=nbytes)

    # -- introspection -------------------------------------------------------

    def resident_bytes(self) -> int:
        with self._cond:
            return sum(r.bytes for r in self._residents.values()
                       if r.model is not None)

    def resident_bytes_for(self, name: str, version: str) -> int:
        """Bytes one (model, version) currently holds resident (0 when
        cold, evicted, or unregistered) — the per-replica accounting the
        cluster serving status map reports."""
        with self._cond:
            row = self._residents.get((name, version))
            return (row.bytes if row is not None
                    and row.model is not None else 0)

    def is_resident(self, name: str, version: str) -> bool:
        with self._cond:
            row = self._residents.get((name, version))
            return row is not None and row.model is not None

    def status(self) -> Dict[str, Any]:
        with self._cond:
            return {
                "budget_bytes": self._budget,
                "policy": self._policy,
                "resident_bytes": sum(
                    r.bytes for r in self._residents.values()
                    if r.model is not None),
                "evictions": self._evictions,
                "cold_starts": self._cold_starts,
                "residents": [
                    {"model": r.name, "version": r.version,
                     "bytes": r.bytes, "pinned": r.pinned,
                     "resident": r.model is not None}
                    for r in self._residents.values()],
            }

    def _require_locked(self, name: str, version: str) -> _Resident:
        try:
            return self._residents[(name, version)]
        except KeyError:
            raise KeyError(
                f"(model={name!r}, version={version!r}) is not "
                "registered with the residency manager") from None
