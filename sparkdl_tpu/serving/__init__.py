"""Online serving plane (ROADMAP items 1 + 5, docs/SERVING.md).

The row-level front-end over the executor choke point: a
:class:`~sparkdl_tpu.serving.server.ModelServer` serves single rows and
small batches through ``core.executor.execute`` with SLO-aware
admission; a :class:`~sparkdl_tpu.serving.registry.ModelRegistry` holds
versioned deployments with shadow traffic, atomic cutover and rollback;
a :class:`~sparkdl_tpu.serving.residency.ResidencyManager` keeps many
models resident under a byte-accounted HBM budget with LRU/weighted
eviction, pinning, and ``sparkdl.model_load`` cold-start spans.

The cluster serving plane (``sparkdl_tpu/serving/cluster.py``:
replicated deployments, worker-death failover, cluster-atomic hot
swap) is deliberately NOT imported here — it loads only when
``EngineConfig.serving_cluster`` arms it, so a single-process serving
stack never pays for (or observes) the cluster machinery. Its names
resolve lazily through this package's ``__getattr__``.
"""

from sparkdl_tpu.serving.registry import (  # noqa: F401
    Deployment,
    ModelRegistry,
    default_registry,
)
from sparkdl_tpu.serving.residency import (  # noqa: F401
    ResidencyExhausted,
    ResidencyManager,
)
from sparkdl_tpu.serving.server import (  # noqa: F401
    ModelServer,
    PredictResult,
    ServingOverloaded,
)

__all__ = [
    "ClusterServingRouter",
    "CutoverFailed",
    "Deployment",
    "ModelRegistry",
    "ModelServer",
    "PredictResult",
    "ResidencyExhausted",
    "ResidencyManager",
    "ServingOverloaded",
    "WorkerServingPlane",
    "default_registry",
]

_LAZY_CLUSTER = ("ClusterServingRouter", "CutoverFailed",
                 "WorkerServingPlane")


def __getattr__(name):
    # PEP 562 lazy export: touching a cluster-serving name imports the
    # module; merely importing the serving package never does
    if name in _LAZY_CLUSTER:
        from sparkdl_tpu.serving import cluster as _cluster

        return getattr(_cluster, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
