"""Online serving plane (ROADMAP items 1 + 5, docs/SERVING.md).

The row-level front-end over the executor choke point: a
:class:`~sparkdl_tpu.serving.server.ModelServer` serves single rows and
small batches through ``core.executor.execute`` with SLO-aware
admission; a :class:`~sparkdl_tpu.serving.registry.ModelRegistry` holds
versioned deployments with shadow traffic, atomic cutover and rollback;
a :class:`~sparkdl_tpu.serving.residency.ResidencyManager` keeps many
models resident under a byte-accounted HBM budget with LRU/weighted
eviction, pinning, and ``sparkdl.model_load`` cold-start spans.
"""

from sparkdl_tpu.serving.registry import (  # noqa: F401
    Deployment,
    ModelRegistry,
    default_registry,
)
from sparkdl_tpu.serving.residency import (  # noqa: F401
    ResidencyExhausted,
    ResidencyManager,
)
from sparkdl_tpu.serving.server import (  # noqa: F401
    ModelServer,
    PredictResult,
    ServingOverloaded,
)

__all__ = [
    "Deployment",
    "ModelRegistry",
    "ModelServer",
    "PredictResult",
    "ResidencyExhausted",
    "ResidencyManager",
    "ServingOverloaded",
    "default_registry",
]
