"""Cluster serving plane: replicated deployments, failover, atomic swap.

``EngineConfig.serving_cluster`` (with ``cluster_workers > 0``) lifts
the serving plane from one process onto the cluster
(docs/SERVING.md "Cluster serving"):

- **Replication.** Every deployed (model, version) fans out to every
  live cluster worker as a cloudpickled loader blob — shipped ONCE per
  worker over the router's private task queues, the same ship-once
  stance as the batch plane's op-chain blobs. Each worker hosts a full
  replica stack (:class:`WorkerServingPlane`: its own ModelRegistry +
  an optional ``serving_worker_residency_bytes`` budget), so a replica
  is a real serving plane, not a thin stub.
- **Routing.** :meth:`ClusterServingRouter.predict` routes with load
  and locality awareness: workers that already hold the version
  HBM-resident win, least-in-flight breaks ties, and a fully-cold
  version designates ONE warming worker (single-flight across the
  cluster — N callers never trigger N cold loads).
- **Failover.** A worker death surfaces (via the router's EOF reap)
  exactly the serving request ids that worker owed answers for; each
  re-admits to a surviving replica within the CALLER's remaining
  deadline — predict is idempotent and journal-free, so the move is
  classified RETRYABLE internally and invisible to the caller beyond
  latency. Accounting is exactly-once: one ``serving_failover`` health
  event per moved request, recorded at the single re-admission site.
- **Cluster-atomic hot swap.** :meth:`ClusterServingRouter.cutover` is
  two-phase: *prepare* makes every live replica load the new version
  and ack residency (pinned, so it cannot evict before commit);
  *commit* closes the deployment's admission gate, drains in-flight
  predicts, flips ONE pointer, moves the pins, reopens. No window
  exists where two callers get different versions — the last old-
  version response strictly precedes the first new-version admission.
  Any prepare failure rolls back (new version unpinned everywhere it
  loaded, ``serving_prepare_failed`` recorded) with the old version
  still serving everywhere.

Lock order is strict and one-way: the serving lock may take the router
lock (``serving_send`` / ``serving_live_workers`` / ``serving_done``),
NEVER the reverse — the router invokes every handler callback
(``on_message`` / ``on_worker_lost`` / ``on_worker_spawn`` /
``on_close``) with its own lock released.

This module is imported ONLY when the knobs arm it
(``ModelServer._cluster`` resolves through ``sys.modules``); a
``cluster_workers=0`` process keeps the single-process serving path
byte-identical and never loads this file.
"""

from __future__ import annotations

import itertools
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from sparkdl_tpu.core import executor, health, resilience, telemetry
from sparkdl_tpu.serving import server as _server
from sparkdl_tpu.serving.registry import ModelRegistry
from sparkdl_tpu.serving.residency import ResidencyManager

__all__ = ["ClusterServingRouter", "CutoverFailed", "WorkerServingPlane",
           "exporter_status", "maybe_cluster_serving", "reset"]

# Poll cadences: waiters re-check deadline/closed between event waits
# (defense against lost wakeups, same stance as the batch router).
_WAIT_POLL_S = 0.05
_GATE_POLL_S = 0.05
# Default bound on a cutover's prepare acks and commit drain — cold
# loads are slow, but a wedged replica must not hold the swap forever.
_CUTOVER_TIMEOUT_S = 60.0


class CutoverFailed(RuntimeError):
    """A cluster-atomic cutover aborted — prepare failed on some
    replica (or the commit drain timed out) and was rolled back: the
    previous version is still serving everywhere, nothing flipped."""


# =============================================================================
# Worker side: one replica stack per cluster worker process
# =============================================================================

class WorkerServingPlane:
    """One cluster worker's serving replica: a private ModelRegistry
    (plus a byte-budgeted ResidencyManager when
    ``EngineConfig.serving_worker_residency_bytes`` is set) fed by
    ``srv_*`` messages off the worker's task queue. Single-threaded by
    construction — the worker loop is the only caller — so no locking
    here; replies go back over the worker's private result pipe (one
    writer per pipe, the transport invariant)."""

    def __init__(self, worker_id: int, name: str, conn: Any) -> None:
        from sparkdl_tpu.engine.dataframe import EngineConfig

        self.worker_id = worker_id
        self.name = name
        self._conn = conn
        budget = EngineConfig.serving_worker_residency_bytes
        self._residency: Optional[ResidencyManager] = (
            ResidencyManager(budget) if budget else None)
        # defer_warmup: a replica materializes (and AOT-warms, when
        # serving_warmup is armed) on ITS cold load — first routed
        # predict or srv_prepare — never at the deploy fan, which would
        # load every version on every replica and turn a broken loader
        # into a worker death instead of a prepare nack.
        self._registry = ModelRegistry(residency=self._residency,
                                       defer_warmup=True)
        self._deployed: Dict[Tuple[str, str], Any] = {}
        self._predicts = 0
        self._errors = 0

    # -- message dispatch ----------------------------------------------------

    def handle(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "srv_deploy":
            self._deploy(*msg[1:])
        elif kind == "srv_retire":
            self._retire(*msg[1:])
        elif kind == "srv_pin":
            self._pin(*msg[1:])
        elif kind == "srv_prepare":
            self._prepare(*msg[1:])
        elif kind == "srv_predict":
            self._predict(*msg[1:])
        # unknown srv_* kinds are ignored: a worker must not die (and
        # take its in-flight answers with it) over a message it does
        # not speak

    def _deploy(self, name: str, version: str, blob: bytes,
                batch_size: int, latency_target_ms: Optional[float],
                pinned: bool) -> None:
        """Idempotent: replica top-ups re-fan every deployment to a
        fresh worker, and a retire/redeploy cycle reuses the immutable
        registry record."""
        key = (name, version)
        if key not in self._deployed:
            import cloudpickle

            loader = cloudpickle.loads(blob)
            try:
                dep = self._registry.deploy(
                    name, version, loader=loader,
                    latency_target_ms=latency_target_ms,
                    batch_size=batch_size)
            except ValueError:
                # redeploy after retire: versions are immutable, reuse
                dep = self._registry.deployment(name, version)
            self._deployed[key] = dep
        if self._residency is not None:
            self._residency.pin(name, version, pinned=bool(pinned))

    def _retire(self, name: str, version: str) -> None:
        if self._deployed.pop((name, version), None) is None:
            return
        if self._residency is not None:
            self._residency.pin(name, version, pinned=False)
            self._residency.evict(name, version)

    def _pin(self, name: str, version: str, pinned: bool) -> None:
        if (self._residency is not None
                and (name, version) in self._deployed):
            self._residency.pin(name, version, pinned=bool(pinned))

    def _prepare(self, req_id: int, name: str, version: str) -> None:
        """Phase one of a cluster-atomic cutover, replica-side: pin the
        incoming version FIRST (it must not evict in the gap before
        commit), then load it and ack residency."""
        try:
            dep = self._require(name, version)
            if self._residency is not None:
                self._residency.pin(name, version, pinned=True)
            dep.model()  # cold load under the sparkdl.model_load span
        # sparkdl: allow(broad-retry): not a retry — the failure ships typed to the coordinator, which owns the rollback decision
        except Exception as e:  # noqa: BLE001 - nacked to coordinator
            self._conn.send(("srv_prepared", req_id, False,
                             f"{type(e).__name__}: {e}",
                             self._resident_bytes()))
            return
        self._conn.send(("srv_prepared", req_id, True, None,
                         self._resident_bytes()))

    def _predict(self, req_id: int, name: str, version: str,
                 payload: bytes, deadline_ms: Optional[float],
                 priority: str, tenant: Optional[str], ctx: Any,
                 crash: bool) -> None:
        """One routed request: stage exactly as the single-process
        ModelServer stages (shared helpers — the chaos proof compares
        outputs bit-for-bit), execute through THIS worker's executor
        choke point, answer over the pipe. ``crash`` is the armed
        ``serving_worker_kill`` marker: die as hard as a machine loss,
        no cleanup — the coordinator's failover leg takes it from
        there."""
        if crash:
            os.kill(os.getpid(), signal.SIGKILL)
        t0 = time.perf_counter()
        try:
            import cloudpickle

            dep = self._require(name, version)
            rows = cloudpickle.loads(payload)
            batch, single = _server.stage_rows(dep, rows)
            deadline = (resilience.Deadline(deadline_ms / 1e3)
                        if deadline_ms is not None else None)
            with telemetry.span(telemetry.SPAN_SERVING_PREDICT,
                                parent=ctx, model=name, version=version,
                                cluster_worker=self.worker_id):
                out = executor.execute(
                    dep.model(), batch, batch_size=dep.batch_size,
                    priority=priority, deadline=deadline,
                    coalesce_window_ms=_server.target_window_ms(dep),
                    tenant=tenant)
            import jax

            if single:
                out = jax.tree_util.tree_map(
                    lambda a: np.asarray(a)[0], out)
            else:
                out = jax.tree_util.tree_map(np.asarray, out)
            blob = cloudpickle.dumps(out)
        # sparkdl: allow(broad-retry): not a retry — the error ships typed (with its classify kind) to the coordinator, whose failover loop owns the retry decision
        except Exception as e:  # noqa: BLE001 - re-raised caller-side
            self._errors += 1
            self._conn.send(("srv_err", req_id, type(e).__name__,
                             str(e), resilience.classify(e)))
            return
        self._predicts += 1
        self._conn.send(("srv_ok", req_id, blob,
                         {"exec_s": time.perf_counter() - t0,
                          "resident_bytes": self._resident_bytes()}))

    # -- plumbing ------------------------------------------------------------

    def _require(self, name: str, version: str) -> Any:
        dep = self._deployed.get((name, version))
        if dep is None:
            raise KeyError(
                f"worker {self.name} holds no deployment {name!r} "
                f"v{version!r} — the deploy fan-out never arrived")
        return dep

    def _resident_bytes(self) -> int:
        if self._residency is not None:
            return self._residency.resident_bytes()
        return sum(dep.resident_bytes()
                   for dep in self._deployed.values())

    def stats(self) -> Dict[str, Any]:
        """This replica's end-of-run section, shipped inside the final
        snapshot (``cluster/aggregate.py`` folds them cluster-wide)."""
        deployments = []
        for (name, version), dep in sorted(self._deployed.items()):
            if self._residency is not None:
                resident = self._residency.is_resident(name, version)
            else:
                resident = dep._model is not None  # no-budget: memoized
            deployments.append({"model": name, "version": version,
                                "resident": resident,
                                "bytes": dep.resident_bytes()})
        return {"worker": self.name,
                "predicts": self._predicts,
                "errors": self._errors,
                "resident_bytes": self._resident_bytes(),
                "deployments": deployments}


# =============================================================================
# Coordinator side: the replicated-serving router
# =============================================================================

class _VersionRoute:
    """Coordinator-side view of one replicated (model, version);
    every field is guarded by the owning ClusterServingRouter's lock."""

    __slots__ = ("blob", "batch_size", "latency_target_ms", "deployed",
                 "resident", "warming")

    def __init__(self, blob: bytes, batch_size: int,
                 latency_target_ms: Optional[float]) -> None:
        self.blob = blob
        self.batch_size = batch_size
        self.latency_target_ms = latency_target_ms
        self.deployed: Set[int] = set()   # wids holding the loader
        self.resident: Set[int] = set()   # wids that have answered hot
        self.warming: Optional[int] = None  # single-flight cold target

class _DeploymentRoute:
    """Per-model routing state. ``gate`` is the admission gate a
    cluster-atomic cutover closes for its commit window; ``swap_lock``
    serializes cutovers per deployment."""

    __slots__ = ("name", "active", "previous", "versions", "gate",
                 "inflight", "swap_lock")

    def __init__(self, name: str, active: str) -> None:
        self.name = name
        self.active = active
        self.previous: Optional[str] = None
        self.versions: Dict[str, _VersionRoute] = {}
        self.gate = threading.Event()
        self.gate.set()
        self.inflight = 0
        self.swap_lock = threading.Lock()

class _Call:
    """One in-flight serving exchange (predict or prepare). Fields are
    written under the serving lock; the waiter reads them only after
    ``event`` is set."""

    __slots__ = ("req_id", "kind", "name", "version", "payload",
                 "deadline", "deadline_ms_total", "priority", "tenant",
                 "ctx", "event", "blob", "meta", "result", "error",
                 "worker", "failovers")

    def __init__(self, kind: str, name: str) -> None:
        self.req_id = 0
        self.kind = kind
        self.name = name
        self.version: Optional[str] = None
        self.payload: Optional[bytes] = None
        self.deadline: Optional[float] = None  # absolute monotonic
        self.deadline_ms_total: Optional[float] = None
        self.priority = executor.PRIORITY_INTERACTIVE
        self.tenant: Optional[str] = None
        self.ctx: Any = None
        self.event = threading.Event()
        self.blob: Optional[bytes] = None
        self.meta: Dict[str, Any] = {}
        self.result: Optional[Tuple] = None  # prepare: (ok, err, bytes)
        self.error: Optional[BaseException] = None
        self.worker: Optional[int] = None
        self.failovers = 0

    def remaining_ms(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return max(0.0, (self.deadline - time.monotonic()) * 1e3)


class ClusterServingRouter:
    """Routes ``ModelServer.predict`` across the cluster's replica set
    and owns failover re-admission plus the two-phase cutover. One
    instance per :class:`~sparkdl_tpu.cluster.router.ClusterRouter`
    (it attaches itself as the router's serving handler)."""

    def __init__(self, router: Any) -> None:
        self.router = router
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._routes: Dict[str, _DeploymentRoute] = {}
        self._pending: Dict[int, _Call] = {}
        self._ids = itertools.count(1)
        self._wid_inflight: Dict[int, int] = {}
        self._worker_bytes: Dict[int, int] = {}
        self._predicts = 0
        self._failovers = 0
        self._moved: List[int] = []
        self._cutovers = 0
        self._prepare_failures = 0
        self._closed = False
        router.serving_attach(self)

    @property
    def closed(self) -> bool:
        return self._closed

    # -- deployment fan-out --------------------------------------------------

    def _ensure(self, name: str, registry: Any,
                adopt: bool = True) -> None:
        """Reconcile the coordinator registry into the replica plane:
        versions the routes have not seen fan out to every live worker
        (ship-once), and — with ``adopt`` — a registry active pointer
        the router has not adopted yet (someone called
        ``registry.cutover`` directly) converges through the
        cluster-atomic two-phase swap."""
        deps = registry.deployments(name)
        reg_active = registry.active_version(name)
        with self._lock:
            route = self._routes.get(name)
            missing = [v for v in sorted(deps)
                       if route is None or v not in route.versions]
        blobs: Dict[str, bytes] = {}
        if missing:
            # pickling runs OUTSIDE the lock: loaders can close over
            # real weights
            import cloudpickle

            for v in missing:
                blobs[v] = cloudpickle.dumps(deps[v].loader)
        with self._lock:
            route = self._routes.get(name)
            if route is None:
                route = _DeploymentRoute(name, reg_active)
                self._routes[name] = route
            live: Optional[List[int]] = None
            for v in missing:
                if v in route.versions:
                    continue  # raced with a sibling _ensure
                dep = deps[v]
                vr = _VersionRoute(blobs[v], dep.batch_size,
                                   dep.latency_target_ms)
                route.versions[v] = vr
                if live is None:
                    live = self.router.serving_live_workers()
                self._fan_deploy_locked(name, route, v, vr, live)
            mismatch = (adopt and route.active != reg_active
                        and reg_active in route.versions)
        if mismatch:
            self.cutover(name, registry, reg_active)

    def _fan_deploy_locked(self, name: str, route: _DeploymentRoute,
                           version: str, vr: _VersionRoute,
                           wids: Sequence[int]) -> None:
        """Ship one version's loader blob to ``wids``. Under the
        serving lock so a version never becomes routable on a worker
        before its deploy message is enqueued (the queue is FIFO: the
        deploy strictly precedes any predict we route there)."""
        for wid in wids:
            if wid in vr.deployed:
                continue
            try:
                self.router.serving_send(
                    wid, ("srv_deploy", name, version, vr.blob,
                          vr.batch_size, vr.latency_target_ms,
                          version == route.active))
            except (resilience.ServingReplicaLost,
                    resilience.WorkerDraining):
                continue  # leaving anyway; EOF reap will drop it
            vr.deployed.add(wid)

    def on_worker_spawn(self, wid: int) -> None:
        """Router callback (post-spawn, router lock released): top the
        replacement worker up with every deployment + the active pins,
        restoring the replication factor."""
        with self._lock:
            if self._closed:
                return
            for name, route in sorted(self._routes.items()):
                for version, vr in sorted(route.versions.items()):
                    self._fan_deploy_locked(name, route, version, vr,
                                            (wid,))

    def retire(self, name: str, version: str) -> None:
        """Retire one non-active version cluster-wide (evicted and
        unpinned on every replica; the route forgets it)."""
        with self._lock:
            route = self._routes.get(name)
            if route is None or version not in route.versions:
                return
            if version == route.active:
                raise ValueError(
                    f"model {name!r} v{version!r} is the active "
                    "version; cut over before retiring it")
            vr = route.versions.pop(version)
            for wid in sorted(vr.deployed):
                try:
                    self.router.serving_send(
                        wid, ("srv_retire", name, version))
                except (resilience.ServingReplicaLost,
                        resilience.WorkerDraining):
                    continue

    # -- the request path ----------------------------------------------------

    def predict(self, name: str, registry: Any, rows: Any, *,
                deadline_ms: Optional[float] = None,
                priority: str = executor.PRIORITY_INTERACTIVE,
                tenant: Optional[str] = None,
                ctx: Any = None) -> Tuple[Any, str]:
        """Route one request to a replica and await its answer; returns
        ``(output, version)``. The version resolves ONCE at admission
        (under the serving lock, gated by any in-progress cutover) and
        failover re-admission keeps it — a moved request never switches
        versions mid-flight."""
        self._ensure(name, registry)
        import cloudpickle

        call = _Call("predict", name)
        call.payload = cloudpickle.dumps(rows)
        call.priority = priority
        call.tenant = tenant
        call.ctx = ctx
        call.deadline_ms_total = deadline_ms
        if deadline_ms is not None:
            call.deadline = time.monotonic() + deadline_ms / 1e3
        with self._lock:
            route = self._routes[name]
        while True:
            # the admission gate: a cluster-atomic cutover closes the
            # deployment for its commit window; new predicts wait for
            # the flip (bounded by their own deadline), never race it
            if not route.gate.wait(timeout=_GATE_POLL_S):
                self._check_admission(call)
                continue
            with self._lock:
                if self._closed:
                    raise resilience.ServingReplicaLost(
                        "the cluster serving plane is closed")
                if not route.gate.is_set():
                    continue  # re-closed between wait and lock
                version = route.active
                wid = self._pick_locked(route, version)
                if wid is None:
                    raise resilience.ServingReplicaLost(
                        f"no live replica can serve {name!r} "
                        f"v{version!r} — every deployed worker is lost "
                        "or draining")
                call.version = version
                call.req_id = next(self._ids)
                self._pending[call.req_id] = call
                route.inflight += 1
                try:
                    self._dispatch_locked(call, wid)
                except (resilience.ServingReplicaLost,
                        resilience.WorkerDraining):
                    # died/drained between pick and send; try another
                    self._pending.pop(call.req_id, None)
                    route.inflight -= 1
                    continue
            break
        blob = self._await(call)
        return cloudpickle.loads(blob), call.version

    def _check_admission(self, call: _Call) -> None:
        if (call.deadline is not None
                and time.monotonic() >= call.deadline):
            raise resilience.DeadlineExceeded(
                f"predict on {call.name!r} spent its "
                f"{call.deadline_ms_total:.0f} ms deadline waiting on "
                "the cutover gate")
        if self._closed or self.router.closed:
            raise resilience.ServingReplicaLost(
                "cluster router closed while the request waited for "
                "admission")

    def _pick_locked(self, route: _DeploymentRoute,
                     version: str) -> Optional[int]:
        return self._pick_excluding_locked(route, version, ())

    def _pick_excluding_locked(self, route: _DeploymentRoute,
                               version: str,
                               exclude: Sequence[int]) -> Optional[int]:
        """Locality- and load-aware replica choice: HBM-resident
        workers first, least-in-flight breaks ties; a fully-cold
        version routes through ONE designated warming worker
        (cluster-wide single-flight on the cold load)."""
        vr = route.versions[version]
        live = [wid for wid in self.router.serving_live_workers()
                if wid in vr.deployed and wid not in exclude]
        if telemetry.active() is not None:
            telemetry.gauge_set(telemetry.M_SERVING_REPLICAS, len(live))
        if not live:
            return None
        resident = [wid for wid in live if wid in vr.resident]
        if resident:
            return min(resident, key=lambda w:
                       (self._wid_inflight.get(w, 0), w))
        if vr.warming in live:
            return vr.warming
        wid = min(live, key=lambda w: (self._wid_inflight.get(w, 0), w))
        vr.warming = wid
        return wid

    def _dispatch_locked(self, call: _Call, wid: int) -> None:
        crash = resilience.should_fire("serving_worker_kill",
                                       model=call.name,
                                       request=call.req_id)
        self.submit_predict(wid, call, tenant=call.tenant, crash=crash)
        call.worker = wid
        self._wid_inflight[wid] = self._wid_inflight.get(wid, 0) + 1

    def submit_predict(self, wid: int, call: _Call, *,
                       tenant: Optional[str],
                       crash: bool = False) -> None:
        """Wire-level predict dispatch (the serving-scope tenant lint
        covers this call site's callers: every dispatch names its
        tenant). The message carries the REMAINING deadline — a
        failed-over request re-admits with whatever budget its caller
        still has, not a fresh one."""
        self.router.serving_send(
            wid, ("srv_predict", call.req_id, call.name, call.version,
                  call.payload, call.remaining_ms(), call.priority,
                  tenant, call.ctx, crash),
            req_id=call.req_id)

    def _await(self, call: _Call) -> bytes:
        while not call.event.wait(_WAIT_POLL_S):
            if (call.deadline is not None
                    and time.monotonic() >= call.deadline):
                self._abandon(call)
                raise resilience.DeadlineExceeded(
                    f"predict {call.req_id} on {call.name!r} exceeded "
                    f"its {call.deadline_ms_total:.0f} ms deadline "
                    f"({call.failovers} failover(s))")
        if call.error is not None:
            raise call.error
        assert call.blob is not None
        return call.blob

    def _abandon(self, call: _Call) -> None:
        """Deadline-expired waiter: withdraw the pending entry so a
        late answer (or a failover) cannot resurrect the request."""
        with self._lock:
            if self._pending.pop(call.req_id, None) is None:
                return  # resolved concurrently; the answer path won
            route = self._routes.get(call.name)
            if route is not None:
                route.inflight -= 1
                self._cond.notify_all()
            if call.worker is not None:
                n = self._wid_inflight.get(call.worker, 0)
                if n > 1:
                    self._wid_inflight[call.worker] = n - 1
                else:
                    self._wid_inflight.pop(call.worker, None)
        if call.worker is not None:
            self.router.serving_done(call.worker, call.req_id)

    # -- router callbacks (collector thread; router lock released) -----------

    def on_message(self, wid: int, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "srv_prepared":
            _, req_id, ok, err, nbytes = msg
            self.router.serving_done(wid, req_id)
            with self._lock:
                self._worker_bytes[wid] = int(nbytes)
                call = self._pending.pop(req_id, None)
                if call is None:
                    return
                call.result = (bool(ok), err, int(nbytes))
                if ok:
                    self._mark_resident_locked(call.name, call.version,
                                               wid)
                call.event.set()
            return
        if kind == "srv_ok":
            _, req_id, blob, meta = msg
            self._resolve(wid, req_id, blob=blob, meta=meta)
        elif kind == "srv_err":
            from sparkdl_tpu.cluster.router import _rebuild_error

            _, req_id, type_name, message, err_kind = msg
            self._resolve(wid, req_id,
                          error=_rebuild_error(type_name, message,
                                               err_kind))

    def _resolve(self, wid: int, req_id: int, blob: Optional[bytes] = None,
                 meta: Optional[Dict] = None,
                 error: Optional[BaseException] = None) -> None:
        self.router.serving_done(wid, req_id)
        with self._lock:
            call = self._pending.pop(req_id, None)
            if call is None:
                return  # abandoned at its deadline; late answer dropped
            if error is None:
                call.blob = blob
                call.meta = dict(meta or {})
                self._worker_bytes[wid] = int(
                    call.meta.get("resident_bytes", 0))
                self._predicts += 1
                self._mark_resident_locked(call.name, call.version, wid)
            else:
                call.error = error
            self._finish_locked(call)

    def _mark_resident_locked(self, name: str, version: Optional[str],
                              wid: int) -> None:
        route = self._routes.get(name)
        if route is None or version not in route.versions:
            return
        vr = route.versions[version]
        vr.resident.add(wid)
        if vr.warming == wid:
            vr.warming = None

    def _finish_locked(self, call: _Call) -> None:
        if call.kind == "predict":
            route = self._routes.get(call.name)
            if route is not None:
                route.inflight -= 1
            if call.worker is not None:
                n = self._wid_inflight.get(call.worker, 0)
                if n > 1:
                    self._wid_inflight[call.worker] = n - 1
                else:
                    self._wid_inflight.pop(call.worker, None)
        call.event.set()
        self._cond.notify_all()

    def on_worker_lost(self, wid: int, req_ids: Sequence[int]) -> None:
        """A worker died owing answers for exactly ``req_ids``. Each
        in-flight predict re-admits to a surviving replica within its
        caller's remaining deadline (idempotent + journal-free, so the
        move needs no recovery protocol); a prepare in flight fails the
        cutover (its waiter rolls back). Exactly-once accounting: this
        is the ONLY site that records ``serving_failover``, one event
        per moved request."""
        moved: List[Tuple[_Call, Optional[int]]] = []
        with self._lock:
            self._wid_inflight.pop(wid, None)
            self._worker_bytes.pop(wid, None)
            for route in self._routes.values():
                for vr in route.versions.values():
                    vr.deployed.discard(wid)
                    vr.resident.discard(wid)
                    if vr.warming == wid:
                        vr.warming = None
            for req_id in req_ids:
                call = self._pending.get(req_id)
                if call is None:
                    continue
                if call.kind == "prepare":
                    call.result = (
                        False, f"worker {wid} died before acking the "
                        f"prepare of {call.name!r} v{call.version!r}",
                        0)
                    self._pending.pop(req_id, None)
                    self._finish_locked(call)
                    continue
                err = self._readmit_locked(call, wid)
                if err is None:
                    moved.append((call, call.worker))
                else:
                    call.error = err
                    self._pending.pop(req_id, None)
                    self._finish_locked(call)
        for call, to_wid in moved:
            health.record(health.SERVING_FAILOVER, model=call.name,
                          version=call.version, request=call.req_id,
                          from_worker=wid, to_worker=to_wid)
            if telemetry.active() is not None:
                telemetry.count(telemetry.M_SERVING_FAILOVER)

    def _readmit_locked(self, call: _Call,
                        dead_wid: int) -> Optional[BaseException]:
        """Re-dispatch one orphaned predict; returns the error that
        fails it instead, or None when it moved."""
        from sparkdl_tpu.engine.dataframe import EngineConfig

        limit = max(0, int(EngineConfig.serving_failover_max))
        if call.failovers >= limit:
            return resilience.ServingReplicaLost(
                f"predict {call.req_id} on {call.name!r} "
                f"v{call.version!r} lost its worker "
                f"{call.failovers + 1} time(s); the failover budget "
                f"({limit}) is spent")
        if (call.deadline is not None
                and time.monotonic() >= call.deadline):
            return resilience.DeadlineExceeded(
                f"predict {call.req_id} on {call.name!r} lost worker "
                f"{dead_wid} with no deadline budget left to re-admit")
        route = self._routes.get(call.name)
        if route is None or call.version not in route.versions:
            return resilience.ServingReplicaLost(
                f"predict {call.req_id}: deployment {call.name!r} "
                f"v{call.version!r} is no longer routed")
        wid = self._pick_excluding_locked(route, call.version,
                                          (dead_wid,))
        if wid is None:
            return resilience.ServingReplicaLost(
                f"predict {call.req_id} on {call.name!r} "
                f"v{call.version!r}: worker {dead_wid} died and no "
                "surviving replica holds the version")
        try:
            self._dispatch_locked(call, wid)
        except (resilience.ServingReplicaLost,
                resilience.WorkerDraining) as e:
            return e
        call.failovers += 1
        # sparkdl: allow(unguarded-shared-write): caller holds self._lock (the _locked-suffix contract)
        self._failovers += 1
        self._moved.append(call.req_id)
        return None

    def on_close(self) -> None:
        """Router shutdown: fail every orphaned exchange (their waiters
        would otherwise poll until their deadlines) and open every gate
        so blocked admissions observe the closed state."""
        with self._lock:
            self._closed = True
            pending = list(self._pending.values())
            self._pending.clear()
            for call in pending:
                if call.kind == "prepare":
                    call.result = (False, "cluster router closed "
                                   "mid-prepare", 0)
                else:
                    call.error = resilience.ServingReplicaLost(
                        "cluster router closed while the request was "
                        "in flight")
                self._finish_locked(call)
            for route in self._routes.values():
                route.gate.set()

    # -- cluster-atomic hot swap ---------------------------------------------

    def cutover(self, name: str, registry: Any, version: str, *,
                timeout_s: float = _CUTOVER_TIMEOUT_S) -> str:
        """Two-phase cluster-atomic hot swap; returns the previous
        active version.

        *Prepare*: every live replica loads ``version`` (pinned) and
        acks residency. Any nack, death, or timeout aborts: the new
        version unpins everywhere it prepared
        (``serving_prepare_failed`` recorded) and :class:`CutoverFailed`
        raises with the old version still serving everywhere.

        *Commit*: the deployment's admission gate closes, in-flight
        predicts drain, ONE pointer flips (plus the coordinator
        registry's, which records ``serving_cutover`` and moves its
        pins), worker pins move, the gate reopens. The last old-version
        response strictly precedes the first new-version admission —
        no caller pair can ever observe mixed versions."""
        self._ensure(name, registry, adopt=False)
        with self._lock:
            route = self._routes.get(name)
            if route is None or version not in route.versions:
                raise KeyError(
                    f"model {name!r} has no version {version!r} to cut "
                    "over to")
            swap_lock = route.swap_lock
        with swap_lock:
            with self._lock:
                prev = route.active
                if prev == version:
                    return prev
                vr = route.versions[version]
                targets = [wid for wid in
                           self.router.serving_live_workers()
                           if wid in vr.deployed]
                if not targets:
                    raise CutoverFailed(
                        f"no live replica holds {name!r} v{version!r} "
                        "to prepare")
                calls: List[_Call] = []
                for wid in targets:
                    call = _Call("prepare", name)
                    call.version = version
                    call.req_id = next(self._ids)
                    call.worker = wid
                    self._pending[call.req_id] = call
                    try:
                        self.router.serving_send(
                            wid, ("srv_prepare", call.req_id, name,
                                  version), req_id=call.req_id)
                    except (resilience.ServingReplicaLost,
                            resilience.WorkerDraining):
                        # leaving anyway — not serving either version
                        self._pending.pop(call.req_id, None)
                        continue
                    calls.append(call)
            failure: Optional[str] = None
            ack_deadline = time.monotonic() + timeout_s
            for call in calls:
                remaining = max(0.0, ack_deadline - time.monotonic())
                if not call.event.wait(remaining):
                    with self._lock:
                        self._pending.pop(call.req_id, None)
                    self.router.serving_done(call.worker, call.req_id)
                    failure = (f"worker {call.worker} did not ack the "
                               f"prepare within {timeout_s:.0f}s")
                    break
                ok, err, _ = call.result
                if not ok:
                    failure = err
                    break
            if failure is not None:
                self._rollback_prepare(name, version, targets)
                health.record(health.SERVING_PREPARE_FAILED, model=name,
                              version=version, error=failure)
                with self._lock:
                    self._prepare_failures += 1
                raise CutoverFailed(
                    f"cluster cutover of {name!r} to v{version!r} "
                    f"failed in prepare — rolled back, v{prev!r} still "
                    f"serving everywhere: {failure}")
            # COMMIT: close admission, drain, flip once, move pins
            drain_deadline = time.monotonic() + timeout_s
            with self._lock:
                route.gate.clear()
                try:
                    while route.inflight > 0:
                        # sparkdl: allow(wait-holding-lock): the per-deployment swap lock is held by design — it serializes cutovers; the wakers (predict resolution/failure paths) take only the serving lock, never the swap lock
                        if not self._cond.wait(timeout=_WAIT_POLL_S):
                            if time.monotonic() >= drain_deadline:
                                raise CutoverFailed(
                                    f"cluster cutover of {name!r} to "
                                    f"v{version!r}: {route.inflight} "
                                    "in-flight predict(s) did not "
                                    f"drain within {timeout_s:.0f}s — "
                                    f"aborted, v{prev!r} still active")
                    route.previous = prev
                    route.active = version
                    for wid in sorted(vr.deployed):
                        try:
                            self.router.serving_send(
                                wid, ("srv_pin", name, prev, False))
                        except (resilience.ServingReplicaLost,
                                resilience.WorkerDraining):
                            continue
                finally:
                    route.gate.set()
                self._cutovers += 1
        # the coordinator registry flips AFTER the cluster committed
        # (records serving_cutover, moves coordinator-side pins); a
        # no-op when _ensure is adopting a flip the registry already
        # made
        if registry.active_version(name) != version:
            registry.cutover(name, version)
        return prev

    def _rollback_prepare(self, name: str, version: str,
                          targets: Sequence[int]) -> None:
        """Undo a failed prepare: the new version unpins (evictable
        again) on every targeted worker; nothing was flipped, so the
        old version's pins and the active pointer are untouched."""
        with self._lock:
            for wid in targets:
                try:
                    self.router.serving_send(
                        wid, ("srv_pin", name, version, False))
                except (resilience.ServingReplicaLost,
                        resilience.WorkerDraining):
                    continue

    def rollback(self, name: str, registry: Any) -> str:
        """Cut back to the previously-active version, cluster-
        atomically (the same two-phase primitive aimed backwards)."""
        with self._lock:
            route = self._routes.get(name)
            target = route.previous if route is not None else None
        if target is None:
            raise ValueError(
                f"model {name!r} has no previous active version to "
                "roll back to")
        return self.cutover(name, registry, target)

    # -- introspection -------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """Per-deployment replica map — worker name -> versions
        deployed/resident, last-reported resident bytes, in-flight
        depth — surfaced through ``ModelServer.status()["cluster"]``
        and the exporter snapshot's ``serving`` section."""
        out: Dict[str, Any] = {}
        with self._lock:
            for name, route in sorted(self._routes.items()):
                wids: Set[int] = set()
                for vr in route.versions.values():
                    wids |= vr.deployed
                replicas = {}
                for wid in sorted(wids):
                    wname = self.router.serving_worker_name(wid)
                    replicas[wname] = {
                        "versions": sorted(
                            v for v, vr in route.versions.items()
                            if wid in vr.deployed),
                        "resident": sorted(
                            v for v, vr in route.versions.items()
                            if wid in vr.resident),
                        "resident_bytes": self._worker_bytes.get(wid, 0),
                        "inflight": self._wid_inflight.get(wid, 0),
                    }
                out[name] = {"active": route.active,
                             "inflight": route.inflight,
                             "replicas": replicas}
        return out

    def report_section(self) -> Dict[str, Any]:
        """The coordinator-side ``serving.router`` block of the merged
        run report: routing totals, the exactly-once failover ledger,
        and the final replica topology."""
        with self._lock:
            return {
                "predicts": self._predicts,
                "failovers": self._failovers,
                "moved_requests": list(self._moved),
                "cutovers": self._cutovers,
                "prepare_failures": self._prepare_failures,
                "deployments": {
                    name: {
                        "active": route.active,
                        "versions": {
                            v: {"deployed": sorted(vr.deployed),
                                "resident": sorted(vr.resident)}
                            for v, vr in sorted(route.versions.items())
                        },
                    }
                    for name, route in sorted(self._routes.items())},
            }


# =============================================================================
# Process-wide wiring
# =============================================================================

_mod_lock = threading.Lock()
_instance: Optional[ClusterServingRouter] = None


def maybe_cluster_serving() -> Optional[ClusterServingRouter]:
    """The process-wide serving router bound to the process-wide
    :func:`~sparkdl_tpu.cluster.router.maybe_router` instance (rebuilt
    whenever the underlying router was rebuilt), or None when no
    cluster is armed. Callers (``ModelServer._cluster``) check the
    knobs BEFORE importing this module."""
    from sparkdl_tpu.cluster import router as cluster_router

    router = cluster_router.maybe_router()
    if router is None:
        return None
    global _instance
    with _mod_lock:
        inst = _instance
        if inst is None or inst.router is not router or inst.closed:
            inst = ClusterServingRouter(router)
            _instance = inst
        return inst


def exporter_status() -> Optional[Dict[str, Any]]:
    """The live replica map for ``SnapshotExporter`` (None when no
    serving router is active — the exporter omits the section)."""
    inst = _instance
    if inst is None or inst.closed or inst.router.closed:
        return None
    return inst.status()


def reset() -> None:
    """Drop the process-wide instance (tests; the underlying router's
    own shutdown already failed any in-flight exchanges)."""
    global _instance
    with _mod_lock:
        _instance = None
