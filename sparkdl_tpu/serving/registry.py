"""Versioned model deployment registry: deploy beside, shadow, cut over.

The registry is the serving plane's source of truth for WHICH model a
request runs (docs/SERVING.md "Deployment lifecycle"):

- ``deploy(name, version, ...)`` registers a version next to the ones
  already serving — the first version of a name activates itself,
  later ones deploy dark until cut over.
- ``shadow(name, version, fraction)`` mirrors a deterministic fraction
  of traffic to a candidate version. Responses ALWAYS come from the
  active version; the shadow leg's outputs and latency are compared and
  recorded (``sparkdl.serving.shadow_divergence`` + the
  ``serving_shadow_compared`` health event) by the ModelServer.
- ``cutover(name, version)`` atomically flips the active pointer.
  Requests resolve their version at admission under the registry lock,
  so every in-flight request completes on the version it resolved —
  zero dropped, zero double-served. ``rollback(name)`` is the SAME
  primitive aimed at the previous active version.

Quarantine/hedging/retry semantics survive a swap for free: a request
holds a direct reference to its resolved
:class:`~sparkdl_tpu.core.model_function.ModelFunction`, and every
device entry stays behind ``executor.execute`` — the swap moves a
pointer, never a queue.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from sparkdl_tpu.core import health, telemetry


def _serving_warmup_armed() -> bool:
    try:
        from sparkdl_tpu.engine.dataframe import EngineConfig
    except Exception:
        return False
    return bool(getattr(EngineConfig, "serving_warmup", False))


def _serving_cluster_armed() -> bool:
    try:
        from sparkdl_tpu.engine.dataframe import EngineConfig
    except Exception:
        return False
    return bool(getattr(EngineConfig, "serving_cluster", False))


def warmup_deployment(model: Any, name: str, version: str,
                      batch_size: int) -> None:
    """AOT-compile ``model``'s FULL bucket ladder — one dummy batch per
    rung, through the ``executor.execute`` choke point, so each rung's
    exact padded variant (precision cast, donation, planner bucket)
    compiles and its fused-kernel shootouts settle BEFORE the
    deployment takes traffic (docs/PERF.md "Fused kernels & AOT
    warmup").

    Runs inside the deployment's loader — i.e. under the residency
    single-flight on EVERY cold load: first deploy, reload after
    eviction, and a cluster replica's ``srv_prepare`` (which therefore
    acks prepared only after the ladder is warm; a warmup failure nacks
    and rolls the cutover back). No-op unless
    ``EngineConfig.serving_warmup``; models without a static input spec
    (dict/dynamic specs) are skipped best-effort — their shapes aren't
    knowable ahead of the first request."""
    if not _serving_warmup_armed():
        return
    from sparkdl_tpu.core import batching, executor

    spec = getattr(model, "input_spec", None)
    elem = getattr(spec, "element_shape", None)
    if elem is None or any(d is None for d in elem):
        return
    try:
        eff_batch, multiple = model.bucket_params(int(batch_size))
    except Exception:  # sparkdl: allow(broad-retry): best-effort skip —
        # a model that cannot report bucket geometry stays lazy-compiled
        return
    planner = batching.default_planner(name, eff_batch, multiple)
    rungs = (planner.ladder() if planner is not None
             else batching._pow2_ladder(eff_batch, multiple, 8))
    t0 = time.monotonic()
    with telemetry.span(telemetry.SPAN_SERVING_WARMUP, model=name,
                        version=version, rungs=repr(tuple(rungs))):
        for rung in rungs:
            batch = np.zeros((int(rung),) + tuple(elem),
                             dtype=np.dtype(spec.dtype))
            executor.execute(model, batch, batch_size=int(batch_size),
                             coalesce=False, tenant=None)
    health.record(health.WARMUP_COMPLETED, model=name, version=version,
                  rungs=len(rungs), seconds=time.monotonic() - t0)


class Deployment:
    """One (name, version) record: how to obtain the model, and the
    per-model serving knobs the ModelServer reads at admission.

    ``loader`` is a zero-arg callable returning the ModelFunction; a
    concrete model deploys as a pre-loaded entry. Materialization goes
    through the residency manager when one is attached to the registry
    (budget/eviction/pinning apply), else it is memoized here — either
    way the FIRST load after registration or eviction runs under a
    ``sparkdl.model_load`` span.
    """

    def __init__(self, name: str, version: str,
                 loader: Callable[[], Any],
                 latency_target_ms: Optional[float],
                 batch_size: int,
                 residency: Optional[Any]) -> None:
        self.name = name
        self.version = version
        self.loader = loader
        self.latency_target_ms = latency_target_ms
        self.batch_size = int(batch_size)
        self._residency = residency
        self._load_lock = threading.Lock()
        self._model: Optional[Any] = None

    @property
    def latency_target_s(self) -> Optional[float]:
        if self.latency_target_ms is None:
            return None
        return self.latency_target_ms / 1e3

    def resident_bytes(self) -> int:
        """Bytes of this version's weights if currently materialized,
        else 0 — never triggers a load (the replica-map introspection
        path must stay cheap)."""
        if self._residency is not None:
            return self._residency.resident_bytes_for(self.name,
                                                      self.version)
        model = self._model
        if model is None:
            return 0
        return (int(model.weight_bytes())
                if hasattr(model, "weight_bytes") else 0)

    def model(self) -> Any:
        """The materialized ModelFunction (loading it on first use)."""
        if self._residency is not None:
            return self._residency.acquire(self.name, self.version)
        cached = self._model
        if cached is not None:
            return cached
        with self._load_lock:
            if self._model is None:
                t0 = time.monotonic()
                with telemetry.span(telemetry.SPAN_MODEL_LOAD,
                                    model=self.name,
                                    version=self.version):
                    self._model = self.loader()
                health.record(health.SERVING_COLD_START, model=self.name,
                              version=self.version,
                              seconds=time.monotonic() - t0)
            return self._model

    def __repr__(self) -> str:
        return f"Deployment({self.name!r}, version={self.version!r})"


class _Entry:
    """Per-model-name registry slot; every field is guarded by the
    owning registry's lock."""

    def __init__(self) -> None:
        self.versions: Dict[str, Deployment] = {}
        self.active: Optional[str] = None
        self.previous: Optional[str] = None  # rollback target
        self.shadow_version: Optional[str] = None
        self.shadow_fraction = 0.0
        self.shadow_acc = 0.0  # deterministic fraction accumulator


class ModelRegistry:
    """Thread-safe versioned deployments (one instance per serving
    plane; :func:`default_registry` is the process-wide one the ml/udf
    layers resolve string model names through)."""

    def __init__(self, residency: Optional[Any] = None, *,
                 defer_warmup: bool = False) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self._residency = residency
        # Cluster replicas set this: their boot config clears
        # serving_cluster (a worker is not a coordinator), so without
        # it the deploy fan would eagerly materialize EVERY version on
        # EVERY replica — warmup must wait for the replica's own cold
        # load (first routed predict or srv_prepare).
        self._defer_warmup = bool(defer_warmup)

    # -- deployment lifecycle ------------------------------------------------

    def deploy(self, name: str, version: str, model: Any = None, *,
               loader: Optional[Callable[[], Any]] = None,
               latency_target_ms: Optional[float] = None,
               batch_size: int = 64,
               activate: Optional[bool] = None) -> Deployment:
        """Register ``version`` of ``name``. Exactly one of ``model`` /
        ``loader`` must be given. The first version of a name activates
        itself; later versions deploy dark unless ``activate=True``
        (which is a :meth:`cutover`). Deploy-time side effects: the
        per-model latency metric is declared, and the version is
        registered with the residency manager (pinned iff active)."""
        if (model is None) == (loader is None):
            raise ValueError("deploy() takes exactly one of model=/loader=")
        if loader is None:
            def loader(m=model):
                return m
        # Every materialization path — Deployment.model(), the residency
        # manager's single-flight acquire (incl. post-eviction reloads),
        # and a cluster replica's srv_prepare — funnels through the
        # loader, so wrapping it HERE is what makes warmup cover all of
        # them. warmup_deployment itself no-ops when the knob is off.
        # The marker keeps the wrap single-layer: the cluster
        # coordinator ships the WRAPPED loader (cloudpickle preserves
        # function attributes) and replicas re-deploy it through this
        # same method — without the guard every replica cold load would
        # pay (and health-record) the ladder twice.
        raw_loader = loader

        if getattr(raw_loader, "_sparkdl_warmup_wrap", False):
            loader = raw_loader
        else:
            def loader(name=name, version=version,
                       batch_size=batch_size, _load=raw_loader):
                m = _load()
                warmup_deployment(m, name, version, batch_size)
                return m

            loader._sparkdl_warmup_wrap = True

        if latency_target_ms is not None and latency_target_ms <= 0:
            raise ValueError(
                f"latency_target_ms must be > 0 (or None), got "
                f"{latency_target_ms!r}")
        dep = Deployment(name, version, loader, latency_target_ms,
                         batch_size, self._residency)
        telemetry.declare_metric(telemetry.serving_request_metric(name),
                                 "histogram")
        with self._lock:
            entry = self._entries.setdefault(name, _Entry())
            if version in entry.versions:
                raise ValueError(
                    f"model {name!r} version {version!r} already "
                    "deployed — versions are immutable; deploy a new "
                    "version and cut over")
            entry.versions[version] = dep
            first = entry.active is None
            if first:
                entry.active = version
        if self._residency is not None:
            self._residency.register(name, version, loader, pinned=first)
        if activate and not first:
            self.cutover(name, version)
        # Eagerly materialize (and therefore warm) at deploy time so the
        # FIRST request pays zero compile — except on a cluster-serving
        # coordinator, where replicas materialize worker-side during
        # srv_prepare and a coordinator-local copy would be dead weight.
        if _serving_warmup_armed() and not _serving_cluster_armed() \
                and not self._defer_warmup:
            dep.model()
        return dep

    def shadow(self, name: str, version: Optional[str],
               fraction: float = 1.0) -> None:
        """Mirror ``fraction`` of ``name``'s traffic to ``version``
        (``None`` clears shadowing). Deterministic: an accumulator takes
        every ceil(1/fraction)-th request, so tests and replay runs see
        the same shadow set — no RNG."""
        if version is not None and not 0.0 < fraction <= 1.0:
            raise ValueError(
                f"shadow fraction must be in (0, 1], got {fraction!r}")
        with self._lock:
            entry = self._require_locked(name)
            if version is None:
                entry.shadow_version = None
                entry.shadow_fraction = 0.0
                entry.shadow_acc = 0.0
                return
            if version not in entry.versions:
                raise KeyError(
                    f"model {name!r} has no version {version!r} to "
                    f"shadow; deployed: {sorted(entry.versions)}")
            if version == entry.active:
                raise ValueError(
                    f"model {name!r} version {version!r} is the active "
                    "version — shadowing it onto itself is meaningless")
            entry.shadow_version = version
            entry.shadow_fraction = float(fraction)
            entry.shadow_acc = 0.0

    def cutover(self, name: str, version: str) -> str:
        """Atomically make ``version`` the active version of ``name``;
        returns the previous active version. In-flight requests finish
        on the version they resolved at admission (no request is
        dropped or served twice); the residency pin moves with the
        active pointer. A shadow pointing at the new active clears."""
        with self._lock:
            entry = self._require_locked(name)
            if version not in entry.versions:
                raise KeyError(
                    f"model {name!r} has no version {version!r}; "
                    f"deployed: {sorted(entry.versions)}")
            prev = entry.active
            if version == prev:
                return prev
            entry.previous = prev
            entry.active = version
            if entry.shadow_version == version:
                entry.shadow_version = None
                entry.shadow_fraction = 0.0
                entry.shadow_acc = 0.0
        if self._residency is not None:
            # pin BEFORE unpin: the new active must never be evictable,
            # even for the instant between the two calls
            self._residency.pin(name, version, pinned=True)
            if prev is not None:
                self._residency.pin(name, prev, pinned=False)
        health.record(health.SERVING_CUTOVER, model=name,
                      previous=prev, to=version)
        return prev

    def rollback(self, name: str) -> str:
        """Cut back over to the previous active version — the SAME
        atomic primitive as :meth:`cutover`, aimed backwards."""
        with self._lock:
            entry = self._require_locked(name)
            target = entry.previous
        if target is None:
            raise ValueError(
                f"model {name!r} has no previous active version to "
                "roll back to")
        return self.cutover(name, target)

    # -- request-path resolution ---------------------------------------------

    def resolve(self, name: str
                ) -> Tuple[Deployment, Optional[Deployment]]:
        """The admission-time snapshot for ONE request: ``(active,
        shadow)`` where ``shadow`` is the deployment to mirror THIS
        request to (``None`` for the complement of the shadow
        fraction). Atomic under the registry lock — a concurrent
        cutover happens entirely before or entirely after."""
        with self._lock:
            entry = self._require_locked(name)
            active = entry.versions[entry.active]
            shadow = None
            if entry.shadow_version is not None:
                entry.shadow_acc += entry.shadow_fraction
                if entry.shadow_acc >= 1.0 - 1e-9:
                    entry.shadow_acc -= 1.0
                    shadow = entry.versions[entry.shadow_version]
            return active, shadow

    def model(self, name: str) -> Any:
        """The ACTIVE version's materialized ModelFunction — the hook
        the ml/udf layers use to resolve a string ``modelFunction``
        param through the serving plane (hot-swap applies to batch
        transformers too: each transform call re-resolves)."""
        active, _ = self.resolve(name)
        return active.model()

    def deployment(self, name: str,
                   version: Optional[str] = None) -> Deployment:
        """The :class:`Deployment` record for (name, version) — the
        ACTIVE version when ``version`` is None — WITHOUT
        :meth:`resolve`'s shadow-accumulator side effect. The cluster
        serving router resolves versions itself (shadow mirroring is a
        single-process feature), and admission checks must not consume
        shadow slots."""
        with self._lock:
            entry = self._require_locked(name)
            v = entry.active if version is None else version
            if v not in entry.versions:
                raise KeyError(
                    f"model {name!r} has no version {v!r}; deployed: "
                    f"{sorted(entry.versions)}")
            return entry.versions[v]

    def deployments(self, name: str) -> Dict[str, Deployment]:
        """Every deployed version of ``name`` (a snapshot copy) — the
        cluster serving router's replica fan-out source."""
        with self._lock:
            return dict(self._require_locked(name).versions)

    # -- introspection -------------------------------------------------------

    def active_version(self, name: str) -> str:
        with self._lock:
            return self._require_locked(name).active

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def targets(self) -> Dict[str, float]:
        """``{model name: active version's p99 target in seconds}`` for
        every model with a latency target — the input
        ``slo.default_serving_rules`` wants."""
        out: Dict[str, float] = {}
        with self._lock:
            for name, entry in self._entries.items():
                dep = entry.versions.get(entry.active)
                if dep is not None and dep.latency_target_s is not None:
                    out[name] = dep.latency_target_s
        return out

    def status(self, name: str) -> Dict[str, Any]:
        with self._lock:
            entry = self._require_locked(name)
            return {
                "active": entry.active,
                "previous": entry.previous,
                "versions": sorted(entry.versions),
                "shadow_version": entry.shadow_version,
                "shadow_fraction": entry.shadow_fraction,
            }

    def _require_locked(self, name: str) -> _Entry:
        try:
            return self._entries[name]
        except KeyError:
            raise KeyError(
                f"no model named {name!r} deployed; deployed models: "
                f"{sorted(self._entries)}") from None


_default_registry = ModelRegistry()


def default_registry() -> ModelRegistry:
    """The process-wide registry (the ml/udf string-name resolution
    target). Serving stacks that want isolation construct their own."""
    return _default_registry
