"""Row-level online request API over the executor choke point.

``ModelServer.predict(model, rows, deadline_ms, priority)`` serves a
single row or a small batch — NOT an engine partition — and enters the
device exclusively via :func:`sparkdl_tpu.core.executor.execute` (the
choke-point lint covers this package), so coalescing, priority lanes,
admission control, the per-model circuit breaker, hedge dedup and
deadline propagation all apply unchanged to online traffic.

What the serving layer ADDS on top of the executor (docs/SERVING.md):

- **SLO-aware admission.** Each deployment can carry a p99 latency
  target; before a request is admitted, the windowed queue-wait p99
  from the live telemetry plane is compared against the target's queue
  budget. Over budget: ``admission="shed"`` (default) rejects with
  :class:`ServingOverloaded` and a ``serving_shed`` health event —
  sub-millisecond, no device time wasted on a request that would miss
  its SLO anyway; ``admission="block"`` admits and lets the executor's
  backpressure + the request deadline bound the wait.
- **Target-driven coalesce window.** The same latency target caps how
  long a request may wait for coalescing siblings (a fraction of the
  target, passed per call via ``executor.execute``'s
  ``coalesce_window_ms`` hook) — tight-SLO models stop batching before
  loose-SLO models do.
- **Versioning.** The model name resolves through the
  :class:`~sparkdl_tpu.serving.registry.ModelRegistry` at admission:
  responses always come from the active version, a configured fraction
  mirrors to the shadow version (compared + recorded, never answering),
  and cutover/rollback are atomic pointer flips.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from sparkdl_tpu.core import executor, health, resilience, telemetry
from sparkdl_tpu.serving.registry import ModelRegistry, default_registry

# Fraction of a model's latency target spent on the coalesce window
# (the rest belongs to queue wait + the launch itself), and the ceiling
# matching the executor's own adaptive bound (_WINDOW_MAX_S).
_TARGET_WINDOW_FRACTION = 0.1
_TARGET_WINDOW_MAX_MS = 20.0
# Fraction of the latency target the QUEUE WAIT may consume before
# admission starts shedding: with queue-wait p99 above this, a new
# request would spend its whole budget waiting in line.
_QUEUE_WAIT_BUDGET_FRACTION = 0.5


class ServingOverloaded(RuntimeError):
    """SLO-aware admission rejected this request: the windowed
    queue-wait p99 already exceeds the model's latency budget, so
    serving it would blow its target AND push every queued sibling
    further over. Clients treat this as retry-with-backoff."""


def stage_rows(dep: Any, rows: Any):
    """Coerce a request payload to ``(batch, single)``: a single row
    (rank = the model's element rank) gains a batch dim here and loses
    it again in the response. Module-level because BOTH serving planes
    stage identically — the in-process ModelServer and the cluster
    worker's replica (``serving/cluster.py``) — and the chaos proof
    compares their outputs bit-for-bit."""
    if isinstance(rows, dict):
        # multi-input models: the payload is already a named batch
        # tree; ModelFunction.stage_inputs (inside execute) owns it
        return rows, False
    batch = np.asarray(rows)
    spec = getattr(dep.model(), "input_spec", None)
    element_shape = getattr(spec, "element_shape", None)
    single = (element_shape is not None
              and batch.ndim == len(element_shape))
    if single:
        batch = batch[None]
    if batch.shape[0] == 0:
        raise ValueError("predict() needs at least one row")
    return batch, single


def target_window_ms(dep: Any) -> Optional[float]:
    """The deployment's coalesce-window cap derived from its latency
    target (None = the executor's adaptive window) — shared by both
    serving planes so a replicated deployment batches exactly like the
    single-process path."""
    if dep.latency_target_ms is None:
        return None
    return min(dep.latency_target_ms * _TARGET_WINDOW_FRACTION,
               _TARGET_WINDOW_MAX_MS)


class PredictResult:
    """One answered request: the output, WHICH version answered, and
    the end-to-end latency (shadow comparison time included when this
    request was mirrored — the overhead the bench leg reports)."""

    __slots__ = ("output", "model", "version", "latency_s", "shadowed")

    def __init__(self, output: Any, model: str, version: str,
                 latency_s: float, shadowed: bool) -> None:
        self.output = output
        self.model = model
        self.version = version
        self.latency_s = latency_s
        self.shadowed = shadowed

    def __repr__(self) -> str:
        return (f"PredictResult(model={self.model!r}, "
                f"version={self.version!r}, "
                f"latency_s={self.latency_s:.4f}, "
                f"shadowed={self.shadowed})")


class ModelServer:
    """The online front-end. One instance per serving plane; stateless
    between requests except the in-flight depth gauge."""

    def __init__(self, registry: Optional[ModelRegistry] = None, *,
                 admission: str = "shed",
                 slo_window_s: float = 10.0,
                 queue_wait_budget_frac: float =
                 _QUEUE_WAIT_BUDGET_FRACTION) -> None:
        if admission not in ("shed", "block"):
            raise ValueError(
                f"admission must be 'shed' or 'block', got {admission!r}")
        if slo_window_s <= 0:
            raise ValueError(
                f"slo_window_s must be > 0, got {slo_window_s!r}")
        if not 0.0 < queue_wait_budget_frac <= 1.0:
            raise ValueError(
                "queue_wait_budget_frac must be in (0, 1], got "
                f"{queue_wait_budget_frac!r}")
        self.registry = registry if registry is not None \
            else default_registry()
        self._admission = admission
        self._slo_window_s = slo_window_s
        self._queue_wait_budget_frac = queue_wait_budget_frac
        self._lock = threading.Lock()
        self._inflight = 0

    # -- the request path ----------------------------------------------------

    def predict(self, model: str, rows: Any, *,
                deadline_ms: Optional[float] = None,
                priority: str = executor.PRIORITY_INTERACTIVE,
                tenant: Optional[str] = None
                ) -> PredictResult:
        """Serve one row (rank = the model's element rank; the batch
        dim is added and squeezed back) or one small batch. Rides the
        interactive lane unless told otherwise; ``deadline_ms`` bounds
        queue wait, backpressure blocking and drain (the executor drops
        an expired request unlaunched). ``tenant`` is the fair-queueing
        tag: requests from different tenants share the executor under
        deficit-round-robin, and non-default tenants get their own
        queue-wait series + shed attribution (None resolves through the
        ambient ``executor.tenant_scope`` / EngineConfig default)."""
        t0 = time.monotonic()
        cluster = self._cluster()
        if cluster is not None:
            return self._predict_cluster(cluster, model, rows,
                                         deadline_ms=deadline_ms,
                                         priority=priority,
                                         tenant=tenant, t0=t0)
        active, shadow = self.registry.resolve(model)
        # shed BEFORE paying for staging / cold load
        self._admit(active, tenant=tenant)
        batch, single = self._stage_rows(active, rows)
        deadline = (resilience.Deadline(deadline_ms / 1e3)
                    if deadline_ms is not None else None)
        window_ms = self._window_ms(active)
        self._note_inflight(1)
        try:
            out = executor.execute(
                active.model(), batch, batch_size=active.batch_size,
                priority=priority, deadline=deadline,
                coalesce_window_ms=window_ms, tenant=tenant)
        finally:
            self._note_inflight(-1)
        shadowed = False
        if shadow is not None:
            active_s = time.monotonic() - t0
            # the request's span context rides into the shadow lane
            # explicitly: shadow work stays attributable to THIS request
            # even if the lane ever moves off the caller thread
            self._run_shadow(model, active, shadow, batch, out, active_s,
                             window_ms, ctx=telemetry.current_context(),
                             tenant=tenant)
            shadowed = True
        latency_s = time.monotonic() - t0
        if telemetry.active() is not None:
            telemetry.observe(telemetry.M_SERVING_REQUEST_S, latency_s)
            telemetry.observe(telemetry.serving_request_metric(model),
                              latency_s)
        if single:
            import jax

            out = jax.tree_util.tree_map(lambda a: np.asarray(a)[0], out)
        return PredictResult(out, model, active.version, latency_s,
                             shadowed)

    # -- the cluster serving plane -------------------------------------------

    @staticmethod
    def _cluster() -> Optional[Any]:
        """The cluster serving router iff the knobs arm it. Resolved
        through ``sys.modules`` so a process that never configured the
        engine — or left ``cluster_workers=0`` / ``serving_cluster``
        off — keeps the single-process request path byte-identical and
        NEVER imports ``serving/cluster.py``."""
        import sys

        eng = sys.modules.get("sparkdl_tpu.engine.dataframe")
        if eng is None:
            return None
        cfg = eng.EngineConfig
        if not (cfg.serving_cluster and cfg.cluster_workers):
            return None
        from sparkdl_tpu.serving import cluster as serving_cluster

        return serving_cluster.maybe_cluster_serving()

    def _predict_cluster(self, cluster: Any, model: str, rows: Any, *,
                         deadline_ms: Optional[float], priority: str,
                         tenant: Optional[str], t0: float
                         ) -> PredictResult:
        """Cluster-routed predict: version resolution, replica routing,
        failover re-admission and the cutover gate live in
        ``serving/cluster.py``; SLO-aware admission and the in-flight
        gauge stay here. Shadow mirroring is single-process-only (in
        cluster mode a candidate replicates dark and cuts over
        cluster-atomically instead). The latency observation carries
        the request's span context as an exemplar, so a failed-over
        request's trace lands in the tail exemplars — the report NAMES
        the requests a worker death touched."""
        active = self.registry.deployment(model)
        self._admit(active, tenant=tenant)
        ctx = telemetry.current_context()
        self._note_inflight(1)
        try:
            out, version = cluster.predict(
                model, self.registry, rows, deadline_ms=deadline_ms,
                priority=priority, tenant=tenant, ctx=ctx)
        finally:
            self._note_inflight(-1)
        latency_s = time.monotonic() - t0
        if telemetry.active() is not None:
            telemetry.observe(telemetry.M_SERVING_REQUEST_S, latency_s,
                              exemplar=ctx)
            telemetry.observe(telemetry.serving_request_metric(model),
                              latency_s, exemplar=ctx)
        return PredictResult(out, model, version, latency_s, False)

    def cutover(self, model: str, version: str) -> str:
        """Hot-swap ``model`` to ``version``; returns the previous
        active version. Single-process: the registry's atomic pointer
        flip. Cluster mode: the two-phase cluster-atomic cutover —
        every replica loads and acks the new version (prepare), then
        ONE router flip (commit), so no window exists where two callers
        get different versions; a failed prepare rolls back with the
        old version still serving everywhere."""
        cluster = self._cluster()
        if cluster is not None:
            return cluster.cutover(model, self.registry, version)
        return self.registry.cutover(model, version)

    def rollback(self, model: str) -> str:
        """Cut back to the previous active version — the same primitive
        as :meth:`cutover`, aimed backwards, cluster-atomic when the
        cluster serving plane is armed."""
        cluster = self._cluster()
        if cluster is not None:
            return cluster.rollback(model, self.registry)
        return self.registry.rollback(model)

    # -- SLO-aware admission -------------------------------------------------

    def _admit(self, dep: Any, tenant: Optional[str] = None) -> None:
        target_s = dep.latency_target_s
        if target_s is None or self._admission != "shed":
            return  # block mode: executor backpressure + deadline bound it
        tel = telemetry.active()
        if tel is None:
            return  # no live metric plane, nothing to decide on
        snap = tel.metrics.window_snapshot(self._slo_window_s)
        hist = snap["histograms"].get(telemetry.M_QUEUE_WAIT_S)
        p99 = hist.get("p99") if hist else None
        budget_s = target_s * self._queue_wait_budget_frac
        if p99 is not None and p99 > budget_s:
            health.record(health.SERVING_SHED, model=dep.name,
                          version=dep.version, queue_wait_p99_s=p99,
                          budget_s=budget_s,
                          tenant=tenant or executor.current_tenant()
                          or executor.DEFAULT_TENANT)
            raise ServingOverloaded(
                f"model {dep.name!r}: windowed queue-wait p99 "
                f"{p99:.4f}s exceeds the {budget_s:.4f}s queue budget "
                f"of its {target_s:.3f}s latency target")

    def _window_ms(self, dep: Any) -> Optional[float]:
        return target_window_ms(dep)

    # -- shadow traffic ------------------------------------------------------

    def _run_shadow(self, name: str, active: Any, shadow: Any,
                    batch: Any, active_out: Any, active_s: float,
                    window_ms: Optional[float],
                    ctx: Optional[telemetry.SpanContext] = None,
                    tenant: Optional[str] = None) -> None:
        """Mirror ONE request to the shadow version: run it on the BULK
        lane (a candidate must never crowd live traffic), compare
        outputs element-wise, record divergence + both latencies. A
        shadow failure records ``serving_shadow_error`` and is
        swallowed — the client already has its answer from the active
        version. The shadow leg runs under its own
        ``sparkdl.serving_shadow`` span parented on the request context
        ``ctx``, and carries the request's ``tenant`` tag so
        candidate-version work burns the requesting tenant's
        fair-queueing quota, not another tenant's."""
        t0 = time.monotonic()
        try:
            with telemetry.span(telemetry.SPAN_SERVING_SHADOW,
                                parent=ctx, model=name,
                                shadow_version=shadow.version):
                shadow_out = executor.execute(
                    shadow.model(), batch, batch_size=shadow.batch_size,
                    priority=executor.PRIORITY_BULK,
                    coalesce_window_ms=window_ms, tenant=tenant)
        except Exception as e:  # noqa: BLE001 - recorded, never re-raised
            health.record(health.SERVING_SHADOW_ERROR, model=name,
                          active_version=active.version,
                          shadow_version=shadow.version,
                          error=type(e).__name__)
            return
        shadow_s = time.monotonic() - t0
        divergence = _max_divergence(active_out, shadow_out)
        if telemetry.active() is not None:
            telemetry.observe(telemetry.M_SERVING_SHADOW_DIVERGENCE,
                              divergence)
        health.record(health.SERVING_SHADOW_COMPARED, model=name,
                      active_version=active.version,
                      shadow_version=shadow.version,
                      divergence=divergence, active_s=active_s,
                      shadow_s=shadow_s)

    # -- plumbing ------------------------------------------------------------

    def _stage_rows(self, dep: Any, rows: Any):
        return stage_rows(dep, rows)

    def _note_inflight(self, delta: int) -> None:
        with self._lock:
            self._inflight += delta
            depth = self._inflight
        if telemetry.active() is not None:
            telemetry.gauge_set(telemetry.M_SERVING_QUEUE_DEPTH, depth)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            inflight = self._inflight
        out = {"inflight": inflight, "admission": self._admission,
               "models": self.registry.names()}
        cluster = self._cluster()
        if cluster is not None:
            # per-deployment replica map: worker -> versions deployed /
            # resident, last-reported resident bytes, in-flight depth
            out["cluster"] = cluster.status()
        return out


def _max_divergence(a: Any, b: Any) -> float:
    """max |active - shadow| across every output leaf (0.0 for
    bit-identical outputs; shape mismatch reports +inf — versions with
    different output schemas ARE divergent, not an error)."""
    import jax

    a_leaves = jax.tree_util.tree_leaves(a)
    b_leaves = jax.tree_util.tree_leaves(b)
    if len(a_leaves) != len(b_leaves):
        return float("inf")
    worst = 0.0
    for x, y in zip(a_leaves, b_leaves):
        x = np.asarray(x)
        y = np.asarray(y)
        if x.shape != y.shape:
            return float("inf")
        if x.size:
            worst = max(worst, float(
                np.max(np.abs(x.astype(np.float64)
                              - y.astype(np.float64)))))
    return worst
