"""sparkdl_tpu — Deep Learning Pipelines, rebuilt TPU-native.

A from-scratch framework with the capabilities of
``chubbyjiang/spark-deep-learning`` ("Deep Learning Pipelines for Apache
Spark", python package ``sparkdl``), built idiomatically on JAX/XLA for TPU:
Flax models resident in HBM, jit/pjit execution via PJRT, declarative
sharding over device meshes (ICI/DCN collectives from XLA, not NCCL), an
Arrow-columnar partitioned DataFrame engine, and Orbax checkpointing.

Public surface mirrors the reference's ``sparkdl/__init__.py`` ``__all__``
(SURVEY.md §2.1), with TPU-native payloads. Heavy submodules are imported
lazily on attribute access so that ``import sparkdl_tpu`` stays cheap.
"""

import logging as _logging

from sparkdl_tpu.version import __version__

# Library logging etiquette: a NullHandler on the package root so the
# framework never prints "No handlers could be found" noise, and apps
# that DON'T configure logging see no output changes. Every module
# logger uses ``logging.getLogger(__name__)``, so all framework records
# route under the ``sparkdl_tpu`` namespace (enforced by
# tests/test_logging.py) — one knob configures the whole library, and
# the telemetry scope's structured-logging adapter (core.telemetry)
# stamps run_id/trace_id onto exactly this namespace.
_logging.getLogger(__name__).addHandler(_logging.NullHandler())

# JAX persistent compilation cache (docs/PERF.md "Cross-partition
# coalescing": the bucket ladder can compile a handful of programs per
# model; a warm on-disk cache makes every process after the first
# compile-free). Opt-in via SPARKDL_COMPILE_CACHE_DIR so the default
# `import sparkdl_tpu` stays jax-import free and cheap.
COMPILE_CACHE_DIR_ENV = "SPARKDL_COMPILE_CACHE_DIR"


def _configure_compile_cache(cache_dir=None):
    """Point JAX's persistent compilation cache at ``cache_dir`` (default:
    ``$SPARKDL_COMPILE_CACHE_DIR``). Returns True when configured. The
    thresholds are zeroed so even the small bucket-ladder programs are
    cached; first-launch compiles are visible as ``sparkdl.compile``
    spans in the telemetry run report either way."""
    import os as _os

    cache_dir = (cache_dir if cache_dir is not None
                 else _os.environ.get(COMPILE_CACHE_DIR_ENV))
    if not cache_dir:
        return False
    try:
        import jax as _jax

        _jax.config.update("jax_compilation_cache_dir", cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # pragma: no cover - jax version drift
        _logging.getLogger(__name__).warning(
            "could not enable the persistent compilation cache at %r: %s",
            cache_dir, e)
        return False
    return True


_configure_compile_cache()

# Grown as subsystems land; every name here must resolve (tested).
_LAZY_EXPORTS = {
    # image layer
    "imageIO": ("sparkdl_tpu.image", "imageIO"),
    "imageSchema": ("sparkdl_tpu.image", "imageSchema"),
    "readImages": ("sparkdl_tpu.image", "readImages"),
    "readImagesWithCustomFn": ("sparkdl_tpu.image", "readImagesWithCustomFn"),
    # engine
    "DataFrame": ("sparkdl_tpu.engine", "DataFrame"),
    "sql": ("sparkdl_tpu.engine", "sql"),
    "table": ("sparkdl_tpu.engine", "table"),
    # ml pipeline surface (reference __all__ parity)
    "Pipeline": ("sparkdl_tpu.ml", "Pipeline"),
    "PipelineModel": ("sparkdl_tpu.ml", "PipelineModel"),
    "Transformer": ("sparkdl_tpu.ml", "Transformer"),
    "Estimator": ("sparkdl_tpu.ml", "Estimator"),
    "TFImageTransformer": ("sparkdl_tpu.ml", "TFImageTransformer"),
    "TFTransformer": ("sparkdl_tpu.ml", "TFTransformer"),
    "TPUImageTransformer": ("sparkdl_tpu.ml", "TPUImageTransformer"),
    "TPUTransformer": ("sparkdl_tpu.ml", "TPUTransformer"),
    "DeepImageFeaturizer": ("sparkdl_tpu.ml", "DeepImageFeaturizer"),
    "DeepImagePredictor": ("sparkdl_tpu.ml", "DeepImagePredictor"),
    "KerasImageFileTransformer": ("sparkdl_tpu.ml", "KerasImageFileTransformer"),
    "KerasImageFileEstimator": ("sparkdl_tpu.ml", "KerasImageFileEstimator"),
    "KerasTransformer": ("sparkdl_tpu.ml", "KerasTransformer"),
    # observability surface (docs/OBSERVABILITY.md)
    "Telemetry": ("sparkdl_tpu.core", "Telemetry"),
    "telemetry": ("sparkdl_tpu.core", "telemetry"),
    "HealthMonitor": ("sparkdl_tpu.core", "HealthMonitor"),
    "slo": ("sparkdl_tpu.core", "slo"),
    "SLORule": ("sparkdl_tpu.core", "SLORule"),
    "SLOWatchdog": ("sparkdl_tpu.core", "SLOWatchdog"),
    # training surface
    "Trainer": ("sparkdl_tpu.train", "Trainer"),
    "TPURunner": ("sparkdl_tpu.train", "TPURunner"),
    "CheckpointManager": ("sparkdl_tpu.train", "CheckpointManager"),
    # udf serving surface
    "registerKerasImageUDF": ("sparkdl_tpu.udf", "registerKerasImageUDF"),
    "registerImageUDF": ("sparkdl_tpu.udf", "registerImageUDF"),
    "registerTensorUDF": ("sparkdl_tpu.udf", "registerTensorUDF"),
    "registerUDF": ("sparkdl_tpu.udf", "registerUDF"),
    "udf_registry": ("sparkdl_tpu.udf", "udf_registry"),
}

__all__ = ["__version__"] + sorted(_LAZY_EXPORTS)


def __getattr__(name):
    try:
        module_name, attr = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(f"module 'sparkdl_tpu' has no attribute {name!r}") from None
    import importlib

    module = importlib.import_module(module_name)
    value = getattr(module, attr)
    globals()[name] = value
    return value
