"""Named UDF registry + registration helpers.

A registered UDF is an object with ``apply(df, input_col, output_col) ->
df`` — uniform for plain row functions and device model UDFs, so the
engine's ``selectExpr`` can invoke any of them by name (the reference's
``spark.sql("SELECT my_udf(image) ...")`` analog, SURVEY.md §3.4).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

import numpy as np


class ColumnUDF:
    """A named column operator: ``apply(df, input_cols, output_col)``.

    ``arity``: number of input columns the UDF consumes; model UDFs are
    unary (one image/tensor column), plain row functions take any arity.
    """

    def __init__(self, name: str, apply_fn: Callable, kind: str,
                 arity: Optional[int] = 1) -> None:
        self.name = name
        self._apply_fn = apply_fn
        self.kind = kind
        self.arity = arity

    def apply(self, df, input_cols, output_col: str):
        if isinstance(input_cols, str):
            input_cols = [input_cols]
        if self.arity is not None and len(input_cols) != self.arity:
            raise ValueError(
                f"UDF {self.name!r} takes {self.arity} argument(s), "
                f"got {len(input_cols)}")
        if self.arity == 1:
            return self._apply_fn(df, input_cols[0], output_col)
        return self._apply_fn(df, input_cols, output_col)

    def __repr__(self) -> str:
        return f"ColumnUDF({self.name!r}, kind={self.kind!r})"


class UDFRegistry:
    """Process-wide named UDFs (the SQL-function namespace analog)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._udfs: Dict[str, ColumnUDF] = {}

    def register(self, udf: ColumnUDF, replace: bool = True) -> ColumnUDF:
        with self._lock:
            if not replace and udf.name in self._udfs:
                raise ValueError(f"UDF {udf.name!r} already registered")
            self._udfs[udf.name] = udf
        return udf

    def get(self, name: str) -> ColumnUDF:
        with self._lock:
            try:
                return self._udfs[name]
            except KeyError:
                raise KeyError(
                    f"No UDF named {name!r}; registered: "
                    f"{sorted(self._udfs)}") from None

    def contains(self, name: str) -> bool:
        with self._lock:
            return name in self._udfs

    def unregister(self, name: str) -> None:
        with self._lock:
            self._udfs.pop(name, None)

    def names(self):
        with self._lock:
            return sorted(self._udfs)


udf_registry = UDFRegistry()


def registerUDF(name: str, fn: Callable, outputType=None, arity: int = 1,
                registry: Optional[UDFRegistry] = None) -> ColumnUDF:
    """Register a plain row function ``(*values) -> value`` under ``name``.

    ``arity``: how many columns the function consumes (``selectExpr``
    passes that many arguments).
    """

    def apply_fn(df, input_cols, output_col):
        if isinstance(input_cols, str):
            input_cols = [input_cols]
        return df.withColumn(output_col, fn, inputCols=list(input_cols),
                             outputType=outputType)

    return (registry or udf_registry).register(
        ColumnUDF(name, apply_fn, "row",
                  arity=None if arity is None else int(arity)))


def registerTensorUDF(name: str, modelFunction, batchSize: int = 64,
                      mesh=None,
                      registry: Optional[UDFRegistry] = None) -> ColumnUDF:
    """Register a ModelFunction over numeric columns under ``name``.

    ``modelFunction`` may also be a serving-registry deployment name
    (str): the UDF then resolves the ACTIVE version per transform call,
    so SQL-surface model calls follow hot-swaps and rollbacks.
    ``mesh``: optional jax.sharding.Mesh for multi-chip serving (falls back
    to the framework default mesh when None).
    """

    def apply_fn(df, input_col, output_col):
        from sparkdl_tpu.ml.tensor_transformer import TPUTransformer

        return TPUTransformer(inputCol=input_col, outputCol=output_col,
                              modelFunction=modelFunction,
                              batchSize=batchSize, mesh=mesh).transform(df)

    return (registry or udf_registry).register(
        ColumnUDF(name, apply_fn, "tensor_model"))


def registerImageUDF(name: str, modelFunction, batchSize: int = 64,
                     preprocessor: Optional[Callable] = None,
                     mesh=None,
                     registry: Optional[UDFRegistry] = None) -> ColumnUDF:
    """Register a ModelFunction over image-struct columns under ``name``.

    ``modelFunction`` may also be a serving-registry deployment name
    (str), resolved to the active version per transform call.
    ``preprocessor`` (optional): host-side ``HWC ndarray -> HWC ndarray``
    applied per image before staging — the analog of the reference's
    preprocessor graph piece composed in front of the model (§3.4).
    ``mesh``: optional jax.sharding.Mesh for multi-chip serving (falls back
    to the framework default mesh when None).
    """

    def apply_fn(df, input_col, output_col):
        from sparkdl_tpu.image import imageIO
        from sparkdl_tpu.ml.image_transformer import TPUImageTransformer

        frame = df
        model_input = input_col
        if preprocessor is not None:
            tmp = output_col + "__pre"

            def pre(struct):
                if struct is None:
                    return None
                arr = preprocessor(imageIO.imageStructToArray(struct))
                return imageIO.imageArrayToStruct(
                    np.asarray(arr), origin=struct.get("origin", ""))

            frame = df.withColumn(tmp, pre, inputCols=[input_col],
                                  outputType=imageIO.imageSchema)
            model_input = tmp
        out = TPUImageTransformer(
            inputCol=model_input, outputCol=output_col,
            modelFunction=modelFunction, outputMode="vector",
            batchSize=batchSize, mesh=mesh).transform(frame)
        if model_input != input_col:
            out = out.drop(model_input)
        return out

    return (registry or udf_registry).register(
        ColumnUDF(name, apply_fn, "image_model"))


def registerKerasImageUDF(udfName: str, kerasModelOrFile: Any,
                          preprocessor: Optional[Callable] = None,
                          batchSize: int = 64,
                          mesh=None,
                          registry: Optional[UDFRegistry] = None) -> ColumnUDF:
    """Keras model (object or .h5/.keras path) as a named image UDF.

    Parity: ``sparkdl.udf.keras_image_model.registerKerasImageUDF``. The
    model is ingested once by the generic layer-DAG walker and served as a
    jitted XLA program.
    """
    from sparkdl_tpu.models.keras_ingest import keras_to_model_function

    if isinstance(kerasModelOrFile, str):
        from sparkdl_tpu.models.convert import load_keras_file

        keras_model = load_keras_file(kerasModelOrFile)
    else:
        keras_model = kerasModelOrFile
    mf = keras_to_model_function(keras_model, name=udfName)
    # single-IO surface: an image UDF binds one image column to one output
    # column — reject multi-IO models HERE, not deep inside a transform
    if isinstance(mf.input_spec, dict) or len(keras_model.outputs) > 1:
        raise ValueError(
            f"registerKerasImageUDF binds one image column to one output; "
            f"model {udfName!r} has {len(keras_model.inputs)} inputs / "
            f"{len(keras_model.outputs)} outputs — serve multi-IO models "
            "via TPUTransformer inputMapping/outputMapping")
    return registerImageUDF(udfName, mf, batchSize=batchSize,
                            preprocessor=preprocessor, mesh=mesh,
                            registry=registry)
