"""Model-as-UDF registry (SQL-serving parity layer).

Parity: the reference's ``udf/keras_image_model.py`` +
``graph/tensorframes_udf.py`` (SURVEY.md §2.1, §3.4): a Keras model became
a named Spark SQL UDF executed by TensorFrames. Here a named UDF is a
column operator on the engine's DataFrame — either a plain row function or
a jitted ModelFunction applied batch-wise — invoked via
``DataFrame.selectExpr("my_udf(image) as preds")``.
"""

from sparkdl_tpu.udf.registry import (
    UDFRegistry,
    registerImageUDF,
    registerKerasImageUDF,
    registerTensorUDF,
    registerUDF,
    udf_registry,
)

__all__ = [
    "UDFRegistry",
    "registerImageUDF",
    "registerKerasImageUDF",
    "registerTensorUDF",
    "registerUDF",
    "udf_registry",
]
