"""The six one-off AST lints, migrated onto the shared framework.

These grew one per PR in ``tests/test_taxonomy_lint.py`` (ISSUEs 2–7),
each with its own tree walk and its own suppression spelling. Here they
are registered rules — one engine, one suppression syntax
(``# sparkdl: allow(<rule>): <why>``), one catalog (docs/ANALYSIS.md) —
and the test module shrinks to thin wrappers that invoke the analyzer.

- ``broad-retry`` — no blind broad-except retry loops bypassing
  ``core.resilience.classify`` (ISSUE 2).
- ``blocking-fetch-in-fit`` — no blocking device fetch inside
  ``Trainer.fit``'s step loop (ISSUE 3).
- ``span-names`` — every ``annotate()``/``span()`` name must be in
  ``core.telemetry.CANONICAL_SPAN_NAMES`` (ISSUE 4).
- ``executor-choke-point`` — the featurize route (ml/udf/engine/image)
  enters the device only via ``executor.execute`` (ISSUE 5).
- ``health-constants`` — every ``health.record(...)`` passes a
  ``health.<CONSTANT>`` declared in ``core/health.py`` (ISSUE 6).
- ``slo-metrics`` — every ``SLORule(metric=…)`` statically resolves to
  a declared metric (ISSUE 7).

Constant resolution goes through the LIVE ``core`` modules (telemetry /
profiling / health import nothing heavy), exactly as the original lints
did — a catalog addition is picked up without touching the analyzer.
"""

from __future__ import annotations

import ast
import pathlib
from typing import List, Optional, Tuple

from sparkdl_tpu.analysis.framework import (Finding, Rule, SourceFile,
                                            register)
from sparkdl_tpu.core import health as _health
from sparkdl_tpu.core import profiling as _profiling
from sparkdl_tpu.core import telemetry as _telemetry

# ---------------------------------------------------------------------------
# broad-retry (ISSUE 2)
# ---------------------------------------------------------------------------

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _consults_taxonomy_or_raises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Name) and node.id in ("classify",
                                                      "resilience"):
            return True
        if isinstance(node, ast.Attribute) and node.attr == "classify":
            return True
    return False


@register
class BroadRetryRule(Rule):
    id = "broad-retry"
    title = "broad except inside a loop without classify/re-raise"
    rationale = (
        "Inside a for/while loop, an `except:`/`except Exception` "
        "handler that neither re-raises nor consults "
        "core.resilience.classify is the blind-retry shape PR 1/2 "
        "removed — FATAL user errors would be silently replayed. "
        "Deliberate non-retry swallows carry a suppression "
        "justification instead.")

    def check(self, src: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        loop_depth = 0

        def visit(node: ast.AST) -> None:
            nonlocal loop_depth
            is_loop = isinstance(node, (ast.For, ast.While,
                                        ast.AsyncFor))
            if is_loop:
                loop_depth += 1
            if isinstance(node, (ast.Try, getattr(ast, "TryStar",
                                                  ast.Try))):
                for handler in node.handlers:
                    if (loop_depth > 0 and _is_broad(handler)
                            and not _consults_taxonomy_or_raises(
                                handler)):
                        findings.append(self.finding(
                            src, handler.lineno,
                            "broad except inside a loop without "
                            "re-raise or core.resilience.classify — "
                            "blind retry would replay FATAL errors"))
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_loop:
                loop_depth -= 1

        visit(src.tree)
        return findings


# ---------------------------------------------------------------------------
# blocking-fetch-in-fit (ISSUE 3)
# ---------------------------------------------------------------------------

_FETCH_NAMES = {"int", "float"}
_FETCH_ATTRS = {"asarray", "device_get", "block_until_ready"}


def blocking_fetches_in_fit(tree: ast.AST) -> List[int]:
    """Lines of blocking-fetch calls inside ``Trainer.fit``'s own loops
    (empty when the tree has no ``Trainer.fit``). Nested function
    DEFINITIONS are exempt — only their call sites block."""
    fit = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Trainer":
            for item in node.body:
                if (isinstance(item, ast.FunctionDef)
                        and item.name == "fit"):
                    fit = item
    if fit is None:
        return []

    loops: List[ast.AST] = []

    def find_loops(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue  # helper closures run at sync points, not here
            if isinstance(child, (ast.For, ast.While, ast.AsyncFor)):
                loops.append(child)
            find_loops(child)

    find_loops(fit)

    def walk_pruned(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from walk_pruned(child)

    violations = []
    for loop in loops:
        for node in walk_pruned(loop):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id in _FETCH_NAMES:
                violations.append(node.lineno)
            elif isinstance(f, ast.Attribute) and f.attr in _FETCH_ATTRS:
                violations.append(node.lineno)
    return sorted(set(violations))


@register
class BlockingFetchInFitRule(Rule):
    id = "blocking-fetch-in-fit"
    title = "blocking device fetch inside Trainer.fit's step loop"
    rationale = (
        "int()/float() on a device scalar, np.asarray, jax.device_get "
        "or block_until_ready inside the fit step loop re-serializes "
        "host staging with device compute — the exact regression the "
        "DevicePrefetcher removed. Fetches belong in the designated "
        "sync helpers, called only at sync points.")

    def check(self, src: SourceFile) -> List[Finding]:
        return [self.finding(
            src, line,
            "blocking device fetch inside Trainer.fit's step loop — "
            "move it into the sync helpers (sync/save_checkpoint) "
            "called only at sync points")
            for line in blocking_fetches_in_fit(src.tree)]


# ---------------------------------------------------------------------------
# span-names (ISSUE 4)
# ---------------------------------------------------------------------------

#: ``remote_span``/``record_remote`` carry span names ACROSS a process
#: boundary (decode-pool / cluster messages): a non-canonical name there
#: is unmergeable on the adopting side, so the lint covers them too —
#: the static half of the runtime rejection in ``Tracer.record_remote``
#: / ``adopt_remote_spans``.
_SPAN_CALL_NAMES = {"annotate", "span", "remote_span", "record_remote"}


def _resolve_span_name(arg: ast.expr) -> Optional[str]:
    """String value of a span-name argument, or None when dynamic."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    attr = None
    if isinstance(arg, ast.Attribute):   # profiling.STAGE_BATCH
        attr = arg.attr
    elif isinstance(arg, ast.Name):      # SPAN_RUN inside telemetry.py
        attr = arg.id
    if attr is not None:
        for mod in (_profiling, _telemetry):
            value = getattr(mod, attr, None)
            if isinstance(value, str):
                return value
    return None


def span_names_in(tree: ast.AST) -> List[Tuple[str, int]]:
    """(name, lineno) for every statically-resolvable
    ``annotate()``/``span()`` call."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        fname = (f.id if isinstance(f, ast.Name)
                 else f.attr if isinstance(f, ast.Attribute) else None)
        if fname not in _SPAN_CALL_NAMES:
            continue
        name = _resolve_span_name(node.args[0])
        if name is not None:
            out.append((name, node.lineno))
    return out


@register
class SpanNamesRule(Rule):
    id = "span-names"
    title = "annotate()/span()/remote_span() names must be canonical"
    rationale = (
        "A typo'd phase name silently forks a timer and a trace track "
        "instead of failing, and a non-canonical name shipped across a "
        "process boundary (remote_span/record_remote) is REJECTED by "
        "the adopting tracer — the span vanishes from the merged "
        "timeline. Every literal or module-constant name must be "
        "declared in core.telemetry.CANONICAL_SPAN_NAMES "
        "(docs/OBSERVABILITY.md is the human catalog); dynamic names "
        "are not checkable and are skipped.")

    def check(self, src: SourceFile) -> List[Finding]:
        catalog = _telemetry.CANONICAL_SPAN_NAMES
        return [self.finding(
            src, line,
            f"span/phase name {name!r} is not declared in "
            "core.telemetry.CANONICAL_SPAN_NAMES — add it to the "
            "catalog (and docs/OBSERVABILITY.md) or fix the typo")
            for name, line in span_names_in(src.tree)
            if name not in catalog]


# ---------------------------------------------------------------------------
# executor-choke-point (ISSUE 5)
# ---------------------------------------------------------------------------

_DEVICE_ENTRY_ATTRS = {"apply_batch", "jitted", "with_dtype"}
#: The featurize/serving route that MUST go through the executor. The
#: choke point itself (core/executor.py) and the model layer it wraps
#: (core/model_function.py) live outside these scopes by design; the
#: training path (train/) owns its own step programs and is exempt.
#: "serving" covers the online plane (sparkdl_tpu/serving/): row-level
#: requests enter the device ONLY via executor.execute, same as batch.
#: "cluster" covers the multi-process inference plane
#: (sparkdl_tpu/cluster/): a worker's op chain reaches the device via
#: its per-process executor — router/worker code never launches
#: directly.
CHOKE_SCOPES = ("ml", "udf", "engine", "image", "serving", "cluster")


def direct_device_entry_calls(tree: ast.AST) -> List[int]:
    """Lines of direct ``.apply_batch(...)`` / ``.jitted(...)`` /
    ``.with_dtype(...)`` calls. ``jitted`` is flagged with or without
    ``donate_batch=`` — both the donation decision and the launch route
    belong to the executor choke point."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _DEVICE_ENTRY_ATTRS:
            out.append(node.lineno)
    return sorted(out)


@register
class ExecutorChokePointRule(Rule):
    id = "executor-choke-point"
    title = "featurize route must enter the device via executor.execute"
    rationale = (
        "A transformer/UDF/engine op calling apply_batch or jitted "
        "directly silently regresses the featurize route to "
        "per-partition launches (docs/PERF.md 'Cross-partition "
        "coalescing'), invisible until the next bench round; a "
        "per-call-site with_dtype or jitted(donate_batch=...) forks the "
        "precision/donation decision away from "
        "EngineConfig.inference_precision / inference_donate_buffers "
        "(docs/PERF.md 'Launch shaping & precision'). Only the executor "
        "choke point and the model layer it wraps may touch those "
        "methods.")

    def check(self, src: SourceFile) -> List[Finding]:
        parts = set(pathlib.PurePath(src.rel).parts)
        if not parts & set(CHOKE_SCOPES):
            return []
        return [self.finding(
            src, line,
            "direct apply_batch/jitted/with_dtype call on the engine "
            "featurize route — device entry, precision, and donation "
            "must go through core.executor.execute and EngineConfig "
            "(the coalescing choke point)")
            for line in direct_device_entry_calls(src.tree)]


# ---------------------------------------------------------------------------
# health-constants (ISSUE 6)
# ---------------------------------------------------------------------------

#: Event-name constants declared in core/health.py: UPPERCASE module
#: attributes holding strings.
HEALTH_EVENT_CONSTANTS = frozenset(
    name for name in vars(_health)
    if name.isupper() and isinstance(getattr(_health, name), str))


def bad_health_record_calls(tree: ast.AST) -> List[Tuple[int, str]]:
    """(lineno, reason) for every ``health.record(...)`` call whose
    event argument is not a declared ``health.<CONSTANT>``."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        # the framework-wide convention: `health.record(...)` on the
        # imported module object (never `from ... import record`)
        if not (isinstance(f, ast.Attribute) and f.attr == "record"
                and isinstance(f.value, ast.Name)
                and f.value.id == "health"):
            continue
        if not node.args:
            out.append((node.lineno, "no event argument"))
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((node.lineno, f"bare string {arg.value!r}"))
            continue
        if not (isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id == "health"):
            out.append((node.lineno,
                        "event name is not a health.<CONSTANT> "
                        "reference"))
            continue
        if arg.attr not in HEALTH_EVENT_CONSTANTS:
            out.append((node.lineno,
                        f"health.{arg.attr} is not declared in "
                        "core/health.py"))
    return out


@register
class HealthConstantsRule(Rule):
    id = "health-constants"
    title = "health.record() must pass a declared health.<CONSTANT>"
    rationale = (
        "A bare-string or typo'd event name silently forks a counter "
        "outside the docs catalog, the chaos accounting and the "
        "sparkdl.health.* telemetry mirrors. Declare the event in "
        "core/health.py and reference the constant.")

    def check(self, src: SourceFile) -> List[Finding]:
        return [self.finding(
            src, line,
            f"health.record() event argument: {reason} — declare the "
            "event in core/health.py and reference it as "
            "health.<CONSTANT>")
            for line, reason in bad_health_record_calls(src.tree)]


# ---------------------------------------------------------------------------
# slo-metrics (ISSUE 7)
# ---------------------------------------------------------------------------

#: Declared health-event VALUES (the strings the mirrors are named
#: after).
_HEALTH_EVENT_VALUES = frozenset(
    getattr(_health, name) for name in HEALTH_EVENT_CONSTANTS)

_SLO_CONST_MODULES = ("telemetry", "health", "profiling", "slo")
_UNRESOLVED = object()


def _resolve_string_expr(node: ast.expr):
    """Static string value: literals, telemetry./health./profiling.
    module constants (bare names resolve too, for constants referenced
    inside their own module), and ``+`` concatenations of those.
    ``_UNRESOLVED`` for a module-constant reference that does not exist
    (a typo'd constant); None when genuinely dynamic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    attr = None
    flag_missing = False
    if isinstance(node, ast.Attribute):
        attr = node.attr
        flag_missing = (isinstance(node.value, ast.Name)
                        and node.value.id in _SLO_CONST_MODULES)
    elif isinstance(node, ast.Name):
        attr = node.id
    if attr is not None:
        for mod in (_telemetry, _health, _profiling):
            value = getattr(mod, attr, None)
            if isinstance(value, str):
                return value
        return _UNRESOLVED if flag_missing else None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _resolve_string_expr(node.left)
        right = _resolve_string_expr(node.right)
        if left is _UNRESOLVED or right is _UNRESOLVED:
            return _UNRESOLVED
        if left is not None and right is not None:
            return left + right
    return None


def bad_slo_rule_metrics(tree: ast.AST) -> List[Tuple[int, str]]:
    """(lineno, reason) for every ``SLORule(...)`` whose metric does
    not statically resolve to a declared metric name."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        fname = (f.id if isinstance(f, ast.Name)
                 else f.attr if isinstance(f, ast.Attribute) else None)
        if fname != "SLORule":
            continue
        metric_arg = None
        for kw in node.keywords:
            if kw.arg == "metric":
                metric_arg = kw.value
        if metric_arg is None and len(node.args) >= 2:
            metric_arg = node.args[1]
        if metric_arg is None:
            out.append((node.lineno, "no metric argument"))
            continue
        metric = _resolve_string_expr(metric_arg)
        if metric is _UNRESOLVED:
            out.append((node.lineno,
                        "metric references an undeclared module "
                        "constant"))
            continue
        if metric is None:
            continue  # dynamic: SLORule's runtime validation covers it
        if metric in _telemetry.CANONICAL_METRIC_NAMES:
            continue
        prefix = _telemetry.HEALTH_METRIC_PREFIX
        if (metric.startswith(prefix)
                and metric[len(prefix):] in _HEALTH_EVENT_VALUES):
            continue
        out.append((node.lineno, f"undeclared metric {metric!r}"))
    return out


#: Sections of a windowed snapshot / federation delta frame that are
#: keyed by metric name — a lookup into one with a typo'd name silently
#: returns None forever, exactly the failure mode slo-metrics exists to
#: catch (the federated fold made these lookups a public idiom:
#: autoscaler, exporter, and watchdog all read them).
_FRAME_SECTIONS = frozenset({"histograms", "counters", "gauges"})


def _declared_metric(name: str) -> bool:
    if name in _telemetry.CANONICAL_METRIC_NAMES:
        return True
    prefix = _telemetry.HEALTH_METRIC_PREFIX
    return (name.startswith(prefix)
            and name[len(prefix):] in _HEALTH_EVENT_VALUES)


def bad_frame_metric_keys(tree: ast.AST) -> List[Tuple[int, str]]:
    """(lineno, reason) for metric-name lookups into a windowed
    snapshot or federation delta-frame section —
    ``X["histograms"].get(<name>)`` and
    ``view.attribution(<metric>, ...)`` — whose name does not
    statically resolve to a declared metric."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        key_arg = None
        what = None
        if (f.attr == "get" and node.args
                and isinstance(f.value, ast.Subscript)
                and isinstance(f.value.slice, ast.Constant)
                and f.value.slice.value in _FRAME_SECTIONS):
            key_arg = node.args[0]
            what = f"[{f.value.slice.value!r}].get() metric key"
        elif f.attr == "attribution":
            key_arg = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "metric":
                    key_arg = kw.value
            what = "attribution() metric"
        if key_arg is None:
            continue
        name = _resolve_string_expr(key_arg)
        if name is _UNRESOLVED:
            out.append((node.lineno,
                        f"{what} references an undeclared module "
                        "constant"))
        elif name is not None and not _declared_metric(name):
            out.append((node.lineno,
                        f"{what}: undeclared metric {name!r}"))
    return out


@register
class SLOMetricsRule(Rule):
    id = "slo-metrics"
    title = "SLO rule metrics and frame keys must resolve to declared names"
    rationale = (
        "A typo'd metric watches nothing forever. SLORule's runtime "
        "validation catches dynamic cases; this rule catches literals "
        "and module-constant concatenations before any scope ever "
        "runs — including a typo'd MODULE CONSTANT, which would "
        "otherwise only surface at import time. The same discipline "
        "covers reads: a metric-name lookup into a windowed snapshot "
        "or federation delta frame (X['histograms'].get(name), "
        "view.attribution(metric, ...)) silently returns None on a "
        "typo, so those keys must resolve too.")

    def check(self, src: SourceFile) -> List[Finding]:
        found = [self.finding(
            src, line,
            f"SLO rule metric: {reason} — must be a "
            "CANONICAL_METRIC_NAMES entry or a sparkdl.health.<event> "
            "mirror of a core/health.py constant")
            for line, reason in bad_slo_rule_metrics(src.tree)]
        found.extend(self.finding(
            src, line,
            f"windowed-metrics lookup: {reason} — frame and snapshot "
            "sections are keyed by declared metric names")
            for line, reason in bad_frame_metric_keys(src.tree))
        return found


# ---------------------------------------------------------------------------
# atomic-write (ISSUE 11)
# ---------------------------------------------------------------------------

# Modules whose on-disk artifacts must survive kill -9: the durable
# journal, checkpoint manifests, baseline stores, telemetry reports.
_STATE_PERSISTING = {"durability.py", "checkpoint.py", "baseline.py",
                     "telemetry.py"}


def _expr_mentions_tmp(node: ast.AST) -> bool:
    """True when the path expression visibly routes through a temp name
    (``tmp`` in an identifier, attribute, or string literal) — the
    write-to-tmp half of the tmp + ``os.replace`` idiom."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "tmp" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "tmp" in sub.attr.lower():
            return True
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and "tmp" in sub.value.lower()):
            return True
    return False


@register
class AtomicWriteRule(Rule):
    id = "atomic-write"
    title = "Durable state must be written tmp-then-os.replace, never in place"
    rationale = (
        "A crash (or injected kill -9) midway through an in-place "
        "open(path, 'w') leaves a torn file that a restart then trusts. "
        "State-persisting modules must write to a tmp path, fsync, and "
        "publish with os.replace so readers only ever see complete "
        "artifacts.")

    def check(self, src: SourceFile) -> List[Finding]:
        if pathlib.PurePath(src.rel).name not in _STATE_PERSISTING:
            return []
        out: List[Finding] = []
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "open"
                    and len(node.args) >= 2):
                continue
            mode = node.args[1]
            if not (isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and "w" in mode.value):
                continue  # reads, appends, r+b: not in-place publishes
            if _expr_mentions_tmp(node.args[0]):
                continue
            out.append(self.finding(
                src, node.lineno,
                f"open(..., {mode.value!r}) writes durable state in "
                "place — a crash mid-write leaves a torn file; write to "
                "a tmp path and os.replace it over the destination"))
        return out


# ---------------------------------------------------------------------------
# tenant-tag (ISSUE 16)
# ---------------------------------------------------------------------------

#: The online plane: every serving request is SOME tenant's request.
#: Batch callers (ml/engine/...) inherit the ambient tenant_scope or
#: the EngineConfig default, so only serving/ is in scope — an online
#: request with no tag burns the shared "default" lane's quota, which
#: under deficit-round-robin lets one client starve the rest invisibly.
TENANT_SCOPES = ("serving",)


#: Serving-plane dispatch entry points the tenant tag must ride
#: through. ``execute`` is the single-process choke point;
#: ``submit_predict`` is the cluster serving router's wire dispatch
#: (serving/cluster.py) — a routed predict that drops the tag would
#: burn the default lane's quota on the WORKER, invisibly to the
#: coordinator's per-tenant series.
_TENANT_DISPATCH_NAMES = ("execute", "submit_predict")


def untagged_execute_calls(tree: ast.AST) -> List[int]:
    """Lines of ``executor.execute(...)`` / bare ``execute(...)`` /
    ``submit_predict(...)`` (bare or as a method) calls with neither a
    ``tenant=`` keyword nor a ``**kwargs`` spread (a spread may carry
    the tag; it is not statically checkable and is skipped, same
    stance as dynamic span names)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        is_dispatch = (
            (isinstance(f, ast.Attribute) and f.attr == "execute"
             and isinstance(f.value, ast.Name)
             and f.value.id == "executor")
            or (isinstance(f, ast.Name)
                and f.id in _TENANT_DISPATCH_NAMES)
            or (isinstance(f, ast.Attribute)
                and f.attr == "submit_predict"))
        if not is_dispatch:
            continue
        kw_names = {kw.arg for kw in node.keywords}
        if "tenant" in kw_names or None in kw_names:
            continue
        out.append(node.lineno)
    return sorted(out)


@register
class TenantTagRule(Rule):
    id = "tenant-tag"
    title = "serving-plane executor.execute() must carry a tenant tag"
    rationale = (
        "The executor's fair-queueing coalescer arbitrates by tenant "
        "(deficit-round-robin within each priority lane, "
        "docs/RESILIENCE.md 'Per-tenant fair queueing'): an online "
        "request submitted without `tenant=` lands in the shared "
        "default lane, where one client's flood starves every other "
        "untagged client with no per-tenant metric series to show it. "
        "The serving plane must thread its caller's tag — even "
        "`tenant=None` (resolve via the ambient scope) is an explicit, "
        "visible decision.")

    def check(self, src: SourceFile) -> List[Finding]:
        parts = set(pathlib.PurePath(src.rel).parts)
        if not parts & set(TENANT_SCOPES):
            return []
        return [self.finding(
            src, line,
            "serving-plane dispatch (executor.execute / "
            "submit_predict) without a tenant= argument — the request "
            "burns the shared default lane's fair-queueing quota; "
            "thread the caller's tenant tag (tenant=None to adopt the "
            "ambient tenant_scope)")
            for line in untagged_execute_calls(src.tree)]


# ---------------------------------------------------------------------------
# columnar-hot-path (ISSUE 18)
# ---------------------------------------------------------------------------

#: The data-plane modules where image/tensor columns flow decode →
#: device. param/ (loader plumbing) and serving/ (row-level requests)
#: are out of scope; their payloads are single rows by design.
COLUMNAR_SCOPES = ("image", "ml", "engine")

#: Per-row wrappers whose appearance inside a loop/comprehension means
#: an image or tensor column is being rebuilt one Python dict at a time.
_PER_ROW_IMAGE_WRAPPERS = ("imageArrayToStruct",)


def per_row_column_hops(tree: ast.AST) -> List[Tuple[int, str]]:
    """(lineno, reason) for per-row hops over columnar data: any
    ``.to_pylist()`` call, and any per-row image-struct construction
    (``imageArrayToStruct``) under a loop or comprehension."""
    out = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "to_pylist":
            out.add((node.lineno,
                     ".to_pylist() materializes the column as per-row "
                     "Python objects"))
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.ListComp, ast.SetComp,
                                 ast.DictComp, ast.GeneratorExp)):
            continue
        for sub in ast.walk(loop):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else None)
            if name in _PER_ROW_IMAGE_WRAPPERS:
                out.add((sub.lineno,
                         f"per-row {name}() in a loop rebuilds the "
                         "image column one Python dict at a time"))
    return sorted(out)


@register
class ColumnarHotPathRule(Rule):
    id = "columnar-hot-path"
    title = "image/tensor columns must stay columnar on the data plane"
    rationale = (
        "The ingest spine is zero-copy columnar end to end (docs/PERF.md "
        "'Columnar data plane'): decode-pool segments become Arrow "
        "binary children become device uint8 batches with no per-row "
        "Python hop. A `.to_pylist()` or loop of `imageArrayToStruct` on "
        "that route silently reintroduces the per-row dict "
        "materialization BENCH_r05 measured at two orders of magnitude "
        "of lost throughput — and no test fails, only the trajectory. "
        "String/URI/label columns and ragged-batch fallbacks are "
        "legitimate: suppress those sites with a reason.")

    def check(self, src: SourceFile) -> List[Finding]:
        parts = set(pathlib.PurePath(src.rel).parts)
        if not parts & set(COLUMNAR_SCOPES):
            return []
        return [self.finding(
            src, line,
            f"{reason} — on the columnar data plane "
            "(image/, ml/, engine/) use the zero-copy views "
            "(arrowImageBatch, list_column_to_numpy, to_numpy with "
            "validity masks) or suppress with the ragged/string-column "
            "justification")
            for line, reason in per_row_column_hops(src.tree)]


# ---------------------------------------------------------------------------
# kernel-gate (ISSUE 20)
# ---------------------------------------------------------------------------

#: The one module allowed to spell ``pallas_call`` / the raw kernel
#: builders — everything else goes through the autotune routes.
KERNELS_MODULE_PARTS = ("core", "kernels.py")


def _raw_kernel_entry_points() -> frozenset:
    """The LIVE raw-builder names from core/kernels.py (same live-module
    resolution as the span/health catalogs — a new kernel is covered
    without touching the analyzer)."""
    from sparkdl_tpu.core import kernels as _kernels
    return _kernels.RAW_KERNEL_ENTRY_POINTS


def _names_kernels_module(value: ast.AST) -> bool:
    return ((isinstance(value, ast.Name) and value.id == "kernels")
            or (isinstance(value, ast.Attribute)
                and value.attr == "kernels"))


def raw_kernel_calls(tree: ast.AST) -> List[Tuple[int, str]]:
    """(lineno, what) for every ``pallas_call`` launch and every raw
    ``core.kernels`` entry-point call in ``tree``."""
    raw_names = _raw_kernel_entry_points()
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            if f.id == "pallas_call":
                out.append((node.lineno, "a raw pallas_call launch"))
            elif f.id in raw_names:
                out.append((node.lineno,
                            f"raw kernel entry point {f.id}()"))
        elif isinstance(f, ast.Attribute):
            if f.attr == "pallas_call":
                out.append((node.lineno, "a raw pallas_call launch"))
            elif f.attr in raw_names and _names_kernels_module(f.value):
                out.append((node.lineno,
                            f"raw kernel entry point kernels.{f.attr}()"))
    return out


@register
class KernelGateRule(Rule):
    id = "kernel-gate"
    title = "Pallas kernels ship only through the autotune registry"
    rationale = (
        "core/kernels.py is the ONE home for pallas_call and the raw "
        "kernel builders, because its route_*/ensure_autotuned entry "
        "points are what enforce the accept-if-faster contract "
        "(docs/PERF.md 'Fused kernels & AOT warmup'): a kernel runs in "
        "production only with an adopted per-(kernel, family, shape, "
        "dtype) verdict — >= 5% faster than its XLA twin AND inside the "
        "numeric contract. A raw pallas_call elsewhere, or a direct "
        "call to a kernels.py builder, ships un-auditioned device code "
        "that can be slower or numerically off with no test failing.")

    def check(self, src: SourceFile) -> List[Finding]:
        if pathlib.PurePath(src.rel).parts[-2:] == KERNELS_MODULE_PARTS:
            return []
        return [self.finding(
            src, line,
            f"{what} outside core/kernels.py — fused kernels ship only "
            "through the autotune registry (kernels.route_* / "
            "ensure_autotuned), which is what guarantees a losing or "
            "numerically-off kernel never reaches production")
            for line, what in raw_kernel_calls(src.tree)]
