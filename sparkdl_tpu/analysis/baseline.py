"""Checked-in baseline for grandfathered findings.

A baseline entry is ``{rule, path, message}`` — deliberately
line-number-free, so unrelated edits shifting a file don't churn the
baseline. The engine treats a finding matching an entry as
*baselined* (reported separately, not a failure); entries that no
longer match anything are *stale* and surfaced so the file shrinks
monotonically.

Policy (ISSUE 8): the baseline exists for future emergencies — the
shipped file is EMPTY. A real hazard gets fixed; an intentional
pattern gets an inline ``# sparkdl: allow(<rule>): <why>`` with its
justification next to the code. Never silently baseline a real hazard.
"""

from __future__ import annotations

import json
import pathlib
import re
from typing import Any, Dict, Iterable, List, Set, Tuple

from sparkdl_tpu.analysis.framework import Finding

#: The checked-in baseline the CLI and the tier-1 gate read by default.
DEFAULT_BASELINE_PATH = pathlib.Path(__file__).with_name("baseline.json")

Key = Tuple[str, str, str]

#: Line references embedded in finding MESSAGES ("acquired line 12",
#: "at path.py:34", "from Cls.m:56") — normalized out of the matching
#: key, or an unrelated edit shifting the file would churn the baseline
#: the line-free key exists to prevent.
_LINE_REF_RE = re.compile(r"\b(line |:)\d+")


def _normalize(message: str) -> str:
    return _LINE_REF_RE.sub(r"\1N", message)


class Baseline:
    """A loaded set of grandfathered findings."""

    def __init__(self, entries: Iterable[Dict[str, Any]] = ()) -> None:
        self.entries: List[Dict[str, Any]] = [
            {"rule": e["rule"], "path": e["path"],
             "message": e["message"]} for e in entries]
        self._keys: Set[Key] = {self.key_of(e) for e in self.entries}

    @staticmethod
    def key_of(entry: Dict[str, Any]) -> Key:
        return (entry["rule"], entry["path"],
                _normalize(entry["message"]))

    def key(self, finding: Finding) -> Key:
        return (finding.rule, finding.path,
                _normalize(finding.message))

    def match(self, finding: Finding) -> bool:
        return self.key(finding) in self._keys

    def stale(self, matched: Set[Key]) -> List[Dict[str, Any]]:
        """Entries no fresh finding matched — candidates for deletion."""
        return [e for e in self.entries
                if self.key_of(e) not in matched]

    # -- persistence ---------------------------------------------------------

    @classmethod
    def load(cls, path: pathlib.Path) -> "Baseline":
        path = pathlib.Path(path)
        if not path.exists():
            return cls()
        data = json.loads(path.read_text() or "{}")
        return cls(data.get("entries", []))

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        return cls(f.as_dict() for f in findings)

    def save(self, path: pathlib.Path) -> None:
        entries = sorted(self.entries,
                         key=lambda e: (e["path"], e["rule"],
                                        e["message"]))
        pathlib.Path(path).write_text(json.dumps(
            {"comment": "grandfathered analyzer findings — see "
                        "docs/ANALYSIS.md; keep empty unless an "
                        "emergency demands otherwise",
             "entries": entries}, indent=2) + "\n")
