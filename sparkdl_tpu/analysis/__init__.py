"""Static-analysis subsystem: one rule framework, one suppression
syntax, one catalog (ISSUE 8; docs/ANALYSIS.md).

Importing this package registers the shipped rule packs — the
concurrency-discipline analyzer (:mod:`.concurrency`) and the six
migrated taxonomy lints (:mod:`.lints`) — into the framework registry.
Run it: ``python -m sparkdl_tpu.analysis [--rule ID] [--json]``; gate
it: ``tests/test_analysis.py`` runs the full catalog over
``sparkdl_tpu/`` in tier-1.
"""

from sparkdl_tpu.analysis.framework import (  # noqa: F401 - public API
    AnalysisResult,
    Finding,
    Rule,
    SourceFile,
    UnknownRuleError,
    all_rules,
    analyze,
    analyze_sources,
    collect_sources,
    register,
    rule,
)
from sparkdl_tpu.analysis import concurrency as _concurrency  # noqa: F401,E501 - registers the concurrency rule pack
from sparkdl_tpu.analysis import lints as _lints  # noqa: F401 - registers the migrated lints
from sparkdl_tpu.analysis.baseline import (  # noqa: F401 - public API
    DEFAULT_BASELINE_PATH,
    Baseline,
)
