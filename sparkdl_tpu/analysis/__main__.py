"""``python -m sparkdl_tpu.analysis`` entry point."""

import sys

from sparkdl_tpu.analysis.cli import main

sys.exit(main())
