"""Shared static-analysis framework (ISSUE 8 tentpole).

PR 6 and PR 7 review rounds each caught a hand-found locking hazard
(the lock-order-safe ``status()``, the half-open probe-slot wedge), and
the repo had grown six one-off AST lints spread through
``tests/test_taxonomy_lint.py`` — each with its own suppression
convention and its own walk of the tree. This package is the shared
engine they all run on, the same move the reference project made when
it leaned on Spark's analyzer-checked execution plans instead of
reviewer vigilance (PAPER.md §0): one rule registry, one
:class:`Finding` shape, one suppression syntax, one baseline format,
one CLI.

The pieces:

- :class:`Finding` — ``(rule, path, line, message)``; everything a rule
  reports, everything the CLI prints, everything a baseline stores.
- :class:`Rule` — the base every check subclasses. ``check(src)`` runs
  per file; ``finalize(sources)`` runs once with every parsed file for
  whole-program rules (the lock-order graph). Rules register into a
  process-wide catalog via :func:`register`.
- :class:`SourceFile` — path + source + lazily-parsed AST + the parsed
  suppression directives, shared by every rule (one parse per file per
  run).
- **Suppressions** — ``# sparkdl: allow(<rule>): <justification>`` on
  the finding's line. The justification is part of the grammar: a bare
  ``allow(<rule>)`` does not suppress (and is itself flagged by the
  built-in ``suppression-hygiene`` check), so every grandfathered
  hazard in the tree carries its reason next to it.
- :func:`analyze` / :func:`analyze_sources` — the engine: run rules,
  apply suppressions, apply the baseline, return an
  :class:`AnalysisResult`.

The CLI lives in :mod:`sparkdl_tpu.analysis.cli`
(``python -m sparkdl_tpu.analysis``); the rule packs in
:mod:`sparkdl_tpu.analysis.concurrency` (the flagship
concurrency-discipline analyzer) and :mod:`sparkdl_tpu.analysis.lints`
(the six migrated one-off lints). Human-readable catalog:
docs/ANALYSIS.md.
"""

from __future__ import annotations

import ast
import io
import pathlib
import re
import tokenize
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: The package this analyzer ships with (the default scan target).
PACKAGE_ROOT = pathlib.Path(__file__).resolve().parent.parent
REPO_ROOT = PACKAGE_ROOT.parent

#: The ONE suppression syntax (matched against COMMENT tokens only, so
#: docstrings and string literals describing the syntax never parse as
#: directives). The justification is required for the directive to
#: suppress anything (enforced by ``suppression-hygiene``).
SUPPRESS_RE = re.compile(
    r"^#\s*sparkdl:\s*allow\(\s*([A-Za-z0-9_\-\s,]+?)\s*\)"
    r"(?:\s*:\s*(?P<why>\S.*?))?\s*$")
#: Any comment STARTING with a ``sparkdl:`` directive (typo'd
#: directives are flagged, never silently ignored; a prose comment
#: merely mentioning the syntax mid-sentence is not a directive).
DIRECTIVE_RE = re.compile(r"^#[:!]?\s*sparkdl\s*:")

#: Rule ids reserved by the engine itself (not subclassable):
#: ``parse-error`` for unparseable files, ``suppression-hygiene`` for
#: malformed/unjustified/unknown-rule suppression directives.
PARSE_ERROR = "parse-error"
SUPPRESSION_HYGIENE = "suppression-hygiene"


@dataclass(frozen=True, order=True)
class Finding:
    """One analyzer hit: which rule, where, and why it matters."""

    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


@dataclass(frozen=True)
class Suppression:
    """One parsed ``# sparkdl: allow(...)`` directive.

    ``line`` is where the comment sits; ``target`` is the line it
    suppresses — the same line for a trailing comment, the NEXT line
    for a comment-only line (so multi-line statements stay
    suppressible without 120-column trailers).
    """

    line: int
    target: int
    rules: Tuple[str, ...]
    justification: Optional[str]

    def covers(self, rule: str) -> bool:
        """True when this directive suppresses ``rule`` findings on its
        target line — which requires BOTH the rule name and a
        justification."""
        return self.justification is not None and rule in self.rules


class SourceFile:
    """One file under analysis: source, lazily-parsed AST, suppressions.

    ``rel`` is the stable display/baseline path (repo-relative when the
    file lives under the repo, the given string otherwise). ``cache``
    is scratch space for cross-rule shared computations (the lock-model
    extraction memoizes here so three concurrency rules pay one walk).
    """

    def __init__(self, source: str, rel: str,
                 path: Optional[pathlib.Path] = None) -> None:
        self.source = source
        self.rel = rel
        self.path = path
        self.lines = source.splitlines()
        self.cache: Dict[str, Any] = {}
        self._tree: Optional[ast.AST] = None

    @classmethod
    def from_path(cls, path: pathlib.Path,
                  root: Optional[pathlib.Path] = None) -> "SourceFile":
        path = pathlib.Path(path).resolve()
        base = root if root is not None else REPO_ROOT
        try:
            rel = str(path.relative_to(base))
        except ValueError:
            rel = str(path)
        return cls(path.read_text(), rel, path=path)

    @classmethod
    def from_source(cls, source: str,
                    rel: str = "<memory>.py") -> "SourceFile":
        return cls(source, rel)

    @property
    def tree(self) -> ast.AST:
        """The parsed AST (raises ``SyntaxError``; the engine converts
        that into a ``parse-error`` finding)."""
        if self._tree is None:
            self._tree = ast.parse(self.source, filename=self.rel)
        return self._tree

    def comments(self) -> Dict[int, str]:
        """lineno → comment text (COMMENT tokens only — docstrings and
        string literals are never directives)."""
        out = self.cache.get("comments")
        if out is None:
            out = {}
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(self.source).readline):
                    if tok.type == tokenize.COMMENT:
                        out[tok.start[0]] = tok.string
            except (tokenize.TokenError, IndentationError, SyntaxError):
                pass  # unparseable files already get a parse-error
            self.cache["comments"] = out
        return out

    def suppressions(self) -> List[Suppression]:
        out = self.cache.get("suppressions")
        if out is None:
            out = []
            for lineno, comment in sorted(self.comments().items()):
                m = SUPPRESS_RE.match(comment)
                if m is None:
                    continue
                rules = tuple(r.strip() for r in m.group(1).split(",")
                              if r.strip())
                src_line = (self.lines[lineno - 1]
                            if lineno <= len(self.lines) else "")
                target = lineno
                if src_line.lstrip().startswith("#"):
                    # comment-only directive: target the next CODE line,
                    # skipping further comment-only and blank lines so
                    # stacked directives (and ordinary spacing) all land
                    # on the same statement
                    target = lineno + 1
                    while target <= len(self.lines):
                        stripped = self.lines[target - 1].strip()
                        if stripped and not stripped.startswith("#"):
                            break
                        target += 1
                out.append(Suppression(lineno, target, rules,
                                       m.group("why")))
            self.cache["suppressions"] = out
        return out

    def allowed(self, line: int, rule: str) -> Optional[Suppression]:
        """The justified suppression covering ``rule`` at ``line``,
        or None."""
        for sup in self.suppressions():
            if sup.target == line and sup.covers(rule):
                return sup
        return None


class Rule:
    """Base class for one registered check.

    Subclasses set ``id`` (kebab-case, the suppression/CLI handle),
    ``title`` (one line), ``rationale`` (why the rule exists — shown by
    ``--list-rules`` and mirrored in docs/ANALYSIS.md), and implement
    ``check`` (per file) and/or ``finalize`` (once, with every parsed
    file — for whole-program rules like the lock-order graph).
    """

    id: str = ""
    title: str = ""
    rationale: str = ""

    def check(self, src: SourceFile) -> List[Finding]:
        return []

    def finalize(self, sources: Sequence[SourceFile]) -> List[Finding]:
        return []

    def finding(self, src_or_path: Any, line: int,
                message: str) -> Finding:
        rel = (src_or_path.rel if isinstance(src_or_path, SourceFile)
               else str(src_or_path))
        return Finding(rel, line, self.id, message)


class UnknownRuleError(ValueError):
    """A ``--rule``/``rule_ids`` name that is not in the registry."""


_REGISTRY: Dict[str, Rule] = {}


def register(cls: type) -> type:
    """Class decorator: instantiate and register a :class:`Rule`."""
    instance = cls()
    if not instance.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if instance.id in (PARSE_ERROR, SUPPRESSION_HYGIENE):
        raise ValueError(f"rule id {instance.id!r} is reserved")
    if instance.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {instance.id!r}")
    _REGISTRY[instance.id] = instance
    return cls


def all_rules() -> Dict[str, Rule]:
    """The registered rule catalog (importing the package registers the
    shipped packs)."""
    return dict(_REGISTRY)


def rule(rule_id: str) -> Rule:
    try:
        return _REGISTRY[rule_id]
    except KeyError:
        raise UnknownRuleError(
            f"unknown rule {rule_id!r}; registered: "
            f"{sorted(_REGISTRY)}") from None


@dataclass
class AnalysisResult:
    """One analyzer run: what fired, what was suppressed, what the
    baseline absorbed."""

    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Tuple[Finding, str]] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[Dict[str, Any]] = field(default_factory=list)
    files: int = 0
    rule_ids: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings

    def as_dict(self) -> Dict[str, Any]:
        """The ``--json`` schema (tests/test_analysis.py pins it)."""
        return {
            "version": 1,
            "rules": list(self.rule_ids),
            "files": self.files,
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [
                {**f.as_dict(), "justification": why}
                for f, why in self.suppressed],
            "counts": {
                "findings": len(self.findings),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
            },
            "stale_baseline": list(self.stale_baseline),
        }


def collect_sources(paths: Iterable[Any]) -> List[SourceFile]:
    """Every ``.py`` file under ``paths`` (files or directories),
    sorted, parsed lazily."""
    files: List[pathlib.Path] = []
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        else:
            files.append(p)
    return [SourceFile.from_path(p) for p in files]


def _hygiene_findings(src: SourceFile,
                      known: Iterable[str]) -> List[Finding]:
    """Malformed / unjustified / unknown-rule suppression directives.
    These findings are not themselves suppressible — they are trivial
    to fix and exist to keep every suppression in the tree justified."""
    known = set(known)
    out: List[Finding] = []
    seen_lines = set()
    for sup in src.suppressions():
        seen_lines.add(sup.line)
        if sup.justification is None:
            out.append(Finding(
                src.rel, sup.line, SUPPRESSION_HYGIENE,
                f"suppression for {', '.join(sup.rules)} has no "
                "justification — write "
                "'# sparkdl: allow(<rule>): <why this is safe>'"))
        for r in sup.rules:
            if r not in known:
                out.append(Finding(
                    src.rel, sup.line, SUPPRESSION_HYGIENE,
                    f"suppression names unknown rule {r!r} (see "
                    "--list-rules); it suppresses nothing"))
    for lineno, comment in sorted(src.comments().items()):
        if lineno in seen_lines:
            continue
        if DIRECTIVE_RE.match(comment) \
                and SUPPRESS_RE.match(comment) is None:
            out.append(Finding(
                src.rel, lineno, SUPPRESSION_HYGIENE,
                "unrecognized '# sparkdl:' directive — the only "
                "supported form is '# sparkdl: allow(<rule>): <why>'"))
    return out


def analyze_sources(sources: Sequence[SourceFile],
                    rule_ids: Optional[Sequence[str]] = None,
                    baseline: Any = None) -> AnalysisResult:
    """Run the analyzer over already-built sources (the engine under
    :func:`analyze`; self-tests seed violations through here)."""
    if rule_ids is None:
        rules = list(_REGISTRY.values())
        run_hygiene = True
    else:
        rules = [rule(r) for r in rule_ids if r != SUPPRESSION_HYGIENE]
        run_hygiene = SUPPRESSION_HYGIENE in rule_ids
    raw: List[Finding] = []
    parsed: List[SourceFile] = []
    by_rel: Dict[str, SourceFile] = {}
    for src in sources:
        by_rel[src.rel] = src
        try:
            src.tree
        except SyntaxError as e:
            raw.append(Finding(src.rel, e.lineno or 1, PARSE_ERROR,
                               f"file does not parse: {e.msg}"))
            continue
        parsed.append(src)
        for r in rules:
            raw.extend(r.check(src))
        if run_hygiene:
            raw.extend(_hygiene_findings(src, _REGISTRY))
    for r in rules:
        raw.extend(r.finalize(parsed))

    result = AnalysisResult(files=len(sources),
                            rule_ids=[r.id for r in rules]
                            + ([SUPPRESSION_HYGIENE] if run_hygiene
                               else []))
    matched_baseline = set()
    for f in sorted(set(raw)):
        src = by_rel.get(f.path)
        if f.rule in (PARSE_ERROR, SUPPRESSION_HYGIENE):
            # neither suppressible nor baselineable: both are trivial
            # to fix, and grandfathering an unjustified suppression
            # would defeat the justification requirement entirely
            result.findings.append(f)
            continue
        if src is not None:
            sup = src.allowed(f.line, f.rule)
            if sup is not None:
                result.suppressed.append((f, sup.justification or ""))
                continue
        if baseline is not None and baseline.match(f):
            matched_baseline.add(baseline.key(f))
            result.baselined.append(f)
            continue
        result.findings.append(f)
    if baseline is not None:
        result.stale_baseline = baseline.stale(matched_baseline)
    return result


def analyze(paths: Optional[Iterable[Any]] = None,
            rule_ids: Optional[Sequence[str]] = None,
            baseline: Any = None) -> AnalysisResult:
    """Analyze ``paths`` (default: the ``sparkdl_tpu`` package) with the
    selected rules (default: all registered)."""
    if paths is None:
        paths = [PACKAGE_ROOT]
    return analyze_sources(collect_sources(paths), rule_ids=rule_ids,
                           baseline=baseline)
