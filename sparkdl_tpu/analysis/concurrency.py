"""Concurrency-discipline rules (ISSUE 8 flagship rule pack).

The codebase is genuinely multi-threaded — coalescer threads
(``core/executor.py``), the snapshot-exporter daemon
(``core/telemetry.py``), the prefetcher producer (``core/pipeline.py``),
the supervisor pool (``engine/dataframe.py``) — coordinating through
~20 locks and conditions. PR 6/7 reviews each caught a locking hazard
by hand; these rules make that vigilance a tool:

- ``lock-order`` — build the cross-module lock-acquisition-order graph
  (every ``with A:`` nesting ``with B:``, directly or through
  same-module calls made while holding ``A``) and fail on cycles —
  two threads taking the same pair of locks in opposite orders is a
  deadlock waiting for load — and on re-acquisition of a plain
  (non-reentrant) ``Lock`` while already held.
- ``wait-holding-lock`` — ``cond.wait()`` releases only the
  condition's OWN lock; waiting while holding any other lock parks
  that lock for the whole wait and deadlocks as soon as the waker
  needs it.
- ``blocking-under-lock`` — ``time.sleep``, ``future.result``,
  thread ``join``, file writes, device fetches (``np.asarray``,
  ``device_get``, ``block_until_ready``), ``executor.execute``,
  ``subprocess.run`` under a held lock stall every sibling contending
  for that lock for the duration — the exact class of bug the PR 6/7
  reviews caught by hand (the coalescer-thread backoff sleep, the
  lock-order-unsafe ``status()``).
- ``unguarded-shared-write`` — in a class that owns a lock, a
  ``self._x = …`` store outside any lock scope (``__init__`` exempt:
  construction is single-threaded by convention) is either a data race
  or an undocumented single-thread contract; the suppression comment
  is the explicit "intentionally unguarded" escape hatch.
- ``thread-lifecycle`` — every ``threading.Thread(…)`` must set
  ``name=`` (anonymous ``Thread-N`` names make every stack dump and
  telemetry track unreadable) and live in a module with a reachable
  ``join`` path (a thread nobody can join is a leak by construction).
  ``multiprocessing.Process(…)`` (any spelling: ``multiprocessing`` /
  ``mp`` / a ``get_context(...)`` variable / bare ``Process``) is held
  to the same bar — ``name=`` required, plus ``daemon=True`` or a
  module join path: a leaked worker PROCESS outlives the interpreter
  unless it is daemonic or someone reaps it (the decode pool names and
  joins its workers; this rule is how it polices itself).

All static, all conservative: resolution failures drop edges rather
than inventing them (see :mod:`sparkdl_tpu.analysis.locks` for exactly
what resolves). Suppress with
``# sparkdl: allow(<rule>): <justification>`` on the finding's line.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Set, Tuple

from sparkdl_tpu.analysis import locks
from sparkdl_tpu.analysis.framework import (Finding, Rule, SourceFile,
                                            register)


def _held_desc(held) -> str:
    return " + ".join(f"{h.lock.qualname} (acquired line {h.line})"
                      for h in held)


@register
class LockOrderRule(Rule):
    id = "lock-order"
    title = "lock-acquisition-order cycles and Lock re-acquisition"
    rationale = (
        "Two code paths taking the same pair of locks in opposite "
        "orders deadlock under load; re-acquiring a plain "
        "threading.Lock already held by this thread deadlocks "
        "immediately. The rule merges every module's nested-with and "
        "held-call acquisition edges into one graph and rejects "
        "cycles.")

    def finalize(self, sources: Sequence[SourceFile]) -> List[Finding]:
        # edge (A, B) -> first observed site (rel, line, via)
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        kinds: Dict[str, str] = {}

        def add(a: locks.Lock, b: locks.Lock, rel: str, line: int,
                via: str) -> None:
            kinds[a.qualname] = a.kind
            kinds[b.qualname] = b.kind
            edges.setdefault((a.qualname, b.qualname), (rel, line, via))

        for src in sources:
            model = locks.module_model(src)
            reach = locks.reachable_acquired(model)
            for key, s in model.all_summaries():
                for a, b, line in s.edges:
                    add(a, b, src.rel, line, s.qualname)
                for callee, line, held in s.calls:
                    if not held:
                        continue
                    for item in reach.get(callee, ()):
                        lk, _lline, via = item
                        for h in held:
                            add(h.lock, lk, src.rel, line,
                                f"{s.qualname} -> {via}")

        findings: List[Finding] = []
        # self-edges: re-acquiring a non-reentrant Lock while held
        for (a, b), (rel, line, via) in sorted(edges.items()):
            if a == b and kinds.get(a) == "lock":
                findings.append(self.finding(
                    rel, line,
                    f"{a} is a plain (non-reentrant) threading.Lock "
                    f"re-acquired while already held (in {via}) — this "
                    "deadlocks immediately"))
        # cycles among distinct locks
        adj: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            if a != b:
                adj.setdefault(a, set()).add(b)
                adj.setdefault(b, set())
        for comp in _sccs(sorted(adj), adj):
            if len(comp) < 2:
                continue
            comp_set = set(comp)
            sites = sorted(
                f"{a} -> {b} at {rel}:{line} (in {via})"
                for (a, b), (rel, line, via) in edges.items()
                if a in comp_set and b in comp_set and a != b)
            anchor = min((edges[(a, b)], (a, b))
                         for (a, b) in edges
                         if a in comp_set and b in comp_set and a != b)[0]
            findings.append(self.finding(
                anchor[0], anchor[1],
                "lock-acquisition-order cycle (potential deadlock) "
                f"among {sorted(comp_set)}: " + "; ".join(sites)))
        return findings


def _sccs(nodes: Sequence[str],
          adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan strongly-connected components (iterative-friendly sizes:
    the lock graph is tiny)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    stack: List[str] = []
    on: Set[str] = set()
    out: List[List[str]] = []
    counter = [0]

    def strong(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in sorted(adj.get(v, ())):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            out.append(comp)

    for v in nodes:
        if v not in index:
            strong(v)
    return out


@register
class WaitHoldingLockRule(Rule):
    id = "wait-holding-lock"
    title = "cond.wait() while holding a different lock"
    rationale = (
        "Condition.wait releases only the condition's own lock; any "
        "OTHER lock held across the wait stays held for the whole "
        "sleep and deadlocks the moment the intended waker needs it.")

    def check(self, src: SourceFile) -> List[Finding]:
        model = locks.module_model(src)
        reach = locks.reachable_waits(model)
        findings: List[Finding] = []
        seen: Set[Tuple[int, str, str]] = set()

        def flag(cond: locks.Lock, line: int, held, via: str = "") -> None:
            foreign = [h for h in held
                       if h.lock.qualname != cond.qualname]
            if not foreign:
                return
            key = (line, cond.qualname,
                   foreign[0].lock.qualname)
            if key in seen:
                return
            seen.add(key)
            findings.append(self.finding(
                src, line,
                f"{cond.qualname}.wait() while holding "
                f"{_held_desc(foreign)}"
                + (f" (reached {via})" if via else "")
                + " — wait releases only the condition's own lock; the "
                "foreign lock stays held for the whole sleep"))

        for _key, s in model.all_summaries():
            for cond, line, held in s.waits:
                flag(cond, line, held)
            for callee, cline, held in s.calls:
                if not held:
                    continue
                for cond, wline, via in reach.get(callee, ()):
                    flag(cond, wline, held,
                         via=f"from {s.qualname}:{cline} via {via}")
        return findings


@register
class BlockingUnderLockRule(Rule):
    id = "blocking-under-lock"
    title = "blocking call under a held lock"
    rationale = (
        "time.sleep / future.result / thread join / file writes / "
        "device fetches (np.asarray, device_get, block_until_ready) / "
        "executor.execute / subprocess under a held lock stall every "
        "thread contending for that lock for the whole duration — the "
        "coalescer, exporter and supervisor threads all share locks "
        "with hot paths. Move the blocking call outside the lock "
        "scope, or suppress with the documented single-writer "
        "justification.")

    def check(self, src: SourceFile) -> List[Finding]:
        model = locks.module_model(src)
        reach = locks.reachable_blocking(model)
        findings: List[Finding] = []
        seen: Set[Tuple[int, str, str]] = set()

        def flag(desc: str, line: int, held, via: str = "") -> None:
            if not held:
                return
            key = (line, desc, held[0].lock.qualname)
            if key in seen:
                return
            seen.add(key)
            findings.append(self.finding(
                src, line,
                f"blocking call {desc} while holding "
                f"{_held_desc(held)}"
                + (f" (reached {via})" if via else "")))

        for _key, s in model.all_summaries():
            for desc, line, held in s.blocking:
                flag(desc, line, held)
            for callee, cline, held in s.calls:
                if not held:
                    continue
                for desc, bline, via in reach.get(callee, ()):
                    flag(desc, bline, held,
                         via=f"from {s.qualname}:{cline} via {via}")
        return findings


@register
class UnguardedSharedWriteRule(Rule):
    id = "unguarded-shared-write"
    title = "self._* store outside any lock scope in a lock-owning class"
    rationale = (
        "A class that owns a lock has declared its state shared; a "
        "``self._x = …`` store outside every lock scope (outside "
        "__init__) is either a data race or an undocumented "
        "single-thread contract. Guard it, or make the contract "
        "explicit with a suppression justification.")

    def check(self, src: SourceFile) -> List[Finding]:
        model = locks.module_model(src)
        findings: List[Finding] = []
        for cls in model.classes.values():
            if not cls.guard_locks:
                continue
            for mname, s in cls.methods.items():
                if mname == "__init__":
                    continue  # construction is single-threaded
                for attr, line, held in s.attr_writes:
                    if held or not attr.startswith("_") \
                            or attr.startswith("__") \
                            or attr in cls.lock_attrs:
                        continue
                    findings.append(self.finding(
                        src, line,
                        f"{cls.name}.{mname} writes self.{attr} "
                        f"outside any lock scope, but {cls.name} owns "
                        f"{', '.join(lk.qualname for lk in cls.guard_locks)}"
                        " — guard the store or justify the "
                        "single-writer contract with a suppression"))
        return findings


@register
class ThreadLifecycleRule(Rule):
    id = "thread-lifecycle"
    title = "threads and worker processes must be named and reapable"
    rationale = (
        "An anonymous Thread-N (or Process-N) makes every stack dump, "
        "log line and telemetry track unreadable; a thread created in "
        "a module with no join path anywhere is a leak by construction "
        "(the prefetcher, coalescer and exporter all pair creation "
        "with a close()/shutdown() join). A multiprocessing.Process is "
        "worse: a leaked non-daemon worker outlives the interpreter — "
        "it needs name= plus daemon=True or a module join path (the "
        "decode pool does both).")

    def check(self, src: SourceFile) -> List[Finding]:
        model = locks.module_model(src)
        findings: List[Finding] = []
        for line, has_name in model.threads:
            if not has_name:
                findings.append(self.finding(
                    src, line,
                    "threading.Thread(...) without name= — name the "
                    "thread (sparkdl-<role>) so stack dumps and "
                    "telemetry tracks stay readable"))
            if not model.has_join:
                findings.append(self.finding(
                    src, line,
                    "threading.Thread(...) in a module with no "
                    ".join(...) call anywhere — every started thread "
                    "needs a reachable join/stop path"))
        for line, has_name, daemonic in model.processes:
            if not has_name:
                findings.append(self.finding(
                    src, line,
                    "multiprocessing.Process(...) without name= — name "
                    "the worker (sparkdl-<role>) so ps output, stack "
                    "dumps and telemetry stay readable"))
            if not daemonic and not model.has_join:
                findings.append(self.finding(
                    src, line,
                    "multiprocessing.Process(...) that is neither "
                    "daemon=True nor in a module with a .join(...) "
                    "call anywhere — a leaked non-daemon worker "
                    "process outlives the interpreter; daemonize it or "
                    "give the module a reachable join/reap path"))
        return findings
