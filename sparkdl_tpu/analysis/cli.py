"""``python -m sparkdl_tpu.analysis`` — the analyzer CLI.

Exit codes (pinned by tests/test_analysis.py):

- ``0`` — clean (no unsuppressed, unbaselined findings)
- ``1`` — findings
- ``2`` — usage error (unknown rule, nonexistent path, bad flags)
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import List, Optional

from sparkdl_tpu.analysis import baseline as baseline_mod
from sparkdl_tpu.analysis import framework


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m sparkdl_tpu.analysis",
        description="sparkdl_tpu static analyzer: concurrency "
                    "discipline + the migrated taxonomy lints "
                    "(docs/ANALYSIS.md)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to analyze (default: the "
                        "sparkdl_tpu package)")
    p.add_argument("--rule", action="append", dest="rules",
                   metavar="ID",
                   help="run only this rule (repeatable; default all)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (schema version 1)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit 0")
    p.add_argument("--baseline", metavar="FILE",
                   default=str(baseline_mod.DEFAULT_BASELINE_PATH),
                   help="baseline file (default: the checked-in "
                        "analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline entirely")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to --baseline and "
                        "exit 0 (emergency grandfathering; prefer "
                        "inline suppressions)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    try:
        args = _parser().parse_args(argv)
    except SystemExit as e:  # argparse exits 2 on usage errors
        return int(e.code or 0)

    if args.list_rules:
        for rule_id, rule in sorted(framework.all_rules().items()):
            print(f"{rule_id:24s} {rule.title}")
        return 0

    paths = [pathlib.Path(p) for p in args.paths] \
        or [framework.PACKAGE_ROOT]
    for p in paths:
        if not p.exists():
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2
    try:
        rule_ids = list(args.rules) if args.rules else None
        # --write-baseline regenerates from the FULL finding set: loading
        # the existing baseline first would absorb its own entries and
        # write an empty file on the second run
        bl = (None if args.no_baseline or args.write_baseline
              else baseline_mod.Baseline.load(pathlib.Path(args.baseline)))
        result = framework.analyze(paths, rule_ids=rule_ids, baseline=bl)
    except framework.UnknownRuleError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        # hygiene/parse-error findings are never baselineable (they are
        # trivial to fix and grandfathering an unjustified suppression
        # would defeat the justification requirement) — writing them
        # would only create instantly-stale entries
        grandfatherable = [
            f for f in result.findings
            if f.rule not in (framework.PARSE_ERROR,
                              framework.SUPPRESSION_HYGIENE)]
        baseline_mod.Baseline.from_findings(grandfatherable).save(
            pathlib.Path(args.baseline))
        print(f"wrote {len(grandfatherable)} entr"
              f"{'y' if len(grandfatherable) == 1 else 'ies'} to "
              f"{args.baseline}")
        return 0

    if args.json:
        print(json.dumps(result.as_dict(), indent=2))
    else:
        for f in result.findings:
            print(str(f))
        for e in result.stale_baseline:
            print(f"stale baseline entry (no longer matches): "
                  f"{e['path']}: [{e['rule']}] {e['message']}",
                  file=sys.stderr)
        print(f"{len(result.findings)} finding(s) "
              f"({len(result.suppressed)} suppressed, "
              f"{len(result.baselined)} baselined) across "
              f"{result.files} file(s)")
    return 1 if result.findings else 0
