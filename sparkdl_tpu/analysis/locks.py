"""Per-module lock-model extraction for the concurrency rules.

One AST walk per file (memoized on ``SourceFile.cache``) produces a
:class:`ModuleLockModel`:

- **lock inventory** — ``threading.Lock/RLock/Condition/Semaphore/
  Event`` objects assigned to module globals (``_pool_lock =
  threading.Lock()``) or to ``self.<attr>`` inside a class
  (``self._lock = threading.Lock()``, ``self.cond =
  threading.Condition()``). Each gets a qualified identity —
  ``module.py:var`` or ``Class.attr`` — and a kind (``lock`` /
  ``rlock`` / ``condition`` / ``event``); Conditions default to an
  internal RLock, so only plain ``lock``s are re-entrancy hazards.
- **per-function summaries** — for every function/method: which locks
  its body acquires (``with lock:`` scopes, plus blocking
  ``.acquire()`` calls; ``acquire(blocking=False)`` is exempt — it
  cannot deadlock), the nested-acquisition edges that implies, every
  ``cond.wait`` site with the locks held around it, every
  blocking-listed call with the locks held around it, every
  ``self._x = ...`` attribute store with the locks held around it, and
  the ``self.method()`` / same-module ``function()`` calls made while
  holding locks (for one-module-deep interprocedural propagation: a
  helper that blocks, called under a lock, is the caller's hazard).

Resolution is deliberately static and conservative: ``self.X`` resolves
through the enclosing class's lock inventory, ``param.X`` resolves when
the parameter is annotated with a same-module class name (the
``state: _FnState`` idiom in ``core/executor.py``), module globals
resolve by name. Anything else — attributes on locals, cross-object
chains — is left unresolved and unreported rather than guessed at.
Nested function definitions are scanned with an EMPTY held-set (their
bodies run later, on an unknown thread, not at the definition site).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from sparkdl_tpu.analysis.framework import SourceFile

_FACTORY_KINDS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "Semaphore": "lock",
    "BoundedSemaphore": "lock",
    "Event": "event",
}

#: Kinds that guard shared state (Events signal, they don't guard).
GUARD_KINDS = ("lock", "rlock", "condition")


@dataclass(frozen=True)
class Lock:
    """One lock object's static identity."""

    qualname: str  # "Class.attr" or "module.py:var"
    kind: str      # lock | rlock | condition | event


@dataclass(frozen=True)
class HeldLock:
    lock: Lock
    line: int  # where it was acquired


@dataclass
class FunctionSummary:
    """Everything the concurrency rules need to know about one
    function/method body."""

    qualname: str
    lineno: int
    acquired: List[Tuple[Lock, int]] = field(default_factory=list)
    edges: List[Tuple[Lock, Lock, int]] = field(default_factory=list)
    waits: List[Tuple[Lock, int, Tuple[HeldLock, ...]]] = \
        field(default_factory=list)
    blocking: List[Tuple[str, int, Tuple[HeldLock, ...]]] = \
        field(default_factory=list)
    attr_writes: List[Tuple[str, int, Tuple[HeldLock, ...]]] = \
        field(default_factory=list)
    calls: List[Tuple[Tuple[str, str], int, Tuple[HeldLock, ...]]] = \
        field(default_factory=list)


@dataclass
class ClassModel:
    name: str
    lock_attrs: Dict[str, Lock] = field(default_factory=dict)
    methods: Dict[str, FunctionSummary] = field(default_factory=dict)

    @property
    def guard_locks(self) -> List[Lock]:
        return [lk for lk in self.lock_attrs.values()
                if lk.kind in GUARD_KINDS]


@dataclass
class ModuleLockModel:
    rel: str
    module_locks: Dict[str, Lock] = field(default_factory=dict)
    classes: Dict[str, ClassModel] = field(default_factory=dict)
    functions: Dict[str, FunctionSummary] = field(default_factory=dict)
    threads: List[Tuple[int, bool]] = field(default_factory=list)
    # (lineno, has_name) per threading.Thread(...) creation
    processes: List[Tuple[int, bool, bool]] = field(default_factory=list)
    # (lineno, has_name, daemon=True) per multiprocessing Process(...)
    # creation (multiprocessing.Process / mp.Process / <get_context
    # var>.Process / bare Process)
    ctx_names: Set[str] = field(default_factory=set)
    # module globals assigned from multiprocessing.get_context(...) —
    # their .Process(...) calls are process factories
    has_join: bool = False

    def summary(self, key: Tuple[str, str]) -> Optional[FunctionSummary]:
        scope, name = key
        if scope:
            cls = self.classes.get(scope)
            return cls.methods.get(name) if cls else None
        return self.functions.get(name)

    def all_summaries(self) -> List[Tuple[Tuple[str, str],
                                          FunctionSummary]]:
        out: List[Tuple[Tuple[str, str], FunctionSummary]] = []
        for name, s in self.functions.items():
            out.append((("", name), s))
        for cname, cls in self.classes.items():
            for mname, s in cls.methods.items():
                out.append(((cname, mname), s))
        return out


def _factory_kind(value: ast.expr) -> Optional[str]:
    """``threading.Lock()`` / ``Lock()`` → "lock", etc."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    name = (f.attr if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else None)
    return _FACTORY_KINDS.get(name) if name else None


def _is_thread_factory(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute):
        return (f.attr == "Thread" and isinstance(f.value, ast.Name)
                and f.value.id == "threading")
    return isinstance(f, ast.Name) and f.id == "Thread"


# Receiver names whose ``.Process(...)`` is a worker-process factory.
# Deliberately narrow: an arbitrary ``X.Process(pid)`` (psutil's process
# HANDLE lookup, say) creates nothing, so only the multiprocessing
# module spellings and get_context(...) results count.
_PROCESS_BASES = {"multiprocessing", "mp"}


def _is_get_context(value: ast.expr) -> bool:
    """``multiprocessing.get_context(...)`` / ``get_context(...)``."""
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = (f.attr if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else None)
    return name == "get_context"


def _is_process_factory(call: ast.Call, ctx_names: Set[str]) -> bool:
    """``multiprocessing.Process(...)`` in any of its spellings:
    ``multiprocessing``/``mp`` attribute access, a variable bound from
    ``get_context(...)`` (module global or local), or a bare imported
    ``Process``."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id == "Process"
    if isinstance(f, ast.Attribute) and f.attr == "Process":
        v = f.value
        if isinstance(v, ast.Name):
            return v.id in _PROCESS_BASES or v.id in ctx_names
    return False


def _nonblocking_acquire(call: ast.Call) -> bool:
    """``.acquire(blocking=False)`` / ``.acquire(False)`` — cannot
    deadlock, so it is neither an ordering edge nor a blocking call."""
    for kw in call.keywords:
        if (kw.arg == "blocking" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False):
            return True
    return bool(call.args and isinstance(call.args[0], ast.Constant)
                and call.args[0].value is False)


def is_thread_join(call: ast.Call) -> bool:
    """``x.join()`` shaped like a thread/process join — no arguments, a
    ``timeout=`` kwarg, or a single numeric timeout — on a receiver
    that is not a string literal or ``os.path``. ``sep.join(items)``
    (an iterable argument) is str.join, not a wait."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "join"):
        return False
    value = f.value
    if isinstance(value, ast.Constant):
        return False  # ", ".join(...)
    if isinstance(value, ast.Attribute) and value.attr == "path":
        return False  # os.path.join
    if any(kw.arg != "timeout" for kw in call.keywords):
        return False
    if len(call.args) > 1:
        return False
    if call.args and not (isinstance(call.args[0], ast.Constant)
                          and isinstance(call.args[0].value,
                                         (int, float))):
        return False  # sep.join(items): a real iterable argument
    return True


def blocking_call_desc(call: ast.Call) -> Optional[str]:
    """Human-readable descriptor when ``call`` is on the blocking-call
    list (docs/ANALYSIS.md ``blocking-under-lock``), else None."""
    f = call.func
    if isinstance(f, ast.Name):
        return "open() (file I/O)" if f.id == "open" else None
    if not isinstance(f, ast.Attribute):
        return None
    attr, value = f.attr, f.value
    vname = value.id if isinstance(value, ast.Name) else None
    if attr == "sleep" and vname == "time":
        return "time.sleep()"
    if attr == "result":
        return ".result() (future wait)"
    if attr == "join":
        return (".join() (thread/process wait)"
                if is_thread_join(call) else None)
    if attr == "asarray" and vname in ("np", "numpy"):
        return "np.asarray() (device fetch)"
    if attr == "device_get":
        return "device_get() (device fetch)"
    if attr == "block_until_ready":
        return "block_until_ready() (device sync)"
    if attr == "execute" and vname in ("executor", "_executor",
                                       "device_executor"):
        return "executor.execute() (device entry)"
    if attr == "write":
        return ".write() (file write)"
    if vname == "subprocess" and attr in ("run", "call", "check_call",
                                          "check_output"):
        return f"subprocess.{attr}()"
    if attr == "wait" and vname in ("futures", "_futures"):
        return "futures.wait()"
    return None


class _ModuleScanner:
    """One pass over a module building the :class:`ModuleLockModel`."""

    def __init__(self, src: SourceFile) -> None:
        self.src = src
        self.model = ModuleLockModel(rel=src.rel)

    # -- inventory (first pass) ---------------------------------------------

    def _collect_inventory(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.Assign):
                kind = _factory_kind(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.model.module_locks[t.id] = Lock(
                                f"{self.model.rel}:{t.id}", kind)
                elif _is_get_context(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.model.ctx_names.add(t.id)
        # EVERY class in the module gets its own inventory — including
        # classes nested in methods (the fitMultiple iterator idiom):
        # their self.<attr> locks belong to THEM, not the enclosing
        # class, so the write/blocking rules judge the right owner
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self._collect_class_inventory(node)

    def _collect_class_inventory(self, cls: ast.ClassDef) -> None:
        model = self.model.classes.setdefault(cls.name,
                                              ClassModel(cls.name))

        def walk_own(node: ast.AST):
            """ast.walk pruned at nested ClassDef boundaries — a nested
            class's ``self`` is not this class's ``self``."""
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    continue
                yield child
                yield from walk_own(child)

        for node in walk_own(cls):
            if isinstance(node, ast.Assign):
                kind = _factory_kind(node.value)
                if not kind:
                    continue
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        # module-qualified: two modules may both define
                        # `class Worker` with a `_lock` — distinct lock
                        # objects must be distinct graph nodes, or the
                        # merged lock-order graph invents phantom
                        # cycles (same-named classes within ONE module
                        # still collide — accepted limitation)
                        model.lock_attrs[t.attr] = Lock(
                            f"{self.model.rel}:{cls.name}.{t.attr}",
                            kind)

    # -- resolution ----------------------------------------------------------

    def _resolve(self, expr: ast.expr, cls: Optional[ClassModel],
                 annotations: Dict[str, str]) -> Optional[Lock]:
        if isinstance(expr, ast.Name):
            return self.model.module_locks.get(expr.id)
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)):
            base, attr = expr.value.id, expr.attr
            if base == "self" and cls is not None:
                return cls.lock_attrs.get(attr)
            ann = annotations.get(base)
            if ann is not None and ann in self.model.classes:
                return self.model.classes[ann].lock_attrs.get(attr)
            # unique-attribute fallback: `state.cond` on an UNANNOTATED
            # local still resolves when exactly one class in the module
            # owns a lock attr of that name (the `state = self._state(…)`
            # idiom in core/executor.py); an ambiguous attr name stays
            # unresolved rather than guessed at
            owners = self._attr_owners().get(attr, ())
            if len(owners) == 1:
                return owners[0]
        return None

    def _attr_owners(self) -> Dict[str, List[Lock]]:
        owners = self.model.__dict__.get("_attr_owners_cache")
        if owners is None:
            owners = {}
            for c in self.model.classes.values():
                for attr, lk in c.lock_attrs.items():
                    owners.setdefault(attr, []).append(lk)
            self.model.__dict__["_attr_owners_cache"] = owners
        return owners

    # -- per-function scan ---------------------------------------------------

    @staticmethod
    def _annotations(func: ast.FunctionDef) -> Dict[str, str]:
        """param name → annotated same-module class name (``state:
        _FnState`` and the quoted-forward-ref form)."""
        out: Dict[str, str] = {}
        args = list(func.args.posonlyargs) + list(func.args.args) \
            + list(func.args.kwonlyargs)
        for a in args:
            ann = a.annotation
            if isinstance(ann, ast.Name):
                out[a.arg] = ann.id
            elif (isinstance(ann, ast.Constant)
                    and isinstance(ann.value, str)):
                out[a.arg] = ann.value.strip('"\'')
        return out

    def _scan_function(self, func: ast.FunctionDef,
                       cls: Optional[ClassModel]) -> FunctionSummary:
        qual = (f"{cls.name}.{func.name}" if cls else func.name)
        return self._scan_stmts(func.body, qual, func.lineno, cls,
                                self._annotations(func))

    def _scan_stmts(self, stmts, qual: str, lineno: int,
                    cls: Optional[ClassModel],
                    annotations: Dict[str, str]) -> FunctionSummary:
        s = FunctionSummary(qualname=qual, lineno=lineno)
        # get_context(...) results bound to locals inside this body:
        # their .Process(...) calls are process factories too
        ctx_locals: Set[str] = set()

        def handle_call(node: ast.Call,
                        held: Tuple[HeldLock, ...]) -> None:
            f = node.func
            if _is_thread_factory(node):
                has_name = any(kw.arg == "name" for kw in node.keywords)
                self.model.threads.append((node.lineno, has_name))
            elif _is_process_factory(node,
                                     self.model.ctx_names | ctx_locals):
                has_name = any(kw.arg == "name" for kw in node.keywords)
                daemonic = any(
                    kw.arg == "daemon"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True for kw in node.keywords)
                self.model.processes.append(
                    (node.lineno, has_name, daemonic))
            if is_thread_join(node):
                self.model.has_join = True
            if isinstance(f, ast.Attribute):
                if f.attr == "acquire":
                    lk = self._resolve(f.value, cls, annotations)
                    if lk is not None and not _nonblocking_acquire(node):
                        for h in held:
                            s.edges.append((h.lock, lk, node.lineno))
                        s.acquired.append((lk, node.lineno))
                    return
                if f.attr == "wait":
                    lk = self._resolve(f.value, cls, annotations)
                    if lk is not None:
                        if lk.kind == "condition":
                            s.waits.append((lk, node.lineno, held))
                            return
                        if lk.kind == "event":
                            s.blocking.append(("Event.wait()",
                                               node.lineno, held))
                            return
            desc = blocking_call_desc(node)
            if desc is not None:
                s.blocking.append((desc, node.lineno, held))
            # call-graph edges for one-module interprocedural checks
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self" and cls is not None):
                s.calls.append(((cls.name, f.attr), node.lineno, held))
            elif isinstance(f, ast.Name):
                s.calls.append((("", f.id), node.lineno, held))

        def record_write_targets(targets: Sequence[ast.expr], line: int,
                                 held: Tuple[HeldLock, ...]) -> None:
            for t in targets:
                if isinstance(t, (ast.Tuple, ast.List)):
                    record_write_targets(t.elts, line, held)
                elif (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    s.attr_writes.append((t.attr, line, held))

        def visit(node: ast.AST, held: Tuple[HeldLock, ...]) -> None:
            if isinstance(node, ast.ClassDef):
                # a nested class's methods are scanned as THAT class's
                # methods (see scan()), not as part of this function
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # a nested def's body runs later, with unknown locks
                # held — scan it with an empty held-set
                for child in ast.iter_child_nodes(node):
                    visit(child, ())
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held
                for item in node.items:
                    visit(item.context_expr, inner)
                    lk = self._resolve(item.context_expr, cls,
                                       annotations)
                    if lk is not None:
                        for h in inner:
                            s.edges.append((h.lock, lk, node.lineno))
                        s.acquired.append((lk, node.lineno))
                        inner = inner + (HeldLock(lk, node.lineno),)
                for stmt in node.body:
                    visit(stmt, inner)
                return
            if isinstance(node, ast.Call):
                handle_call(node, held)
            elif isinstance(node, ast.Assign):
                if _is_get_context(node.value):
                    ctx_locals.update(t.id for t in node.targets
                                      if isinstance(t, ast.Name))
                record_write_targets(node.targets, node.lineno, held)
            elif isinstance(node, ast.AugAssign):
                record_write_targets([node.target], node.lineno, held)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                record_write_targets([node.target], node.lineno, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in stmts:
            visit(stmt, ())
        return s

    # -- driver --------------------------------------------------------------

    def scan(self) -> ModuleLockModel:
        tree = self.src.tree
        self._collect_inventory(tree)

        # every class's IMMEDIATE methods, wherever the class lives
        # (module level, nested in a class, nested in a method)
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            cls_model = self.model.classes[node.name]
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    cls_model.methods[item.name] = \
                        self._scan_function(item, cls_model)
        # module-level functions (the same-module propagation targets)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.model.functions.setdefault(
                    node.name, self._scan_function(node, None))
        # import-time statements: a Thread started (or a lock held) at
        # module level must not be invisible to the rules
        module_stmts = [
            stmt for stmt in tree.body
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef))]
        self.model.functions.setdefault(
            "<module>",
            self._scan_stmts(module_stmts, "<module>", 1, None, {}))
        return self.model


def module_model(src: SourceFile) -> ModuleLockModel:
    """The (memoized) lock model for one parsed file."""
    model = src.cache.get("lock_model")
    if model is None:
        model = _ModuleScanner(src).scan()
        src.cache["lock_model"] = model
    return model


# ---------------------------------------------------------------------------
# One-module-deep interprocedural closures
# ---------------------------------------------------------------------------


def _closure(model: ModuleLockModel, extract) -> Dict[Tuple[str, str],
                                                      List]:
    """Transitive closure of ``extract(summary)`` items over the
    same-module call graph (self-methods + module functions). Items are
    ``(payload..., via)`` tuples; ``via`` names the function the item
    physically lives in.

    Computed as a fixpoint (sets unioned until stable) rather than a
    memoized DFS: mutually-recursive helpers form call cycles, and a
    cycle participant visited mid-traversal must not have a PARTIAL
    reachable set cached — that would silently drop real hazards
    depending on traversal order. The per-module graphs are tiny."""
    result: Dict[Tuple[str, str], Set] = {}
    calls: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    for key, s in model.all_summaries():
        result[key] = {item + (s.qualname,) for item in extract(s)}
        calls[key] = [callee for callee, _line, _held in s.calls]
    changed = True
    while changed:
        changed = False
        for key, callees in calls.items():
            mine = result[key]
            for callee in callees:
                theirs = result.get(callee)
                if theirs and not theirs <= mine:
                    mine |= theirs
                    changed = True
    # deterministic item order (Lock dataclasses aren't orderable; repr
    # is stable) so downstream first-site anchoring never jitters
    return {key: sorted(items, key=repr)
            for key, items in result.items()}


def reachable_acquired(model: ModuleLockModel) -> Dict[Tuple[str, str],
                                                       List]:
    """key → [(Lock, line, via)] acquired in the function or any
    same-module callee."""
    return _closure(model, lambda s: [(lk, line)
                                      for lk, line in s.acquired])


def reachable_blocking(model: ModuleLockModel) -> Dict[Tuple[str, str],
                                                       List]:
    """key → [(desc, line, via)] blocking sites in the function or any
    same-module callee (held-or-not at the site — the caller's held
    locks are what make them hazards)."""
    return _closure(model, lambda s: [(desc, line)
                                      for desc, line, _h in s.blocking])


def reachable_waits(model: ModuleLockModel) -> Dict[Tuple[str, str],
                                                    List]:
    """key → [(condition Lock, line, via)] condition-wait sites in the
    function or any same-module callee."""
    return _closure(model, lambda s: [(lk, line)
                                      for lk, line, _h in s.waits])
