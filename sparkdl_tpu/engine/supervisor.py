"""Task-level supervision for the engine data plane.

Spark gave every partition task a supervisor: bounded retry with failure
classification, task deadlines, speculative re-execution of stragglers,
and blacklisting (SURVEY.md §5.3). This module is the engine's analog,
built on ``core.resilience``'s taxonomy so task retry, gang restart and
chunk retry all agree on what is worth retrying:

- :func:`run_partition_task` replaces the old blind retry loop: FATAL is
  never retried (a replay reproduces the traceback), OOM propagates (the
  batching layer already owns the shrink-and-retry response; an OOM that
  escapes the op chain has exhausted it), RETRYABLE backs off through a
  :class:`~sparkdl_tpu.core.resilience.RetryPolicy`. The terminal
  :class:`TaskFailure` carries the full per-attempt history.
- :class:`PartitionSupervisor` schedules tasks on the shared pool with a
  **deadline watchdog** (a hung op fails the task instead of wedging the
  materialization — the supervising thread enforces the budget since a
  Python worker thread cannot be interrupted), **speculative hedging** of
  stragglers (Dean & Barroso, "The Tail at Scale": once a quantile of
  sibling tasks has finished, a task running far past their typical
  duration gets a duplicate attempt; the first result wins and the loser
  is discarded, so output stays bit-identical and order-preserving — ops
  are pure by the engine's contract), and opt-in **quarantine** (a
  partition that fails fatally is dropped — replaced by a zero-row batch
  with the op chain's output schema — and recorded, instead of failing
  the job).

Everything reports into :mod:`sparkdl_tpu.core.health`.
"""

from __future__ import annotations

import concurrent.futures as _futures
import itertools
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Tuple)

from sparkdl_tpu.core import executor as _executor
from sparkdl_tpu.core import health, resilience, telemetry

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class TaskAttempt:
    """One attempt of a partition task: classification + timing.

    ``kind`` is ``"ok"`` for a successful attempt, otherwise the
    ``resilience.classify`` result (``fatal`` / ``oom`` / ``retryable``)
    of the error recorded in ``error``.
    """

    kind: str
    error: Optional[str]
    duration_s: float


class TaskFailure(RuntimeError):
    """A partition task failed terminally; carries per-attempt history.

    ``attempts`` records every attempt's classification, error and
    duration (what was retried and why — the health report and test
    assertions read it). ``failure_kind`` is the terminal attempt's
    classification; ``resilience.classify`` trusts it, so a fatal task
    failure stays fatal through upstream retry layers (TPURunner must not
    restart a gang to replay a shape error). ``deadline_exceeded`` marks
    a deadline (timeout) failure — FATAL for retry purposes, but
    excluded from quarantine: a timeout is slowness, not poison.
    """

    def __init__(self, message: str, index: Optional[int] = None,
                 attempts: Sequence[TaskAttempt] = (),
                 kind: Optional[str] = None,
                 deadline: bool = False) -> None:
        super().__init__(message)
        self.index = index
        self.attempts = list(attempts)
        self.failure_kind = kind or (
            self.attempts[-1].kind if self.attempts else resilience.RETRYABLE)
        self.deadline_exceeded = deadline

    def retries(self) -> int:
        """How many times the task was re-attempted (attempts - 1)."""
        return max(0, len(self.attempts) - 1)


# Upper bound on an injected task_stall's sleep: long enough that any
# reasonable test deadline expires first, short enough that the wedged
# pool thread frees up without a real hang.
_MAX_STALL_S = 30.0


def _maybe_stall(index: int, attempt: int,
                 deadline: resilience.Deadline) -> None:
    """The ``task_stall`` behavioral injection point: hang, don't raise.

    Sleeps past the task's deadline so the *supervisor's watchdog* — not
    this thread — decides the task's fate, then raises a retryable stall
    as a backstop for the inline (unsupervised) execution paths, where
    the cooperative deadline check on the retry fails the task instead.
    """
    if not resilience.should_fire("task_stall", partition=index,
                                  attempt=attempt):
        return
    budget = deadline.remaining()
    if budget == float("inf"):
        budget = 0.05  # no deadline armed: brief stall, then fail retryably
    time.sleep(min(max(budget, 0.0) * 2 + 0.05, _MAX_STALL_S))
    raise resilience.TransferStall(
        f"injected task_stall: partition {index} op hung")


def run_partition_task(index: int, batch: Any, ops: Sequence[Callable],
                       policy: resilience.RetryPolicy,
                       deadline_s: Optional[float] = None,
                       legacy_injector: Optional[Callable[[int, int], None]]
                       = None,
                       max_fatal_attempts: int = 1,
                       cancelled: Optional[threading.Event] = None,
                       sleep: Callable[[float], None] = time.sleep) -> Any:
    """Run the op chain on one partition with classified retry.

    The deadline here is *cooperative* (checked between ops and before
    each retry); :class:`PartitionSupervisor`'s watchdog enforces the
    same budget preemptively for ops that hang. ``legacy_injector`` is
    the compat shim for the old ``EngineConfig.fault_injector``
    ``(index, attempt)`` hook — new code arms the ``engine_task`` /
    ``task_stall`` points of ``resilience.FaultInjector`` instead (one
    injection mechanism, one seeding story).

    ``max_fatal_attempts`` (quarantine mode only, > 1): a FATAL failure
    is re-attempted — immediately, no backoff — up to this many total
    fatal attempts to *confirm the poison* before the partition is
    dropped. At the default 1, FATAL is never retried.

    ``cancelled`` (set by the supervisor's watchdog after it abandons
    this task): once set, the task bails out quietly between ops and
    between attempts — no further retries, and no health records, since
    the watchdog already recorded the outcome and discarded the result.
    """
    deadline = resilience.Deadline(deadline_s)
    attempts: List[TaskAttempt] = []
    attempt = 0

    def abandoned() -> bool:
        return cancelled is not None and cancelled.is_set()

    health.record(health.TASK_STARTED, partition=index)
    while True:
        t0 = time.monotonic()
        # each retry-loop attempt re-runs the op chain from the top, so
        # its device calls restart at call 0 — realign the executor's
        # hedge-dedup sequence, or a retried primary's call 0 (seq N)
        # could cross-dedup a fresh hedge's call N onto the wrong output
        _executor.reset_call_sequence()
        try:
            # one telemetry span per retry-loop attempt (ambient-parented
            # under the pool thread's sparkdl.task span, so a retried or
            # hedged task's attempts all share the task's trace); an
            # exception unwinding through it stamps an `error` attribute
            # the task's Deadline rides into the device execution service
            # ambiently (core/executor.py): a queued device request whose
            # budget expires is dropped at drain time — before paying for
            # a launch — and the blocking-admission wait is bounded by it
            with telemetry.span(telemetry.SPAN_TASK_ATTEMPT,
                                partition=index, attempt=attempt), \
                    _executor.deadline_scope(deadline):
                if legacy_injector is not None:
                    legacy_injector(index, attempt)
                resilience.inject("engine_task", partition=index,
                                  attempt=attempt, phase="start")
                _maybe_stall(index, attempt, deadline)
                out = batch
                for op in ops:
                    if abandoned():
                        raise TaskFailure(
                            f"partition {index} task abandoned by the "
                            "supervisor", index=index, attempts=attempts,
                            kind=resilience.FATAL, deadline=True)
                    deadline.check(f"partition {index} task")
                    out = op(out)
                resilience.inject("engine_task", partition=index,
                                  attempt=attempt, phase="finish")
                return out
        except Exception as e:  # noqa: BLE001 - classified below
            if abandoned():
                # The watchdog already failed this task, recorded the
                # event, and discarded the result — bail quietly instead
                # of retrying (and double-counting) into the void.
                raise
            kind = resilience.classify(e)
            attempts.append(TaskAttempt(kind, repr(e),
                                        time.monotonic() - t0))
            if isinstance(e, resilience.DeadlineExceeded):
                # Cooperative expiry (the op chain crossed the budget
                # between watchdog ticks): FATAL for retry purposes but
                # marked as a deadline failure — quarantine must not
                # treat slowness as poison. Supervised runs (cancelled
                # is not None) leave the event recording to the
                # supervisor — it records EITHER at resolution OR from
                # the watchdog, never both — so the count stays exact.
                if cancelled is None:
                    health.record(health.TASK_DEADLINE_EXCEEDED,
                                  partition=index)
                raise TaskFailure(
                    str(e), index=index, attempts=attempts,
                    kind=resilience.FATAL, deadline=True) from e
            if kind == resilience.FATAL:
                fatal_seen = sum(1 for a in attempts
                                 if a.kind == resilience.FATAL)
                if fatal_seen < max_fatal_attempts and not deadline.expired():
                    # quarantine confirmation: deliberately replay the
                    # deterministic failure before dropping the partition
                    health.record(health.TASK_RETRIED, partition=index,
                                  attempt=attempt + 1, kind=kind,
                                  error=type(e).__name__)
                    logger.warning(
                        "partition %d task failed fatally (%s: %s); "
                        "confirming poison, attempt %d/%d", index,
                        type(e).__name__, e, fatal_seen + 1,
                        max_fatal_attempts)
                    attempt += 1
                    continue
                health.record(health.TASK_FAILED, partition=index, kind=kind)
                raise TaskFailure(
                    f"partition {index} failed with a fatal error on "
                    f"attempt {attempt + 1} "
                    + ("(never retried)" if max_fatal_attempts == 1 else
                       f"({fatal_seen} fatal attempt(s))")
                    + f": {e}",
                    index=index, attempts=attempts, kind=kind) from e
            if kind == resilience.OOM:
                # The batching layer's bucket-halving already ran inside
                # the op; an OOM surfacing here reproduces at these shapes.
                health.record(health.TASK_FAILED, partition=index, kind=kind)
                raise TaskFailure(
                    f"partition {index} exhausted device memory past the "
                    f"batching layer's fallback: {e}",
                    index=index, attempts=attempts, kind=kind) from e
            attempt += 1
            if attempt > policy.max_retries:
                health.record(health.TASK_FAILED, partition=index, kind=kind)
                raise TaskFailure(
                    f"partition {index} failed after {attempt} attempts: "
                    f"{e}", index=index, attempts=attempts, kind=kind) from e
            if deadline.expired():
                if cancelled is None:  # supervised: recorder is the
                    health.record(     # supervisor (see above)
                        health.TASK_DEADLINE_EXCEEDED, partition=index)
                raise TaskFailure(
                    f"partition {index} task exceeded its {deadline_s}s "
                    f"deadline after {attempt} attempt(s) (last: {e})",
                    index=index, attempts=attempts,
                    kind=resilience.FATAL, deadline=True) from e
            health.record(health.TASK_RETRIED, partition=index,
                          attempt=attempt, kind=kind,
                          error=type(e).__name__)
            d = policy.delay(attempt)
            logger.warning(
                "partition %d task failed (%s: %s); retry %d/%d in %.2fs",
                index, type(e).__name__, e, attempt, policy.max_retries, d)
            if d > 0:
                sleep(d)


# ---------------------------------------------------------------------------
# Scheduling-level supervision: watchdog, hedging, quarantine
# ---------------------------------------------------------------------------

@dataclass
class SupervisorConfig:
    """Scheduling knobs, snapshotted from ``EngineConfig`` per run."""

    task_timeout_s: Optional[float] = None
    speculation: bool = False
    speculation_quantile: float = 0.75
    speculation_multiplier: float = 1.5
    speculation_min_runtime_s: float = 0.05
    quarantine: bool = False
    quarantine_max_fatal: int = 1

    @property
    def poll_interval_s(self) -> float:
        """Watchdog tick: tight when a deadline or hedging is armed (they
        need timely checks), relaxed otherwise (completions wake the wait
        regardless)."""
        if self.task_timeout_s is not None:
            return min(0.05, self.task_timeout_s / 4)
        if self.speculation:
            return 0.02
        return 0.5


class _Task:
    """One logical partition task: primary attempt + optional hedge.

    ``runner`` receives the task's cancellation event (set by the
    watchdog when the task is abandoned) so an attempt can bail out
    quietly instead of retrying into the void.
    """

    __slots__ = ("index", "runner", "_submit", "holders", "futures",
                 "hedged", "done", "result", "error", "duration",
                 "deadline_failed", "cancel_event", "trace_ctx",
                 "task_seq")

    _task_counter = itertools.count(1)

    def __init__(self, index: int,
                 runner: Callable[[threading.Event], Any],
                 submit: Callable) -> None:
        self.index = index
        self.runner = runner
        self._submit = submit
        self.task_seq = next(_Task._task_counter)
        # Captured on the SCHEDULING thread: every attempt of this task
        # (primary, retries inside it, a hedge duplicate) opens its pool-
        # thread span under this context, so they all share the task's
        # trace (core.telemetry cross-thread handoff).
        self.trace_ctx = telemetry.current_context()
        self.holders: List[Dict[str, float]] = []
        self.futures: List[_futures.Future] = []
        self.hedged = False
        self.done = False
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.duration: Optional[float] = None
        self.deadline_failed = False
        self.cancel_event = threading.Event()

    def launch(self) -> _futures.Future:
        holder: Dict[str, float] = {}
        runner = self.runner
        cancel_event = self.cancel_event
        attempt = len(self.holders)  # 0 = primary, 1 = the hedge
        ctx = self.trace_ctx
        index = self.index

        # every attempt of this task (primary, hedge) shares one executor
        # task token, so a hedged duplicate's device requests DEDUP onto
        # the primary's still-queued coalescing request instead of
        # launching the same rows twice (core/executor.py). The id comes
        # from a monotonic counter, NOT id(self): a freed _Task's address
        # can be recycled while a hedge loser's request is still queued,
        # and a colliding token could hand a new task stale rows.
        token = ("task", self.task_seq, index)

        def run(h=holder):
            h["started"] = time.monotonic()
            # explicit parent (NOT telemetry.attach): pool threads are
            # reused, an attached base would leak into the next task
            with telemetry.span(telemetry.SPAN_TASK, parent=ctx,
                                partition=index, pool_attempt=attempt):
                with _executor.task_scope(token):
                    return runner(cancel_event)

        self.holders.append(holder)
        fut = self._submit(run)
        self.futures.append(fut)
        return fut

    def first_started(self) -> Optional[float]:
        ts = [h["started"] for h in self.holders if "started" in h]
        return min(ts) if ts else None


class PartitionSupervisor:
    """Supervises a set (or stream) of partition tasks on the shared pool.

    ``quarantine_probe(partition_index)`` builds the zero-row stand-in for
    a quarantined partition (the op chain run on an empty slice — keeps
    the chain's output schema and partition alignment while dropping the
    poisoned rows); when even the probe fails, the original failure
    propagates.
    """

    def __init__(self, pool: _futures.ThreadPoolExecutor,
                 config: SupervisorConfig,
                 quarantine_probe: Optional[Callable[[int], Any]] = None
                 ) -> None:
        self._pool = pool
        self._cfg = config
        self._probe = quarantine_probe
        self._durations: List[float] = []
        # Hedge losers still running after their task resolved: their pure
        # ops are harmless and their results are discarded, so a CLEAN run
        # returns without waiting for them (the latency win hedging
        # exists for). A FAILURE unwind waits them out — user ops must
        # not still be running when the caller starts cleanup.
        self._lingering: List[_futures.Future] = []

    # -- barrier mode (materialize) ------------------------------------------

    def run_all(self, indexed_runners:
                Sequence[Tuple[int, Callable[[threading.Event], Any]]]
                ) -> List[Any]:
        """Run every task; results in input order. First failure raises
        (after the barrier drain), unless quarantine absorbs it. Each
        runner receives the task's cancellation event."""
        tasks: List[_Task] = []
        outstanding: Dict[_futures.Future, _Task] = {}
        for index, runner in indexed_runners:
            task = _Task(index, runner, self._pool.submit)
            outstanding[task.launch()] = task
            tasks.append(task)
        try:
            while not all(t.done for t in tasks):
                self._tick(outstanding, tasks, len(tasks))
        except BaseException:
            self._drain(outstanding, include_lingering=True)
            raise
        self._drain(outstanding,
                    include_lingering=any(t.error is not None
                                          for t in tasks))
        return [self._terminal(t) for t in tasks]

    # -- streaming mode (streamPartitions) -----------------------------------

    def run_stream(self, indexed_runners:
                   Iterable[Tuple[int, Callable[[threading.Event], Any]]],
                   prefetch: int) -> Iterator[Any]:
        """Yield task results in input order; in-flight capped at
        ``prefetch + 1``. Abandoned iteration (early break / error)
        CANCELS unstarted attempts — an early ``break`` must not silently
        compute (and decode) the rest of the epoch — then waits out
        attempts already running user ops (the barrier ``_materialize``
        keeps), skipping watchdog-failed tasks whose threads may be
        wedged."""
        it = iter(indexed_runners)
        window: "deque[_Task]" = deque()
        outstanding: Dict[_futures.Future, _Task] = {}
        launched = 0
        exhausted = False

        def refill() -> None:
            nonlocal launched, exhausted
            while not exhausted and len(window) <= prefetch:
                try:
                    index, runner = next(it)
                except StopIteration:
                    exhausted = True
                    return
                task = _Task(index, runner, self._pool.submit)
                launched += 1
                outstanding[task.launch()] = task
                window.append(task)

        clean = False
        try:
            refill()
            while window:
                head = window[0]
                while not head.done:
                    self._tick(outstanding, list(window),
                               launched if exhausted else launched + 1)
                window.popleft()
                refill()
                yield self._terminal(head)
            clean = True
        finally:
            # Anything but clean exhaustion (a task failure, abandoned
            # iteration, an error unwind) gets the full barrier,
            # including remembered hedge losers. A clean run leaves
            # losers (if any) to finish their discarded pure ops in the
            # background.
            self._drain(outstanding, include_lingering=not clean)

    # -- the supervision tick ------------------------------------------------

    def _tick(self, outstanding: Dict[_futures.Future, _Task],
              tasks: List[_Task], total: int) -> None:
        live = [f for f in outstanding]
        if live:
            _futures.wait(live, timeout=self._cfg.poll_interval_s,
                          return_when=_futures.FIRST_COMPLETED)
        self._resolve_ready(outstanding)
        self._check_deadlines(tasks, outstanding)
        self._maybe_hedge(tasks, outstanding, total)

    def _resolve_ready(self, outstanding: Dict[_futures.Future, _Task]
                       ) -> None:
        for fut in [f for f in outstanding if f.done()]:
            task = outstanding.pop(fut, None)
            if task is None or task.done or fut.cancelled():
                continue
            # the WINNING attempt's own runtime (a hedge win must not
            # feed the primary's straggle into the speculation baseline)
            attempt_idx = task.futures.index(fut)
            started = task.holders[attempt_idx].get(
                "started", task.first_started())
            task.done = True
            task.duration = (time.monotonic() - started
                             if started is not None else 0.0)
            telemetry.observe(telemetry.M_TASK_DURATION_S, task.duration)
            err = fut.exception()
            if err is not None:
                # First terminal outcome wins, success or failure: the
                # sibling attempt runs the same pure ops and would fail
                # the same way.
                task.error = err
                if isinstance(err, TaskFailure) and err.deadline_exceeded:
                    # cooperative expiry inside a supervised task: the
                    # worker deferred recording to us (single recorder —
                    # the watchdog path can't also fire, its guard sees
                    # this resolved task)
                    health.record(health.TASK_DEADLINE_EXCEEDED,
                                  partition=task.index)
            else:
                task.result = fut.result()
                self._durations.append(task.duration)
                if telemetry.active() is not None:
                    # rows/bytes of the WINNING attempt only (a hedge
                    # loser's identical result is discarded above and
                    # must not double-count the partition)
                    num_rows = getattr(task.result, "num_rows", None)
                    if num_rows is not None:
                        telemetry.count(telemetry.M_ENGINE_ROWS_OUT,
                                        num_rows)
                        telemetry.count(telemetry.M_ENGINE_BYTES_OUT,
                                        task.result.nbytes)
                if task.hedged and fut is not task.futures[0]:
                    health.record(health.HEDGE_WON, partition=task.index)
                    logger.info("hedge won for partition %d", task.index)
            # deterministic dedup: only the winner is kept. Signal the
            # cancel event so a RUNNING loser bails quietly at its next
            # op/except boundary instead of retrying (and recording
            # failure events) for a task that already resolved.
            task.cancel_event.set()
            for other in task.futures:
                if other is not fut:
                    # An unstarted loser is dropped outright; a running
                    # loser is remembered so a failure unwind can wait it
                    # out (its result is discarded by the task.done guard
                    # above either way).
                    outstanding.pop(other, None)
                    if not other.cancel():
                        self._lingering.append(other)

    def _check_deadlines(self, tasks: List[_Task],
                         outstanding: Dict[_futures.Future, _Task]) -> None:
        timeout = self._cfg.task_timeout_s
        if timeout is None:
            return
        now = time.monotonic()
        for task in tasks:
            if task.done:
                continue
            if any(f.done() for f in task.futures):
                # an attempt completed between ticks (possibly via the
                # cooperative deadline check, which already recorded the
                # event) — let the next _resolve_ready claim it rather
                # than double-reporting the same task
                continue
            started = task.first_started()
            if started is None or now - started <= timeout:
                continue
            task.done = True
            task.deadline_failed = True
            task.cancel_event.set()  # abandoned attempts bail quietly
            elapsed = now - started
            task.duration = elapsed
            # watchdog kills feed the duration histogram too: the
            # sliding-window task-duration view (docs/OBSERVABILITY.md
            # "Live metrics & SLOs") must show the stall tail, not just
            # the tasks that resolved on their own
            telemetry.observe(telemetry.M_TASK_DURATION_S, elapsed)
            cause = resilience.DeadlineExceeded(
                f"partition {task.index} task exceeded its {timeout}s "
                f"deadline ({elapsed:.2f}s elapsed)")
            failure = TaskFailure(
                str(cause), index=task.index,
                attempts=[TaskAttempt(resilience.FATAL, repr(cause),
                                      elapsed)],
                kind=resilience.FATAL, deadline=True)
            failure.__cause__ = cause
            task.error = failure
            health.record(health.TASK_DEADLINE_EXCEEDED, partition=task.index,
                          timeout_s=timeout)
            logger.error("watchdog: %s — failing the task (its thread may "
                         "still be running the hung op)", cause)
            for fut in task.futures:
                fut.cancel()
                outstanding.pop(fut, None)

    def _maybe_hedge(self, tasks: List[_Task],
                     outstanding: Dict[_futures.Future, _Task],
                     total: int) -> None:
        cfg = self._cfg
        if not cfg.speculation:
            return
        done = len(self._durations)
        running = [t for t in tasks if not t.done and not t.hedged]
        if not running or done < 2:
            return
        if done < cfg.speculation_quantile * total:
            return
        durs = sorted(self._durations)
        q = durs[min(len(durs) - 1,
                     int(cfg.speculation_quantile * len(durs)))]
        threshold = max(q * cfg.speculation_multiplier,
                        cfg.speculation_min_runtime_s)
        now = time.monotonic()
        for task in running:
            started = task.first_started()
            if started is None or now - started < threshold:
                continue
            task.hedged = True
            outstanding[task.launch()] = task
            health.record(health.TASK_HEDGED, partition=task.index,
                          elapsed_s=round(now - started, 4),
                          threshold_s=round(threshold, 4))
            logger.info(
                "hedging straggler partition %d (%.2fs running > %.2fs "
                "threshold over %d completed siblings)", task.index,
                now - started, threshold, done)

    def _drain(self, outstanding: Dict[_futures.Future, _Task],
               include_lingering: bool) -> None:
        """Barrier before the caller unwinds: cancel what never started,
        wait out attempts already running user ops — plus, on a failure
        unwind, the remembered hedge losers. Watchdog-failed tasks'
        futures were already removed — their threads may be wedged on the
        hung op, and waiting for them would undo the deadline."""
        for fut in list(outstanding):
            if fut.cancel():
                outstanding.pop(fut, None)
        if outstanding:
            _futures.wait(list(outstanding))
            outstanding.clear()
        if include_lingering:
            live = [f for f in self._lingering if not f.done()]
            if live:
                _futures.wait(live)
            self._lingering.clear()

    # -- terminal outcome ----------------------------------------------------

    def _terminal(self, task: _Task) -> Any:
        if task.error is None:
            return task.result
        err = task.error
        # Deadline failures never quarantine: a timeout is slowness, not
        # the deterministic poison quarantine targets — dropping rows on
        # a transient straggle would be silent data loss. Both the
        # watchdog flag and the TaskFailure marker (cooperative expiry
        # between watchdog ticks) are honored.
        if (self._cfg.quarantine and self._probe is not None
                and not task.deadline_failed
                and isinstance(err, TaskFailure)
                and not err.deadline_exceeded
                and err.failure_kind == resilience.FATAL
                and sum(1 for a in err.attempts
                        if a.kind == resilience.FATAL)
                >= self._cfg.quarantine_max_fatal):
            try:
                sub = self._probe(task.index)
            except Exception as probe_err:  # noqa: BLE001 - degrade path
                logger.error(
                    "cannot quarantine partition %d (zero-row probe of the "
                    "op chain failed: %s); propagating the original "
                    "failure", task.index, probe_err)
                raise err
            health.record(health.TASK_QUARANTINED, partition=task.index,
                          error=str(err),
                          attempts=[a.kind for a in err.attempts])
            logger.error(
                "quarantining poisoned partition %d after %d fatal "
                "attempt(s): %s — dropping its rows (skip-and-degrade)",
                task.index, len(err.attempts), err)
            return sub
        raise err
