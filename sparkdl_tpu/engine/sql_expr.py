"""selectExpr expression language: tokenizer + recursive-descent parser.

The engine analog of the reference's model-as-SQL-UDF serving surface
(``spark.sql("SELECT my_udf(image) FROM ...")``, SURVEY.md §3.4). Grammar:

    select_expr := '*' | expr ('as' IDENT)?
    expr        := IDENT '(' [expr (',' expr)*] ')'   -- registered UDF call
                 | IDENT                              -- column reference
                 | NUMBER | STRING                    -- literal

UDF calls nest (``clip(featurize(image))``) and take multiple arguments
(arity-checked against the registration); literals project as constant
columns. This replaces the r1/r2 single-pattern regex the VERDICT called a
toy. Deliberately NOT supported (use the DataFrame API instead): operators,
CASE/CAST, subqueries — the reference's serving path only ever invoked
registered model UDFs over columns, which this covers.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, List, Optional, Tuple, Union

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<number>-?\d+(?:\.\d+)?)
    | (?P<string>'[^']*')
    | (?P<ident>[A-Za-z_]\w*)
    | (?P<punct>[(),*])
    )""", re.VERBOSE)


@dataclass(frozen=True)
class Column:
    name: str


@dataclass(frozen=True)
class Literal:
    value: Any


@dataclass(frozen=True)
class Call:
    fn: str
    args: Tuple[Any, ...]


@dataclass(frozen=True)
class Star:
    pass


def tokenize(text: str) -> List[Tuple[str, str]]:
    tokens = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m or m.end() == m.start():
            rest = text[pos:].strip()
            if not rest:
                break
            raise ValueError(f"Cannot tokenize {text!r} at {rest[:20]!r}")
        pos = m.end()
        for kind in ("number", "string", "ident", "punct"):
            val = m.group(kind)
            if val is not None:
                tokens.append((kind, val))
                break
    return tokens


class _Parser:
    def __init__(self, text: str) -> None:
        self.text = text
        self.tokens = tokenize(text)
        self.pos = 0

    def peek(self) -> Optional[Tuple[str, str]]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> Tuple[str, str]:
        tok = self.peek()
        if tok is None:
            raise ValueError(f"Unexpected end of expression in {self.text!r}")
        self.pos += 1
        return tok

    def expect(self, value: str) -> None:
        tok = self.next()
        if tok[1] != value:
            raise ValueError(
                f"Expected {value!r}, got {tok[1]!r} in {self.text!r}")

    def parse_select(self) -> Tuple[Union[Column, Literal, Call, Star],
                                    Optional[str]]:
        tok = self.peek()
        if tok == ("punct", "*"):
            self.next()
            self._expect_end()
            return Star(), None
        node = self.parse_expr()
        alias = None
        tok = self.peek()
        if tok is not None and tok[0] == "ident" and tok[1].lower() == "as":
            self.next()
            kind, alias = self.next()
            if kind != "ident":
                raise ValueError(f"Bad alias {alias!r} in {self.text!r}")
        self._expect_end()
        return node, alias

    def _expect_end(self) -> None:
        if self.peek() is not None:
            raise ValueError(
                f"Trailing tokens {self.tokens[self.pos:]} in {self.text!r}")

    def parse_expr(self):
        kind, val = self.next()
        if kind == "number":
            return Literal(float(val) if "." in val else int(val))
        if kind == "string":
            return Literal(val[1:-1])
        if kind == "ident":
            if self.peek() == ("punct", "("):
                self.next()
                args = []
                if self.peek() != ("punct", ")"):
                    args.append(self.parse_expr())
                    while self.peek() == ("punct", ","):
                        self.next()
                        args.append(self.parse_expr())
                self.expect(")")
                return Call(val, tuple(args))
            return Column(val)
        raise ValueError(f"Unexpected token {val!r} in {self.text!r}")


def parse(text: str):
    """Parse one select expression → (node, alias-or-None)."""
    return _Parser(text).parse_select()


def default_name(text: str) -> str:
    """Output column name for an unaliased expression: the trimmed text
    (Spark's convention for expression columns)."""
    return " ".join(text.split())
