"""selectExpr / where / sql() expression language: tokenizer + parser.

The engine analog of the reference's model-as-SQL-UDF serving surface
(``spark.sql("SELECT my_udf(image) FROM ...")``, SURVEY.md §3.4). Grammar:

    query       := 'SELECT' select_expr (',' select_expr)*
                   'FROM' IDENT ('WHERE' bool_expr)?   -- sql() over a view
    select_expr := '*' | expr ('as' IDENT)?
    expr        := IDENT '(' [expr (',' expr)*] ')'   -- registered UDF call
                 | IDENT                              -- column reference
                 | NUMBER | STRING                    -- literal
    bool_expr   := and_expr ('OR' and_expr)*          -- where()/WHERE
    and_expr    := not_expr ('AND' not_expr)*
    not_expr    := 'NOT' not_expr | '(' bool_expr ')' | cmp
    cmp         := expr (('='|'=='|'!='|'<>'|'<'|'<='|'>'|'>=') expr
                         | 'IS' ('NOT')? 'NULL')

UDF calls nest (``clip(featurize(image))``) and take multiple arguments
(arity-checked against the registration); literals project as constant
columns. Comparisons follow SQL null semantics: any comparison against
NULL is not-true, so the row is filtered out (``IS [NOT] NULL`` tests
nulls explicitly). Deliberately NOT supported (use the DataFrame API):
arithmetic, CASE/CAST, joins, subqueries, UDF calls inside WHERE — the
reference's serving path invoked registered model UDFs over columns with
simple row filters, which this covers.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple, Union

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<number>-?\d+(?:\.\d+)?)
    | (?P<string>'[^']*')
    | (?P<ident>[A-Za-z_]\w*)
    | (?P<op><=|>=|==|!=|<>|=|<|>)
    | (?P<punct>[(),*])
    )""", re.VERBOSE)


@dataclass(frozen=True)
class Column:
    name: str


@dataclass(frozen=True)
class Literal:
    value: Any


@dataclass(frozen=True)
class Call:
    fn: str
    args: Tuple[Any, ...]


@dataclass(frozen=True)
class Star:
    pass


@dataclass(frozen=True)
class Compare:
    op: str
    left: Any
    right: Any


@dataclass(frozen=True)
class BoolOp:
    kind: str  # "and" | "or"
    parts: Tuple[Any, ...]


@dataclass(frozen=True)
class Not:
    node: Any


@dataclass(frozen=True)
class IsNull:
    node: Any
    negated: bool


def _token_spans(text: str) -> List[Tuple[str, str, int, int]]:
    """(kind, value, start, end) tokens — spans let sql() slice the
    original text back out of a parsed query."""
    tokens = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m or m.end() == m.start():
            rest = text[pos:].strip()
            if not rest:
                break
            raise ValueError(f"Cannot tokenize {text!r} at {rest[:20]!r}")
        pos = m.end()
        for kind in ("number", "string", "ident", "op", "punct"):
            val = m.group(kind)
            if val is not None:
                tokens.append((kind, val, m.start(kind), m.end(kind)))
                break
    return tokens


def tokenize(text: str) -> List[Tuple[str, str]]:
    return [(kind, val) for kind, val, _, _ in _token_spans(text)]


class _Parser:
    def __init__(self, text: str) -> None:
        self.text = text
        self.tokens = tokenize(text)
        self.pos = 0

    def peek(self) -> Optional[Tuple[str, str]]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> Tuple[str, str]:
        tok = self.peek()
        if tok is None:
            raise ValueError(f"Unexpected end of expression in {self.text!r}")
        self.pos += 1
        return tok

    def expect(self, value: str) -> None:
        tok = self.next()
        if tok[1] != value:
            raise ValueError(
                f"Expected {value!r}, got {tok[1]!r} in {self.text!r}")

    def parse_select(self) -> Tuple[Union[Column, Literal, Call, Star],
                                    Optional[str]]:
        tok = self.peek()
        if tok == ("punct", "*"):
            self.next()
            self._expect_end()
            return Star(), None
        node = self.parse_expr()
        alias = None
        tok = self.peek()
        if tok is not None and tok[0] == "ident" and tok[1].lower() == "as":
            self.next()
            kind, alias = self.next()
            if kind != "ident":
                raise ValueError(f"Bad alias {alias!r} in {self.text!r}")
        self._expect_end()
        return node, alias

    def _expect_end(self) -> None:
        if self.peek() is not None:
            raise ValueError(
                f"Trailing tokens {self.tokens[self.pos:]} in {self.text!r}")

    def parse_expr(self):
        kind, val = self.next()
        if kind == "number":
            return Literal(float(val) if "." in val else int(val))
        if kind == "string":
            return Literal(val[1:-1])
        if kind == "ident":
            if self.peek() == ("punct", "("):
                self.next()
                args = []
                if self.peek() != ("punct", ")"):
                    args.append(self.parse_expr())
                    while self.peek() == ("punct", ","):
                        self.next()
                        args.append(self.parse_expr())
                self.expect(")")
                return Call(val, tuple(args))
            return Column(val)
        raise ValueError(f"Unexpected token {val!r} in {self.text!r}")

    # -- boolean expressions (where/WHERE) -----------------------------------

    def _peek_kw(self, word: str) -> bool:
        tok = self.peek()
        return (tok is not None and tok[0] == "ident"
                and tok[1].lower() == word)

    def parse_bool(self):
        parts = [self.parse_and()]
        while self._peek_kw("or"):
            self.next()
            parts.append(self.parse_and())
        return parts[0] if len(parts) == 1 else BoolOp("or", tuple(parts))

    def parse_and(self):
        parts = [self.parse_not()]
        while self._peek_kw("and"):
            self.next()
            parts.append(self.parse_not())
        return parts[0] if len(parts) == 1 else BoolOp("and", tuple(parts))

    def parse_not(self):
        if self._peek_kw("not"):
            self.next()
            return Not(self.parse_not())
        if self.peek() == ("punct", "("):
            # grouped boolean — a UDF call's '(' is consumed by parse_expr
            # inside parse_cmp, so a leading '(' here is always a group
            self.next()
            node = self.parse_bool()
            self.expect(")")
            return node
        return self.parse_cmp()

    def parse_cmp(self):
        left = self.parse_expr()
        if isinstance(left, Call):
            raise ValueError(
                f"UDF calls are not supported in WHERE ({self.text!r}); "
                "materialize the column with selectExpr first")
        if self._peek_kw("is"):
            self.next()
            negated = False
            if self._peek_kw("not"):
                self.next()
                negated = True
            tok = self.next()
            if tok[0] != "ident" or tok[1].lower() != "null":
                raise ValueError(f"Expected NULL after IS in {self.text!r}")
            return IsNull(left, negated)
        tok = self.peek()
        if tok is None or tok[0] != "op":
            raise ValueError(
                f"Expected a comparison operator in {self.text!r}, got "
                f"{tok!r}")
        op = self.next()[1]
        right = self.parse_expr()
        if isinstance(right, Call):
            raise ValueError(
                f"UDF calls are not supported in WHERE ({self.text!r}); "
                "materialize the column with selectExpr first")
        return Compare({"==": "=", "<>": "!="}.get(op, op), left, right)


def parse(text: str):
    """Parse one select expression → (node, alias-or-None)."""
    return _Parser(text).parse_select()


def parse_bool(text: str):
    """Parse a where/WHERE boolean expression → AST node."""
    parser = _Parser(text)
    node = parser.parse_bool()
    parser._expect_end()
    return node


def bool_columns(node) -> List[str]:
    """Column names referenced by a boolean AST (sorted, unique)."""
    out = set()

    def walk(n):
        if isinstance(n, Column):
            out.add(n.name)
        elif isinstance(n, Compare):
            walk(n.left)
            walk(n.right)
        elif isinstance(n, BoolOp):
            for p in n.parts:
                walk(p)
        elif isinstance(n, Not):
            walk(n.node)
        elif isinstance(n, IsNull):
            walk(n.node)

    walk(node)
    return sorted(out)


_CMP = {
    "=": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def eval_bool(node, env: Dict[str, Any]) -> Optional[bool]:
    """Evaluate a boolean AST over one row's {column: value}.

    SQL three-valued logic on comparisons: NULL operands make the
    comparison None (not-true). AND/OR short-circuit treating None like
    SQL UNKNOWN (None AND False = False, None OR True = True, else None).
    """
    if isinstance(node, IsNull):
        value = _eval_value(node.node, env)
        return (value is not None) if node.negated else (value is None)
    if isinstance(node, Not):
        inner = eval_bool(node.node, env)
        return None if inner is None else not inner
    if isinstance(node, BoolOp):
        # Genuinely short-circuit: stop at the first deciding operand
        # (False for AND, True for OR) without evaluating the rest — SQL
        # UNKNOWN (None) cannot flip a decided AND/OR, so skipping the
        # remaining operands is semantics-preserving and a per-row win.
        saw_unknown = False
        if node.kind == "and":
            for p in node.parts:
                v = eval_bool(p, env)
                if v is False:
                    return False
                if v is None:
                    saw_unknown = True
            return None if saw_unknown else True
        for p in node.parts:
            v = eval_bool(p, env)
            if v is True:
                return True
            if v is None:
                saw_unknown = True
        return None if saw_unknown else False
    if isinstance(node, Compare):
        left = _eval_value(node.left, env)
        right = _eval_value(node.right, env)
        if left is None or right is None:
            return None
        return bool(_CMP[node.op](left, right))
    raise ValueError(f"Cannot evaluate {node!r} as a boolean")


def _eval_value(node, env: Dict[str, Any]):
    if isinstance(node, Column):
        return env[node.name]
    if isinstance(node, Literal):
        return node.value
    raise ValueError(f"Cannot evaluate {node!r} in WHERE")


def split_query(text: str) -> Dict[str, Any]:
    """Split ``SELECT ... FROM view [WHERE ...]`` into its parts.

    Returns {"select": [expr_text, ...], "view": name,
    "where": text-or-None}; expression texts slice out of the original
    query (spans), so selectExpr/parse_bool re-parse them unchanged.
    Keywords match case-insensitively at paren depth 0 only — a UDF
    named ``from_x(...)`` or a quoted 'where' never splits the query.
    """
    toks = _token_spans(text)
    if not toks or toks[0][0] != "ident" or toks[0][1].lower() != "select":
        raise ValueError(f"sql() query must start with SELECT: {text!r}")
    depth = 0
    from_i = where_i = None
    commas: List[int] = []
    for i, (kind, val, _s, _e) in enumerate(toks):
        if kind == "punct" and val == "(":
            depth += 1
        elif kind == "punct" and val == ")":
            depth -= 1
        elif depth == 0 and kind == "ident":
            word = val.lower()
            if word == "from" and from_i is None:
                from_i = i
            elif word == "where" and from_i is not None and where_i is None:
                where_i = i
        elif depth == 0 and kind == "punct" and val == "," and from_i is None:
            commas.append(i)
    if from_i is None:
        raise ValueError(f"sql() query needs FROM <view>: {text!r}")
    view_at = from_i + 1
    if view_at >= len(toks) or toks[view_at][0] != "ident":
        raise ValueError(f"FROM must name a view in {text!r}")
    view = toks[view_at][1]
    after_view = view_at + 1
    expected_next = where_i if where_i is not None else len(toks)
    if after_view != expected_next:
        raise ValueError(
            f"Unexpected tokens after FROM {view} in {text!r} (joins/"
            "aliases are not supported)")
    # select list: token spans between SELECT and FROM, split on commas
    bounds = [toks[0][3]] + [toks[i][2] for i in commas] \
        + [toks[from_i][2]]
    starts = [toks[0][3]] + [toks[i][3] for i in commas]
    select = [text[s:e].strip() for s, e in zip(starts, bounds[1:])]
    if not all(select):
        raise ValueError(f"Empty select expression in {text!r}")
    where = None
    if where_i is not None:
        if where_i + 1 >= len(toks):
            raise ValueError(f"WHERE needs a condition in {text!r}")
        where = text[toks[where_i][3]:].strip()
    return {"select": select, "view": view, "where": where}


def default_name(text: str) -> str:
    """Output column name for an unaliased expression: the trimmed text
    (Spark's convention for expression columns)."""
    return " ".join(text.split())
