"""Columnar execution engine: partitioned DataFrame, UDF registry, SQL shim.

Replaces the reference's Spark JVM data plane (SURVEY.md §1 L1, §2.3) with
an Arrow-native engine sized to this framework's workloads.
"""

from sparkdl_tpu.engine.dataframe import (
    DataFrame,
    EngineConfig,
    TaskFailure,
    sql,
    table,
)
from sparkdl_tpu.engine.supervisor import (
    PartitionSupervisor,
    SupervisorConfig,
    TaskAttempt,
)

__all__ = ["DataFrame", "EngineConfig", "TaskFailure", "TaskAttempt",
           "PartitionSupervisor", "SupervisorConfig", "sql", "table"]
