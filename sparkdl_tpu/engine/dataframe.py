"""Partitioned, columnar DataFrame over Arrow record batches.

This is the rebuild's replacement for the reference's L1 JVM data plane
(Spark core/SQL + TensorFrames; SURVEY.md §1, §2.3). Design points, chosen
for the TPU data path rather than translated from Spark:

- **Columnar storage**: each partition is a ``pyarrow.RecordBatch``; image
  bytes stay contiguous so host staging before ``device_put`` is zero-copy.
- **Lazy plans**: transformations append ops to a plan; ``collect`` /
  ``toArrow`` / transformer execution materialize partition-by-partition in
  one pass (op fusion per partition, like Spark's pipelined narrow stages).
- **Partition-parallel execution with supervision**: a thread pool maps
  partitions under task-level supervision (``engine/supervisor.py``) — the
  engine analog of Spark task retry/speculation (SURVEY.md §5.3):
  failures are classified through ``core.resilience`` (FATAL never
  retried, RETRYABLE backed off, OOM surfaced), hung tasks fail via a
  deadline watchdog, stragglers can be speculatively hedged, and poisoned
  partitions can be quarantined. Ops must be pure/idempotent, which every
  op built by this framework is.
- **No JVM, no shuffle**: the workloads this framework serves (per-row model
  application, featurize, fit) are narrow; wide shuffles are out of scope,
  matching the reference's actual usage of Spark.
"""

from __future__ import annotations

import concurrent.futures as _futures
import os
import threading
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple, Union)

import numpy as np
import pandas as pd
import pyarrow as pa

from sparkdl_tpu.core import durability, resilience, telemetry
from sparkdl_tpu.engine import supervisor as _sup
from sparkdl_tpu.engine.supervisor import (  # noqa: F401 - re-exported API
    PartitionSupervisor,
    SupervisorConfig,
    TaskAttempt,
    TaskFailure,
)


class EngineConfig:
    """Engine-wide knobs (no globals beyond this explicit, test-overridable one)."""

    # -- task retry (engine/supervisor.run_partition_task) -------------------
    max_task_retries: int = 2
    # Backoff between retryable attempts; 0 keeps the historical
    # retry-immediately behavior. task_retry_policy overrides both.
    task_retry_delay_s: float = 0.0
    task_retry_policy: Optional[resilience.RetryPolicy] = None
    # -- deadline watchdog ----------------------------------------------------
    # Per-task wall-clock budget (seconds); None disables. Enforced
    # cooperatively inside the task and preemptively by the supervisor's
    # watchdog, so a hung op fails the task instead of wedging the run.
    task_timeout_s: Optional[float] = None
    # -- speculative execution (straggler hedging) ----------------------------
    # Off by default (Spark's spark.speculation default): hedging re-runs
    # ops, which must be pure — results are identical, but op side effects
    # (counters in tests) would double.
    speculation: bool = False
    speculation_quantile: float = 0.75
    speculation_multiplier: float = 1.5
    speculation_min_runtime_s: float = 0.05
    # -- quarantine (opt-in skip-and-degrade) ---------------------------------
    # Drop a partition that fails FATALLY (after quarantine_max_fatal
    # classified-fatal attempts) instead of failing the job: a zero-row
    # batch with the op chain's output schema stands in, and the drop is
    # recorded in the active HealthMonitor.
    quarantine: bool = False
    quarantine_max_fatal: int = 1
    # -- cross-partition dynamic batch coalescing (core/executor.py) ----------
    # The inference data plane's device execution service: concurrent
    # partition tasks submitting small chunks against the same compiled fn
    # are coalesced into one padded bucket-ladder launch (docs/PERF.md
    # "Cross-partition coalescing"). Default ON for inference; the
    # training path (Trainer.fit) never routes through the service. A solo
    # request under no contention takes the inline path unchanged.
    coalesce: bool = True
    # Bounded wait (milliseconds) for sibling requests before launching;
    # None = adaptive (a fraction of the observed request latency).
    coalesce_window_ms: Optional[float] = None
    # Row cap of one coalesced launch; None = the request's batch_size.
    coalesce_max_rows: Optional[int] = None
    # -- raw-speed inference (docs/PERF.md "Launch shaping & precision") ------
    # Numeric width of the featurize/transform path, applied at the
    # executor choke point via ModelFunction.with_dtype. "bfloat16"
    # (default): bf16 compute, outputs cast back to float32 (per-element
    # tolerance contract in docs/PERF.md); "float32": the one-knob escape
    # hatch, bit-identical to the pre-knob behavior; "int8": weight-only
    # symmetric per-channel post-training quantization, bf16 activations.
    inference_precision: str = "bfloat16"
    # Donate each staged input batch to its launch so XLA reuses the
    # input's HBM for the outputs — peak memory drops by ~one batch,
    # which is direct headroom for the executor_max_queued_rows shed
    # thresholds above.
    inference_donate_buffers: bool = True
    # Tail-bucket ladder: "tuned" (default) arms the per-model
    # telemetry-tuned BucketPlanner (core/batching.py) — identical to
    # the blind ladder until enough launches are observed, then rungs
    # move to the observed size distribution; "pow2" restores the blind
    # power-of-two ladder everywhere.
    bucket_ladder: str = "tuned"
    # -- executor overload protection (core/executor.py, docs/RESILIENCE.md
    # "Overload & graceful degradation") ---------------------------------------
    # Admission control: per-compiled-fn bounds on queued requests / queued
    # rows. None (default) = unbounded — today's behavior.
    executor_max_queued_requests: Optional[int] = None
    executor_max_queued_rows: Optional[int] = None
    # Over the bound: "block" (default) waits with backpressure, bounded by
    # the caller's task deadline; "shed" fails fast with ExecutorOverloaded
    # (classified RETRYABLE — the engine task retry absorbs the spike).
    executor_overload_mode: str = "block"
    # Priority lane for requests that don't say ("interactive" > "bulk"):
    # interactive drains first and sheds last. Transformers override per
    # instance via their `priority` param.
    executor_default_priority: str = "bulk"
    # Per-model circuit breaker: trip open after this many terminal launch
    # failures within executor_breaker_window_s; fail fast for
    # executor_breaker_cooldown_s, then admit one half-open probe. 0
    # (default) disables the breaker entirely.
    executor_breaker_threshold: int = 0
    executor_breaker_window_s: float = 30.0
    executor_breaker_cooldown_s: float = 1.0
    # Idle coalescing-state retirement: a model's compiled-fn state (and
    # the strong reference pinning its weights) is dropped after this
    # many seconds without a request. The serving residency manager
    # (sparkdl_tpu/serving/residency.py) and tests lower it to make
    # eviction prompt; 5 s is the historical hard-coded value.
    executor_idle_retire_s: float = 5.0
    # -- parallel host decode pool (core/decode_pool.py, docs/PERF.md
    # "Parallel host ingest") --------------------------------------------------
    # Spawn-context worker PROCESSES for the image-decode fan-out (JPEG
    # decode on the PIL fallback is GIL-bound, so the partition thread
    # pool cannot parallelize it). 0 (default) keeps today's inline
    # decode, bit-identical; N > 0 shares one process-wide pool across
    # every ingest path (readImages, loadImagesInternal, streaming fit).
    decode_workers: int = 0
    # Max in-flight decode chunks pool-wide (backpressure bound on host
    # memory for decoded-but-unconsumed pixels); None = 2 * decode_workers.
    decode_pool_inflight: Optional[int] = None
    # -- zero-copy columnar image plane (image/imageIO.py, docs/PERF.md
    # "Columnar data plane") ---------------------------------------------------
    # Build image-struct columns COLUMNAR: a uniform decoded batch packs
    # into ONE contiguous values buffer wrapped zero-copy as the Arrow
    # column's binary child (imageIO.imageArraysToStructColumn — no
    # per-row dict, no per-row tobytes), which arrowImageBatch views
    # back as one NHWC batch downstream, again without copying. The
    # column's logical values are identical to the per-row builder's;
    # ragged batches fall back to it, and False restores it everywhere.
    columnar_images: bool = True
    # Fuse resize into the device program: the uniform fast path ships
    # raw HWC uint8 at SOURCE size and the compiled fn runs cast →
    # resize → normalize → forward as one XLA program
    # (ModelFunction.resized; composes with inference_precision and
    # donation at the executor choke point). False restores the
    # measured r3 host-resize downscale policy
    # (ml/image_transformer._resize_uniform_batch).
    fused_preprocess: bool = True
    # -- durable job recovery (core/durability.py, docs/RESILIENCE.md
    # "Durable recovery") ------------------------------------------------------
    # Root directory for write-ahead partition journals + atomic spills.
    # None (default) = no durability: every path is byte- and
    # behavior-identical to before the knob existed. Set, each
    # materialize/streamPartitions job derives a stable job id (hash of
    # plan + config) under this root and survives kill -9: on restart
    # committed partitions load from verified spill, only uncommitted
    # ones recompute, and rows re-emit in original order.
    durable_dir: Optional[str] = None
    # -- cluster inference plane (sparkdl_tpu/cluster/, docs/DISTRIBUTED.md
    # "Cluster inference") -----------------------------------------------------
    # Spawn-context worker PROCESSES, each hosting a full per-process
    # inference stack (own device runtime, DeviceExecutor + compiled-fn
    # cache, telemetry pinned to the coordinator's run id); supervised
    # materialize/stream partitions route to the least-loaded worker,
    # with retry/hedging/quarantine/deadlines preserved coordinator-side.
    # 0 (default) keeps today's in-process path byte-identical — the
    # cluster package is never even imported.
    cluster_workers: int = 0
    # Max in-flight partition dispatches router-wide (backpressure bound
    # on coordinator memory for shipped-but-unconsumed partitions);
    # None = 2 * cluster_workers.
    cluster_inflight_partitions: Optional[int] = None
    # -- elastic capacity (cluster autoscaler + graceful drain,
    # docs/DISTRIBUTED.md "Elastic capacity") ----------------------------------
    # Arm the router's autoscaler: grow/shrink the live worker set
    # between cluster_min_workers and cluster_max_workers from windowed
    # queue-wait p99 and outstanding rows per worker. False (default)
    # keeps the worker set exactly cluster_workers — byte-identical to
    # before the knob existed. Always forced off INSIDE workers.
    cluster_autoscale: bool = False
    cluster_min_workers: int = 1
    cluster_max_workers: int = 8
    # Telemetry window the scaling signals are computed over, and the
    # minimum quiet period between two scaling actions (cooldown — paired
    # with the high/low hysteresis gap below so the set never flaps).
    autoscale_window_s: float = 5.0
    autoscale_cooldown_s: float = 5.0
    # Scale UP when windowed queue-wait p99 exceeds the high-water mark
    # (or rows-in-flight per worker exceed theirs); scale DOWN only when
    # p99 is below the much lower low-water mark AND a worker sits idle.
    autoscale_queue_wait_high_s: float = 0.5
    autoscale_queue_wait_low_s: float = 0.05
    autoscale_rows_per_worker_high: int = 4096
    # -- live metrics federation (cluster/aggregate.ClusterMetricsView,
    # docs/OBSERVABILITY.md "Cluster metrics federation") ----------------------
    # Cadence (seconds) at which each cluster worker ships a bounded
    # windowed-metrics frame over its result pipe; the coordinator folds
    # the frames into a live cluster-wide view (merged percentiles,
    # summed rates) that the federated SLO watchdog, the autoscaler, and
    # the exporter read mid-run. None (default) disables federation —
    # no frames ship, no view exists, all artifacts byte-identical.
    # NOT forced off inside workers: the worker loop reads this knob to
    # drive its frame cadence.
    cluster_federation_s: Optional[float] = None
    # -- cluster serving plane (sparkdl_tpu/serving/cluster.py,
    # docs/SERVING.md "Cluster serving") ---------------------------------------
    # Route ModelServer.predict through the cluster router: deployments
    # replicate across the cluster workers, requests route with
    # load/locality awareness, worker death re-admits in-flight predicts
    # to survivors within the caller's deadline, and hot-swap becomes a
    # cluster-atomic two-phase cutover. Requires cluster_workers > 0;
    # False (default) keeps the single-process serving path
    # byte-identical — serving/cluster.py is never even imported.
    # Always forced off INSIDE workers (a replica must not recurse).
    serving_cluster: bool = False
    # Per-worker HBM residency budget for replicated deployments; None
    # gives each worker-side registry an unbudgeted cache (models stay
    # resident until retired).
    serving_worker_residency_bytes: Optional[int] = None
    # How many times one in-flight predict may be re-admitted after
    # replica deaths before failing with ServingReplicaLost (the
    # caller's deadline bounds it anyway; this bounds pathological
    # rolling-death churn).
    serving_failover_max: int = 2
    # -- Pallas fused kernels (core/kernels.py, docs/PERF.md "Fused
    # kernels & AOT warmup") ----------------------------------------------------
    # "autotune" (default): fused Pallas kernels are auditioned per
    # (kernel, model-family, bucket-shape, dtype) against their XLA
    # twins at first compile and adopted only on a >= 5% win within the
    # numeric contract (fp32 exact, bf16 <= 0.05) — a losing kernel
    # never ships, verdicts persist beside the compile cache. "force"
    # routes every feasible site unconditionally (tests/benchmarks).
    # "off": byte-identical XLA programs, core/kernels.py never
    # imported (subprocess-pinned like the cluster/serving packages).
    pallas_kernels: str = "autotune"
    # AOT-compile a deployment's full bucket ladder (running its kernel
    # shootouts) at deploy/prepare time so the first request pays zero
    # compile: wired into ModelRegistry.deploy, ResidencyManager cold
    # loads, and the cluster srv_prepare phase (a replica acks prepared
    # only after its ladder is warm). False (default) keeps today's
    # lazy first-request compile.
    serving_warmup: bool = False
    # -- per-tenant fair queueing (core/executor.py, docs/RESILIENCE.md
    # "Tenant fairness") --------------------------------------------------------
    # Relative deficit-round-robin weights per tenant tag; tenants absent
    # from the dict (and all tenants when None) get weight 1. A tenant
    # with weight 2 drains twice the rows per round of a weight-1 tenant
    # when both have queued work — a flooding tenant saturates only its
    # share.
    executor_tenant_weights: Optional[Dict[str, int]] = None
    # Tenant tag assigned to requests that don't carry one (explicit
    # execute(tenant=...) > ambient executor.tenant_scope > this).
    executor_default_tenant: str = "default"
    # Tenant tag stamped on this job's PARTITION dispatches (engine
    # materialize/stream through the cluster router); None leaves
    # partition work on the default tenant.
    job_tenant: Optional[str] = None
    max_workers: int = max(2, (os.cpu_count() or 4) // 2)
    # DEPRECATED test hook (SURVEY.md §5.3 fault injection):
    # callable(partition_index, attempt) that may raise to simulate a task
    # failure. Kept as a compat shim — new code arms the unified
    # resilience.FaultInjector "engine_task" / "task_stall" points, which
    # share the injector's seeding story.
    fault_injector: Optional[Callable[[int, int], None]] = None

    @classmethod
    def snapshot(cls) -> Dict[str, Any]:
        """Every public knob's current value — the ONE save/restore idiom
        for fixtures and bench legs that mutate the class-wide config
        (new knobs are covered without listing them). Callable knob
        values (a set ``fault_injector``) are deliberately excluded, as
        are the classmethods themselves."""
        return {k: getattr(cls, k) for k in vars(cls)
                if not k.startswith("_") and not callable(getattr(cls, k))}

    @classmethod
    def restore(cls, saved: Dict[str, Any]) -> None:
        """Reapply a :meth:`snapshot`."""
        for k, v in saved.items():
            setattr(cls, k, v)

    # last-validated knob values: validate() is called per device entry,
    # so an unchanged config must cost one tuple build + compare, not the
    # full check battery. Underscore-prefixed: excluded from the test
    # fixtures' public-knob snapshots.
    _validated_knobs: Optional[tuple] = None

    @classmethod
    def validate(cls) -> None:
        """Validate every knob at READ time with a clear ``ValueError``
        (instead of undefined downstream behavior: a negative timeout
        silently expiring every task, a zero queue cap wedging admission,
        an out-of-range quantile never hedging). Called by the knob
        consumers — ``_supervisor_config`` per materialization and
        ``core.executor.execute`` per device entry; memoized on the knob
        values, so the per-entry cost of a steady config is one tuple
        compare."""
        knobs = (cls.max_task_retries, cls.task_retry_delay_s,
                 cls.task_timeout_s, cls.speculation_quantile,
                 cls.speculation_multiplier, cls.speculation_min_runtime_s,
                 cls.quarantine_max_fatal, cls.coalesce_window_ms,
                 cls.coalesce_max_rows, cls.inference_precision,
                 cls.inference_donate_buffers, cls.bucket_ladder,
                 cls.executor_max_queued_requests,
                 cls.executor_max_queued_rows, cls.executor_overload_mode,
                 cls.executor_default_priority,
                 cls.executor_breaker_threshold,
                 cls.executor_breaker_window_s,
                 cls.executor_breaker_cooldown_s,
                 cls.executor_idle_retire_s, cls.decode_workers,
                 cls.decode_pool_inflight, cls.columnar_images,
                 cls.fused_preprocess, cls.cluster_workers,
                 cls.cluster_inflight_partitions, cls.cluster_autoscale,
                 cls.cluster_min_workers, cls.cluster_max_workers,
                 cls.autoscale_window_s, cls.autoscale_cooldown_s,
                 cls.autoscale_queue_wait_high_s,
                 cls.autoscale_queue_wait_low_s,
                 cls.autoscale_rows_per_worker_high,
                 cls.cluster_federation_s,
                 cls.serving_cluster, cls.serving_worker_residency_bytes,
                 cls.serving_failover_max, cls.pallas_kernels,
                 cls.serving_warmup,
                 (None if cls.executor_tenant_weights is None
                  else tuple(sorted(cls.executor_tenant_weights.items()))),
                 cls.executor_default_tenant, cls.job_tenant,
                 cls.durable_dir, cls.max_workers)
        if knobs == cls._validated_knobs:
            return

        def positive(name, value, allow_none=True, minimum=0.0,
                     exclusive=True):
            if value is None:
                if not allow_none:
                    raise ValueError(f"EngineConfig.{name} must be set")
                return
            bad = value <= minimum if exclusive else value < minimum
            if bad:
                op = ">" if exclusive else ">="
                raise ValueError(
                    f"EngineConfig.{name} must be {op} {minimum} (or "
                    f"None), got {value!r}")

        if cls.max_task_retries < 0:
            raise ValueError("EngineConfig.max_task_retries must be >= 0, "
                             f"got {cls.max_task_retries!r}")
        positive("task_retry_delay_s", cls.task_retry_delay_s,
                 exclusive=False)
        positive("task_timeout_s", cls.task_timeout_s)
        if not 0.0 <= cls.speculation_quantile <= 1.0:
            raise ValueError(
                "EngineConfig.speculation_quantile must be in [0, 1], "
                f"got {cls.speculation_quantile!r}")
        positive("speculation_multiplier", cls.speculation_multiplier)
        positive("speculation_min_runtime_s", cls.speculation_min_runtime_s,
                 exclusive=False)
        if cls.quarantine_max_fatal < 1:
            raise ValueError(
                "EngineConfig.quarantine_max_fatal must be >= 1, got "
                f"{cls.quarantine_max_fatal!r}")
        positive("coalesce_window_ms", cls.coalesce_window_ms,
                 exclusive=False)
        positive("coalesce_max_rows", cls.coalesce_max_rows)
        if cls.inference_precision not in ("float32", "bfloat16", "int8"):
            raise ValueError(
                "EngineConfig.inference_precision must be 'float32', "
                "'bfloat16' or 'int8', got "
                f"{cls.inference_precision!r}")
        if not isinstance(cls.inference_donate_buffers, bool):
            raise ValueError(
                "EngineConfig.inference_donate_buffers must be a bool, "
                f"got {cls.inference_donate_buffers!r}")
        if cls.bucket_ladder not in ("tuned", "pow2"):
            raise ValueError(
                "EngineConfig.bucket_ladder must be 'tuned' or 'pow2', "
                f"got {cls.bucket_ladder!r}")
        positive("executor_max_queued_requests",
                 cls.executor_max_queued_requests)
        positive("executor_max_queued_rows", cls.executor_max_queued_rows)
        if cls.executor_overload_mode not in ("block", "shed"):
            raise ValueError(
                "EngineConfig.executor_overload_mode must be 'block' or "
                f"'shed', got {cls.executor_overload_mode!r}")
        if cls.executor_default_priority not in ("interactive", "bulk"):
            raise ValueError(
                "EngineConfig.executor_default_priority must be "
                "'interactive' or 'bulk', got "
                f"{cls.executor_default_priority!r}")
        if cls.executor_breaker_threshold < 0:
            raise ValueError(
                "EngineConfig.executor_breaker_threshold must be >= 0 "
                f"(0 disables), got {cls.executor_breaker_threshold!r}")
        positive("executor_breaker_window_s", cls.executor_breaker_window_s)
        positive("executor_breaker_cooldown_s",
                 cls.executor_breaker_cooldown_s, exclusive=False)
        positive("executor_idle_retire_s", cls.executor_idle_retire_s,
                 allow_none=False)
        if cls.decode_workers < 0:
            raise ValueError(
                "EngineConfig.decode_workers must be >= 0 (0 disables "
                f"the decode pool), got {cls.decode_workers!r}")
        positive("decode_pool_inflight", cls.decode_pool_inflight)
        if not isinstance(cls.columnar_images, bool):
            raise ValueError(
                "EngineConfig.columnar_images must be a bool, got "
                f"{cls.columnar_images!r}")
        if not isinstance(cls.fused_preprocess, bool):
            raise ValueError(
                "EngineConfig.fused_preprocess must be a bool, got "
                f"{cls.fused_preprocess!r}")
        if cls.cluster_workers < 0:
            raise ValueError(
                "EngineConfig.cluster_workers must be >= 0 (0 disables "
                f"the cluster plane), got {cls.cluster_workers!r}")
        positive("cluster_inflight_partitions",
                 cls.cluster_inflight_partitions)
        if not isinstance(cls.cluster_autoscale, bool):
            raise ValueError(
                "EngineConfig.cluster_autoscale must be a bool, got "
                f"{cls.cluster_autoscale!r}")
        if cls.cluster_min_workers < 1:
            raise ValueError(
                "EngineConfig.cluster_min_workers must be >= 1, got "
                f"{cls.cluster_min_workers!r}")
        if cls.cluster_max_workers < cls.cluster_min_workers:
            raise ValueError(
                "EngineConfig.cluster_max_workers must be >= "
                f"cluster_min_workers ({cls.cluster_min_workers}), got "
                f"{cls.cluster_max_workers!r}")
        positive("autoscale_window_s", cls.autoscale_window_s,
                 allow_none=False)
        positive("autoscale_cooldown_s", cls.autoscale_cooldown_s,
                 allow_none=False, exclusive=False)
        positive("autoscale_queue_wait_high_s",
                 cls.autoscale_queue_wait_high_s, allow_none=False)
        positive("autoscale_queue_wait_low_s",
                 cls.autoscale_queue_wait_low_s, allow_none=False)
        if cls.autoscale_queue_wait_low_s >= cls.autoscale_queue_wait_high_s:
            raise ValueError(
                "EngineConfig.autoscale_queue_wait_low_s must be < "
                "autoscale_queue_wait_high_s "
                f"({cls.autoscale_queue_wait_high_s}), got "
                f"{cls.autoscale_queue_wait_low_s!r} — the hysteresis "
                "gap is what keeps the worker set from flapping")
        if cls.autoscale_rows_per_worker_high < 1:
            raise ValueError(
                "EngineConfig.autoscale_rows_per_worker_high must be "
                f">= 1, got {cls.autoscale_rows_per_worker_high!r}")
        positive("cluster_federation_s", cls.cluster_federation_s)
        if not isinstance(cls.serving_cluster, bool):
            raise ValueError(
                "EngineConfig.serving_cluster must be a bool, got "
                f"{cls.serving_cluster!r}")
        positive("serving_worker_residency_bytes",
                 cls.serving_worker_residency_bytes)
        if cls.serving_failover_max < 0:
            raise ValueError(
                "EngineConfig.serving_failover_max must be >= 0 (0 "
                "fails a moved request on first replica death), got "
                f"{cls.serving_failover_max!r}")
        if cls.pallas_kernels not in ("off", "autotune", "force"):
            raise ValueError(
                "EngineConfig.pallas_kernels must be 'off', 'autotune' "
                f"or 'force', got {cls.pallas_kernels!r}")
        if not isinstance(cls.serving_warmup, bool):
            raise ValueError(
                "EngineConfig.serving_warmup must be a bool, got "
                f"{cls.serving_warmup!r}")
        if cls.executor_tenant_weights is not None:
            if not isinstance(cls.executor_tenant_weights, dict):
                raise ValueError(
                    "EngineConfig.executor_tenant_weights must be None "
                    "or a dict of tenant -> positive int weight, got "
                    f"{cls.executor_tenant_weights!r}")
            for t, w in cls.executor_tenant_weights.items():
                if not isinstance(t, str) or not t:
                    raise ValueError(
                        "EngineConfig.executor_tenant_weights keys must "
                        f"be non-empty tenant strings, got {t!r}")
                if not isinstance(w, int) or isinstance(w, bool) or w < 1:
                    raise ValueError(
                        "EngineConfig.executor_tenant_weights values "
                        f"must be positive ints, got {t!r}={w!r}")
        if (not isinstance(cls.executor_default_tenant, str)
                or not cls.executor_default_tenant):
            raise ValueError(
                "EngineConfig.executor_default_tenant must be a "
                f"non-empty string, got {cls.executor_default_tenant!r}")
        if cls.job_tenant is not None and (
                not isinstance(cls.job_tenant, str) or not cls.job_tenant):
            raise ValueError(
                "EngineConfig.job_tenant must be None or a non-empty "
                f"tenant string, got {cls.job_tenant!r}")
        if cls.durable_dir is not None and (
                not isinstance(cls.durable_dir, str) or not cls.durable_dir):
            raise ValueError(
                "EngineConfig.durable_dir must be None or a non-empty "
                f"directory path, got {cls.durable_dir!r}")
        if cls.max_workers < 1:
            raise ValueError("EngineConfig.max_workers must be >= 1, got "
                             f"{cls.max_workers!r}")
        cls._validated_knobs = knobs


def _task_policy() -> resilience.RetryPolicy:
    if EngineConfig.task_retry_policy is not None:
        return EngineConfig.task_retry_policy
    return resilience.RetryPolicy(
        max_retries=EngineConfig.max_task_retries,
        base_delay_s=EngineConfig.task_retry_delay_s, jitter=0.0)


def _supervisor_config() -> SupervisorConfig:
    EngineConfig.validate()  # read-time knob validation
    return SupervisorConfig(
        task_timeout_s=EngineConfig.task_timeout_s,
        speculation=EngineConfig.speculation,
        speculation_quantile=EngineConfig.speculation_quantile,
        speculation_multiplier=EngineConfig.speculation_multiplier,
        speculation_min_runtime_s=EngineConfig.speculation_min_runtime_s,
        quarantine=EngineConfig.quarantine,
        quarantine_max_fatal=EngineConfig.quarantine_max_fatal)


# Process-wide partition executor, reused across materializations (VERDICT
# r2 weak #7: a fresh ThreadPoolExecutor per materialize). Rebuilt if
# EngineConfig.max_workers changes (test hook).
_pool: Optional[_futures.ThreadPoolExecutor] = None
_pool_workers: Optional[int] = None
_pool_lock = threading.Lock()


def _executor() -> _futures.ThreadPoolExecutor:
    global _pool, _pool_workers
    with _pool_lock:
        if _pool is None or _pool_workers != EngineConfig.max_workers:
            if _pool is not None:
                _pool.shutdown(wait=False)
            _pool = _futures.ThreadPoolExecutor(
                EngineConfig.max_workers,
                thread_name_prefix="sparkdl-part")
            _pool_workers = EngineConfig.max_workers
        return _pool


def _run_partition(index: int, batch: pa.RecordBatch,
                   ops: Sequence[Callable[[pa.RecordBatch], pa.RecordBatch]],
                   cancelled: Optional[threading.Event] = None
                   ) -> pa.RecordBatch:
    """One partition task: classified retry per engine/supervisor.py
    (FATAL never retried, OOM surfaced, RETRYABLE backed off; terminal
    TaskFailure carries the per-attempt history). ``cancelled`` is the
    supervisor watchdog's abandonment signal (None on inline paths)."""
    out = _sup.run_partition_task(
        index, batch, ops, policy=_task_policy(),
        deadline_s=EngineConfig.task_timeout_s,
        legacy_injector=EngineConfig.fault_injector,
        max_fatal_attempts=(EngineConfig.quarantine_max_fatal
                            if EngineConfig.quarantine else 1),
        cancelled=cancelled)
    if cancelled is None and telemetry.active() is not None:
        # inline (unsupervised) execution paths only — supervised tasks
        # are counted once per WINNING attempt by the supervisor's
        # resolve (a hedge loser running to completion must not
        # double-count the partition's rows)
        telemetry.count(telemetry.M_ENGINE_ROWS_OUT, out.num_rows)
        telemetry.count(telemetry.M_ENGINE_BYTES_OUT, out.nbytes)
    return out


def _cluster_dispatch() -> Callable[..., pa.RecordBatch]:
    """The partition runner for the supervised paths: in-process
    ``_run_partition`` at the default ``cluster_workers=0`` (the cluster
    package is never even imported — the byte-identity gate), or the
    process-wide :meth:`ClusterRouter.run_partition` drop-in when the
    cluster plane is armed. Resolved once per materialization/stream,
    not per task. The nested-inline guard paths stay ``_run_partition``
    unconditionally: a partition task already running ON a cluster
    worker must not recurse into the coordinator's router."""
    if not EngineConfig.cluster_workers:
        return _run_partition
    from sparkdl_tpu.cluster import router as _cluster_router

    router = _cluster_router.maybe_router()
    return _run_partition if router is None else router.run_partition


def _as_record_batches(table: pa.Table, num_partitions: int) -> List[pa.RecordBatch]:
    n = max(1, table.num_rows)
    num_partitions = max(1, min(num_partitions, n))
    rows_per = -(-n // num_partitions)  # ceil
    out = []
    for start in range(0, table.num_rows, rows_per):
        chunk = table.slice(start, rows_per).combine_chunks()
        out.extend(chunk.to_batches())
    if not out:  # empty table: keep one empty batch so schema survives
        out = table.to_batches() or [
            pa.RecordBatch.from_arrays(
                [pa.array([], type=f.type) for f in table.schema],
                schema=table.schema)
        ]
    return out


class DataFrame:
    """Immutable, lazily-evaluated partitioned columnar frame."""

    def __init__(self, partitions: List[pa.RecordBatch], schema: pa.Schema,
                 ops: Optional[List[Callable]] = None):
        self._partitions = partitions
        self._schema = schema
        self._ops = list(ops or [])
        self._materialized: Optional[List[pa.RecordBatch]] = None
        self._lock = threading.Lock()
        # (process_id, num_processes) when this frame is one host's
        # round-robin partition share (processShard); propagated through
        # lazy ops so downstream transforms don't re-shard and
        # gatherProcesses can reassemble the original partition order.
        self._process_shard: Optional[Tuple[int, int]] = None

    # -- constructors --------------------------------------------------------

    @classmethod
    def fromArrow(cls, table: pa.Table, numPartitions: Optional[int] = None
                  ) -> "DataFrame":
        parts = _as_record_batches(table, numPartitions or EngineConfig.max_workers)
        return cls(parts, table.schema)

    @classmethod
    def fromPandas(cls, pdf: pd.DataFrame, numPartitions: Optional[int] = None
                   ) -> "DataFrame":
        return cls.fromArrow(pa.Table.from_pandas(pdf, preserve_index=False),
                             numPartitions)

    @classmethod
    def fromRows(cls, rows: List[Dict[str, Any]], schema: Optional[pa.Schema] = None,
                 numPartitions: Optional[int] = None) -> "DataFrame":
        if schema is not None:
            table = pa.Table.from_pylist(rows, schema=schema)
        else:
            table = pa.Table.from_pylist(rows)
        return cls.fromArrow(table, numPartitions)

    @classmethod
    def fromColumns(cls, columns: Dict[str, Any],
                    numPartitions: Optional[int] = None) -> "DataFrame":
        """Build from {name: numpy-or-list}; N-D arrays become FixedSizeList cols."""
        arrays, fields = [], []
        for name, values in columns.items():
            arr = to_arrow_array(values)
            arrays.append(arr)
            fields.append(pa.field(name, arr.type))
        table = pa.Table.from_arrays(arrays, schema=pa.schema(fields))
        return cls.fromArrow(table, numPartitions)

    # -- metadata ------------------------------------------------------------

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    @property
    def columns(self) -> List[str]:
        return [f.name for f in self._schema]

    @property
    def numPartitions(self) -> int:
        return len(self._partitions)

    def __repr__(self) -> str:
        cols = ", ".join(f"{f.name}: {f.type}" for f in self._schema)
        return f"DataFrame[{cols}] ({self.numPartitions} partitions)"

    # -- execution -----------------------------------------------------------

    def _quarantine_probe(self, index: int) -> pa.RecordBatch:
        """Zero-row stand-in for a quarantined partition: the op chain run
        on an empty slice keeps the chain's output schema and partition
        alignment while dropping the poisoned rows (data-dependent
        failures don't fire on zero rows; if even this fails, the
        supervisor propagates the original TaskFailure)."""
        out = self._partitions[index].slice(0, 0)
        for op in self._ops:
            out = op(out)
        return out

    def _materialize(self) -> List[pa.RecordBatch]:
        with self._lock:
            if self._materialized is not None:
                return self._materialized
            if not self._ops:
                self._materialized = self._partitions
                return self._materialized
            if threading.current_thread().name.startswith("sparkdl-part"):
                # nested materialization from inside a partition task: run
                # inline — waiting on the shared pool from one of its own
                # threads could deadlock. Classified retry still applies;
                # deadline enforcement is cooperative only (no watchdog).
                self._materialized = [
                    _run_partition(i, b, self._ops)
                    for i, b in enumerate(self._partitions)]
                return self._materialized
            # Supervised parallel execution (engine/supervisor.py):
            # classified retry per task, deadline watchdog, optional
            # straggler hedging and quarantine. The supervisor keeps the
            # old barrier semantics on FAILURE — it waits out attempts
            # still running user ops (the shared pool outlives this call),
            # skipping only watchdog-failed tasks, whose threads may be
            # wedged on the hung op. A clean run may leave a hedge
            # loser's discarded pure ops finishing in the background.
            ops = self._ops
            journal = durability.maybe_journal(self._partitions,
                                               self._schema, ops)
            if journal is not None:
                with telemetry.span(telemetry.SPAN_MATERIALIZE,
                                    partitions=len(self._partitions),
                                    ops=len(ops), durable=True):
                    self._materialized = self._materialize_durable(journal,
                                                                   ops)
                return self._materialized
            sup = PartitionSupervisor(_executor(), _supervisor_config(),
                                      quarantine_probe=self._quarantine_probe)
            dispatch = _cluster_dispatch()
            # the span is open while tasks are CREATED, so every
            # partition task's trace context parents under it
            with telemetry.span(telemetry.SPAN_MATERIALIZE,
                                partitions=len(self._partitions),
                                ops=len(ops)):
                self._materialized = sup.run_all(
                    [(i, lambda cancel, i=i, b=b: dispatch(i, b, ops,
                                                           cancel))
                     for i, b in enumerate(self._partitions)])
            return self._materialized

    def _durable_supervisor(self, journal) -> PartitionSupervisor:
        """Supervisor whose quarantine verdicts COMMIT: a poisoned
        partition's zero-row stand-in is journaled (quarantined=True), so
        a restarted job honors the verdict from spill instead of
        re-poisoning the gang."""
        return PartitionSupervisor(
            _executor(), _supervisor_config(),
            quarantine_probe=lambda i: journal.commit(
                i, self._quarantine_probe(i), quarantined=True))

    def _durable_runner(self, journal, i: int, ops,
                        dispatch: Callable[..., pa.RecordBatch]
                        = _run_partition):
        """A partition runner that journals: count the attempt, run the
        op chain (in-process or via the cluster router — the journal
        wraps OUTSIDE the dispatch, so a cluster re-dispatch after a
        worker death is zero-recompute for committed partitions), spill
        + commit the result before handing it back."""
        b = self._partitions[i]

        def run(cancel=None, i=i, b=b):
            journal.note_attempt(i)
            return journal.commit(i, dispatch(i, b, ops, cancel))

        return run

    def _materialize_durable(self, journal, ops) -> List[pa.RecordBatch]:
        """Durable materialization (docs/RESILIENCE.md "Durable
        recovery"): verified-committed partitions load from spill, only
        uncommitted ones run through the supervisor, each committing
        through the write-ahead journal as it completes. Output order
        and bytes are identical to an uninterrupted run."""
        committed = journal.resume()
        todo = [i for i in range(len(self._partitions)) if i not in committed]
        results: Dict[int, pa.RecordBatch] = {}
        if todo:
            sup = self._durable_supervisor(journal)
            dispatch = _cluster_dispatch()
            computed = sup.run_all(
                [(i, self._durable_runner(journal, i, ops,
                                          dispatch=dispatch))
                 for i in todo])
            results.update(zip(todo, computed))
        for i in committed:
            results[i] = journal.load(i)
        return [results[i] for i in range(len(self._partitions))]

    def _stream_durable(self, journal, indices: List[int], prefetch: int
                        ) -> Iterable[pa.RecordBatch]:
        """Durable streaming: restored partitions serve from spill,
        uncommitted ones stream through the supervisor (same bounded
        prefetch), interleaved back into the requested visit order."""
        committed = journal.resume()
        ops = self._ops
        todo = [i for i in indices if i not in committed]
        sup = self._durable_supervisor(journal)
        dispatch = _cluster_dispatch()

        def runners():
            for i in todo:
                yield i, self._durable_runner(journal, i, ops,
                                              dispatch=dispatch)

        stream = sup.run_stream(runners(), prefetch=prefetch)
        try:
            for i in indices:
                if i in committed:
                    yield journal.load(i)
                else:
                    yield next(stream)
        finally:
            stream.close()

    def toArrow(self) -> pa.Table:
        batches = self._materialize()
        try:
            return pa.Table.from_batches(batches, schema=self._schema)
        except (pa.ArrowInvalid, pa.ArrowTypeError):
            # Declared schema can be imprecise when a withColumn had no
            # explicit outputType (type inferred at materialization); unify
            # the materialized batch schemas, preferring non-null types.
            unified = pa.unify_schemas([b.schema for b in batches],
                                       promote_options="permissive")
            casted = [b.cast(unified) for b in batches]
            return pa.Table.from_batches(casted, schema=unified)

    def toPandas(self) -> pd.DataFrame:
        return self.toArrow().to_pandas()

    def collect(self) -> List[Dict[str, Any]]:
        # sparkdl: allow(columnar-hot-path): collect's CONTRACT is
        # per-row Python dicts (Spark Row analog); batch callers use
        # streamPartitions/toArrow
        return self.toArrow().to_pylist()

    def count(self) -> int:
        return sum(b.num_rows for b in self._materialize())

    def isEmpty(self) -> bool:
        return self.count() == 0

    def show(self, n: int = 20) -> None:
        print(self.limit(n).toPandas())

    def foreachPartition(self, fn: Callable[[pa.RecordBatch], None]) -> None:
        for batch in self._materialize():
            fn(batch)

    def partitionsIter(self) -> Iterable[pa.RecordBatch]:
        """Iterate materialized partitions (streaming consumption order)."""
        yield from self._materialize()

    def streamPartitions(self, prefetch: int = 2,
                         order: Optional[Sequence[int]] = None,
                         process_id: Optional[int] = None,
                         num_processes: Optional[int] = None
                         ) -> Iterable[pa.RecordBatch]:
        """Compute and yield partitions one at a time WITHOUT caching.

        Memory stays bounded by ``prefetch + 1`` computed partitions (the
        streaming-``fit`` contract, SURVEY.md §3.3: the reference
        ``collect()``-ed the dataset to the driver — its scalability
        cliff). Re-iterating recomputes the op chain (use ``cache()``
        first to trade memory for decode-once). Already-materialized
        frames yield their cached partitions directly. ``order``: visit
        partitions in this index order (per-epoch shuffle of a streaming
        train loop).

        ``process_id``/``num_processes`` (SURVEY.md §2.5, multi-host data
        plane): restrict this process to its round-robin share of the
        (possibly permuted) visit order — host ``p`` computes/decodes only
        positions ``p, p+n, p+2n, …``, the engine analog of Spark
        assigning partitions to executors. Every process must pass the
        same ``order`` (derive it from a shared seed) for the assignment
        to partition the dataset.
        """
        indices = list(order) if order is not None else list(range(
            len(self._partitions)))
        if num_processes is not None and num_processes > 1:
            if process_id is None or not 0 <= process_id < num_processes:
                raise ValueError(
                    f"process_id must be in [0, {num_processes}), got "
                    f"{process_id}")
            indices = indices[process_id::num_processes]
        with self._lock:
            materialized = self._materialized
        if materialized is not None:
            for i in indices:
                yield materialized[i]
            return
        if not self._ops:
            for i in indices:
                yield self._partitions[i]
            return
        if threading.current_thread().name.startswith("sparkdl-part"):
            # nested streaming from inside a partition task: run inline —
            # waiting on the shared pool from one of its own threads could
            # deadlock (same guard as _materialize)
            for i in indices:
                yield _run_partition(i, self._partitions[i], self._ops)
            return
        journal = durability.maybe_journal(self._partitions, self._schema,
                                           self._ops)
        if journal is not None:
            yield from self._stream_durable(journal, indices, prefetch)
            return
        # Supervised bounded-prefetch streaming on the shared process-wide
        # executor (VERDICT r3 weak #6: no per-epoch pool churn). In-flight
        # work is capped by `prefetch`, not by pool width; tasks get the
        # same classified retry / deadline watchdog / hedging / quarantine
        # as _materialize. Abandoned iteration (early break / error)
        # CANCELS unstarted attempts before draining the running ones, so
        # an early break doesn't silently compute (and decode) the rest of
        # the epoch.
        sup = PartitionSupervisor(_executor(), _supervisor_config(),
                                  quarantine_probe=self._quarantine_probe)
        parts, ops = self._partitions, self._ops
        dispatch = _cluster_dispatch()

        def runners():
            for i in indices:
                yield i, (lambda cancel, i=i: dispatch(
                    i, parts[i], ops, cancel))

        yield from sup.run_stream(runners(), prefetch=prefetch)

    # -- transformations (lazy) ----------------------------------------------

    def _with_op(self, op: Callable[[pa.RecordBatch], pa.RecordBatch],
                 schema: pa.Schema) -> "DataFrame":
        # Reuse already-materialized results (e.g. after cache()) so derived
        # frames don't recompute the upstream op chain.
        if self._materialized is not None and self._ops:
            out = DataFrame(self._materialized, schema, [op])
        else:
            out = DataFrame(self._partitions, schema, self._ops + [op])
        out._process_shard = self._process_shard
        return out

    def mapPartitions(self, fn: Callable[[pa.RecordBatch], pa.RecordBatch],
                      schema: Optional[pa.Schema] = None) -> "DataFrame":
        return self._with_op(fn, schema or self._schema)

    def select(self, *cols: str) -> "DataFrame":
        names = list(cols)
        for name in names:
            if name not in self.columns:
                raise KeyError(f"No such column: {name!r}")
        schema = pa.schema([self._schema.field(n) for n in names])

        def op(batch: pa.RecordBatch) -> pa.RecordBatch:
            cols = [batch.column(batch.schema.get_field_index(n)) for n in names]
            # Use the batch's actual types, not the declared schema: an
            # upstream withColumn without explicit outputType only learns its
            # type at materialization.
            actual = pa.schema([pa.field(n, c.type) for n, c in zip(names, cols)])
            return pa.RecordBatch.from_arrays(cols, schema=actual)

        return self._with_op(op, schema)

    def drop(self, *cols: str) -> "DataFrame":
        keep = [c for c in self.columns if c not in cols]
        return self.select(*keep)

    def selectExpr(self, *exprs: str) -> "DataFrame":
        """SQL projection over columns, literals and registered UDFs.

        Supports ``col``, ``col as alias``, ``*``, numeric/'string'
        literals, and nested multi-argument UDF calls
        (``udf1(udf2(image), other_col) as out``) — the engine analog of
        the reference's model-as-SQL-UDF serving path (SURVEY.md §3.4).
        UDFs resolve against ``sparkdl_tpu.udf.udf_registry``; the grammar
        lives in ``engine/sql_expr.py``.
        """
        from sparkdl_tpu.engine import sql_expr

        frame = self
        temp_counter = [0]
        # (source_col_on_frame, output_name); rename happens only in the
        # final projection — temp columns drop by omission — so one source
        # column can feed several outputs.
        projection: List[Tuple[str, str]] = []

        def fresh_temp() -> str:
            temp_counter[0] += 1
            return f"__sdl_expr_{temp_counter[0]}"

        def evaluate(node) -> str:
            """Materialize the expression as a column; returns its name."""
            nonlocal frame
            if isinstance(node, sql_expr.Column):
                if node.name not in self.columns:
                    raise KeyError(f"No such column: {node.name!r}")
                return node.name
            if isinstance(node, sql_expr.Literal):
                tmp = fresh_temp()
                frame = frame.withConstantColumn(tmp, node.value)
                return tmp
            if isinstance(node, sql_expr.Call):
                from sparkdl_tpu.udf import udf_registry  # lazy: layering

                arg_cols = [evaluate(a) for a in node.args]
                tmp = fresh_temp()
                frame = udf_registry.get(node.fn).apply(frame, arg_cols, tmp)
                return tmp
            raise ValueError(f"Cannot evaluate {node!r}")

        for expr in exprs:
            node, alias = sql_expr.parse(expr)
            if isinstance(node, sql_expr.Star):
                projection.extend((c, c) for c in self.columns)
                continue
            src = evaluate(node)
            out = alias or (src if isinstance(node, sql_expr.Column)
                            else sql_expr.default_name(expr))
            projection.append((src, out))

        def project(batch: pa.RecordBatch) -> pa.RecordBatch:
            cols = [batch.column(batch.schema.get_field_index(src))
                    for src, _ in projection]
            actual = pa.schema([pa.field(out, c.type)
                                for (_, out), c in zip(projection, cols)])
            return pa.RecordBatch.from_arrays(cols, schema=actual)

        schema = pa.schema([
            pa.field(out, frame._schema.field(src).type
                     if src in frame._schema.names else pa.null())
            for src, out in projection])
        return frame._with_op(project, schema)

    def withConstantColumn(self, name: str, value: Any) -> "DataFrame":
        """Add a column holding ``value`` in every row (literal support)."""
        arrow_type = pa.scalar(value).type

        def op(batch: pa.RecordBatch) -> pa.RecordBatch:
            arr = pa.array([value] * batch.num_rows, type=arrow_type)
            return _set_column(batch, name, arr)

        return self._with_op(op, _schema_with(self._schema, name, arrow_type))

    def withColumnRenamed(self, existing: str, new: str) -> "DataFrame":
        if existing not in self.columns:
            raise KeyError(f"No such column: {existing!r}")
        schema = pa.schema([
            pa.field(new, f.type) if f.name == existing else f
            for f in self._schema])

        def op(batch: pa.RecordBatch) -> pa.RecordBatch:
            actual = pa.schema([
                pa.field(new, c.type) if n == existing else pa.field(n, c.type)
                for n, c in zip(batch.schema.names, batch.columns)])
            return pa.RecordBatch.from_arrays(list(batch.columns), schema=actual)

        return self._with_op(op, schema)

    def withColumn(self, name: str, fn: Callable, inputCols: Sequence[str],
                   outputType: Optional[pa.DataType] = None) -> "DataFrame":
        """Row-wise UDF column: ``fn(*input_values) -> value``.

        The engine analog of a Spark Python UDF ``withColumn``. For
        vectorized device work use :meth:`withColumnBatch`.
        """
        out_type = outputType

        def op(batch: pa.RecordBatch) -> pa.RecordBatch:
            # sparkdl: allow(columnar-hot-path): row-wise UDF semantics —
            # fn receives Python values by contract; vectorized work
            # belongs in withColumnBatch
            inputs = [batch.column(batch.schema.get_field_index(c)).to_pylist()
                      for c in inputCols]
            values = [fn(*row) for row in zip(*inputs)] if inputs else []
            if out_type is not None:
                arr = pa.array(values, type=out_type)
            else:
                arr = pa.array(values)
            return _set_column(batch, name, arr)

        schema = _schema_with(self._schema, name,
                              out_type if out_type is not None else pa.null())
        return self._with_op(op, schema)

    def withColumnBatch(self, name: str, fn: Callable[[pa.RecordBatch], pa.Array],
                        outputType: Optional[pa.DataType] = None) -> "DataFrame":
        """Vectorized column: ``fn(record_batch) -> pa.Array`` (len == num_rows).

        This is the hook model transformers use: fn stages the whole
        partition to the device in one transfer and returns a columnar
        result — the TensorFrames ``map_blocks`` analog (SURVEY.md §3.2).
        """
        out_type = outputType

        def op(batch: pa.RecordBatch) -> pa.RecordBatch:
            arr = fn(batch)
            if not isinstance(arr, (pa.Array, pa.ChunkedArray)):
                arr = pa.array(arr, type=out_type)
            elif out_type is not None and arr.type != out_type:
                arr = arr.cast(out_type)
            if len(arr) != batch.num_rows:
                raise ValueError(
                    f"withColumnBatch fn returned {len(arr)} values for "
                    f"{batch.num_rows} rows")
            return _set_column(batch, name, arr)

        schema = _schema_with(self._schema, name,
                              out_type if out_type is not None else pa.null())
        return self._with_op(op, schema)

    def where(self, expr: str) -> "DataFrame":
        """SQL row filter: ``df.where("label = 1 AND score > 0.5")``.

        The filter side of the serving surface (SURVEY.md §3.4):
        comparisons (``= != <> < <= > >=``), ``AND/OR/NOT``, grouping
        parens and ``IS [NOT] NULL`` over columns and literals, with SQL
        null semantics (a comparison against NULL is not-true — the row
        drops). Grammar in ``engine/sql_expr.py``; UDF calls belong in
        ``selectExpr``, not here.
        """
        from sparkdl_tpu.engine import sql_expr

        node = sql_expr.parse_bool(expr)
        cols = sql_expr.bool_columns(node)
        for c in cols:
            if c not in self.columns:
                raise KeyError(f"No such column: {c!r}")

        def pred(*vals) -> bool:
            return sql_expr.eval_bool(node, dict(zip(cols, vals))) is True

        return self.filter(pred, inputCols=cols)

    def createOrReplaceTempView(self, name: str) -> None:
        """Register this frame under ``name`` for ``engine.sql()`` queries
        (the analog of Spark's temp-view registry, SURVEY.md §3.4)."""
        if not name or not name.replace("_", "").isalnum():
            raise ValueError(f"Bad view name {name!r}")
        _temp_views[name] = self

    def filter(self, predicate: Callable, inputCols: Sequence[str]) -> "DataFrame":
        def op(batch: pa.RecordBatch) -> pa.RecordBatch:
            if not inputCols:
                # constant predicate (e.g. where("1 = 1")): zip(*[]) would
                # yield a zero-length mask regardless of num_rows
                keep = bool(predicate())
                mask = pa.array([keep] * batch.num_rows, type=pa.bool_())
                return batch.filter(mask)
            # sparkdl: allow(columnar-hot-path): row-wise predicate
            # semantics — the user callable receives Python values
            inputs = [batch.column(batch.schema.get_field_index(c)).to_pylist()
                      for c in inputCols]
            mask = pa.array([bool(predicate(*row)) for row in zip(*inputs)],
                            type=pa.bool_())
            return batch.filter(mask)

        return self._with_op(op, self._schema)

    def dropna(self, subset: Optional[Sequence[str]] = None) -> "DataFrame":
        cols = list(subset or self.columns)

        def op(batch: pa.RecordBatch) -> pa.RecordBatch:
            mask = np.ones(batch.num_rows, dtype=bool)
            for c in cols:
                arr = batch.column(batch.schema.get_field_index(c))
                mask &= np.asarray(arr.is_valid())
            return batch.filter(pa.array(mask))

        return self._with_op(op, self._schema)

    # -- materializing transformations ---------------------------------------

    def repartition(self, numPartitions: int) -> "DataFrame":
        return DataFrame.fromArrow(self.toArrow(), numPartitions)

    def limit(self, n: int) -> "DataFrame":
        """First n rows, materializing only as many partitions as needed."""
        if self._materialized is not None:
            return DataFrame.fromArrow(self.toArrow().slice(0, n),
                                       numPartitions=1)
        taken: List[pa.RecordBatch] = []
        count = 0
        for i, part in enumerate(self._partitions):
            batch = _run_partition(i, part, self._ops)
            taken.append(batch)
            count += batch.num_rows
            if count >= n:
                break
        if not taken:
            return DataFrame(self._partitions, self._schema, self._ops)
        table = pa.Table.from_batches(taken, schema=taken[0].schema).slice(0, n)
        return DataFrame.fromArrow(table, numPartitions=1)

    def union(self, other: "DataFrame") -> "DataFrame":
        table = pa.concat_tables([self.toArrow(), other.toArrow()])
        return DataFrame.fromArrow(
            table, numPartitions=self.numPartitions + other.numPartitions)

    def orderBy(self, *cols: str, ascending: Union[bool, Sequence[bool]] = True
                ) -> "DataFrame":
        """Global sort (materializing, like Spark's orderBy shuffle)."""
        if not cols:
            raise ValueError("orderBy needs at least one column")
        if isinstance(ascending, bool):
            ascending = [ascending] * len(cols)
        if len(ascending) != len(cols):
            raise ValueError("ascending must match the number of columns")
        for c in cols:
            if c not in self.columns:
                raise KeyError(f"No such column: {c!r}")
        keys = [(c, "ascending" if a else "descending")
                for c, a in zip(cols, ascending)]
        return DataFrame.fromArrow(self.toArrow().sort_by(keys),
                                   numPartitions=self.numPartitions)

    def groupBy(self, *cols: str) -> "GroupedData":
        """Grouped aggregation (Arrow-native group_by under the hood)."""
        for c in cols:
            if c not in self.columns:
                raise KeyError(f"No such column: {c!r}")
        return GroupedData(self, list(cols))

    def join(self, other: "DataFrame", on: Union[str, Sequence[str]],
             how: str = "inner") -> "DataFrame":
        """Equi-join on key column(s) (Spark's ``df.join(other, on, how)``;
        ``inner`` or ``left``).

        Materializing hash join sized to this framework's workloads:
        the RIGHT side builds the hash table (metadata/label frames —
        keep the small side on the right), the left streams through it.
        Key columns appear once (Spark's USING semantics); other
        name collisions raise rather than silently disambiguate.
        Row multiplicity matches SQL: matching left×right pairs multiply.
        """
        from collections import defaultdict

        keys = [on] if isinstance(on, str) else list(on)
        if how not in ("inner", "left"):
            raise ValueError(f"how must be 'inner' or 'left', got {how!r}")
        for k in keys:
            if k not in self.columns:
                raise KeyError(f"No such column on left: {k!r}")
            if k not in other.columns:
                raise KeyError(f"No such column on right: {k!r}")
        left_other = [c for c in self.columns if c not in keys]
        right_other = [c for c in other.columns if c not in keys]
        clash = set(left_other) & set(right_other)
        if clash:
            raise ValueError(
                f"join would duplicate columns {sorted(clash)}; rename "
                "one side first (withColumnRenamed)")

        # build side: the right frame, fully materialized once. Keys are
        # frozen (nested list/struct/binary keys hash like distinct()'s).
        right_table = other.toArrow()
        build: Dict[Any, List[Dict[str, Any]]] = defaultdict(list)
        # sparkdl: allow(columnar-hot-path): hash-join build side needs
        # hashable Python keys — documented metadata-frame operation
        for r in right_table.to_pylist():
            key = tuple(_freeze_value(r[k]) for k in keys)
            if any(v is None for v in key):
                continue  # SQL: null keys never match
            build[key].append({c: r[c] for c in right_other})

        # probe side streams per materialized partition; the output uses
        # an EXPLICIT schema (actual left types + right types) in one
        # fixed column order, so dtypes survive instead of being
        # re-inferred from Python values (an all-null right column under
        # a left join would otherwise degrade to pa.null()).
        left_batches = self._materialize()
        left_schema = (pa.unify_schemas([b.schema for b in left_batches],
                                        promote_options="permissive")
                       if left_batches else self._schema)
        joined_schema = pa.schema(
            [left_schema.field(name) for name in left_schema.names]
            + [right_table.schema.field(c) for c in right_other])

        out_tables: List[pa.Table] = []
        for batch in left_batches:
            out_rows: List[Dict[str, Any]] = []
            # sparkdl: allow(columnar-hot-path): hash-join probe side —
            # same Python-key hashing as the build side above
            for r in batch.to_pylist():
                key = tuple(_freeze_value(r[k]) for k in keys)
                matches = ([] if any(v is None for v in key)
                           else build.get(key, []))
                if matches:
                    for m in matches:
                        out_rows.append({**r, **m})
                elif how == "left":
                    out_rows.append(
                        {**r, **{c: None for c in right_other}})
            if out_rows:
                out_tables.append(
                    pa.Table.from_pylist(out_rows, schema=joined_schema))
        if not out_tables:
            empty = pa.Table.from_pylist([], schema=joined_schema)
            return DataFrame.fromArrow(empty, numPartitions=1)
        return DataFrame.fromArrow(pa.concat_tables(out_tables),
                                   numPartitions=max(1, self.numPartitions))

    def distinct(self) -> "DataFrame":
        """Deduplicated rows (Spark's distinct; materializing, order of
        first occurrence).

        Cost note: rows convert to Python objects for hashing — O(dataset)
        driver-side work, like Spark's own shuffle-dedup. Meant for
        metadata frames (labels, uris), not image-blob columns.
        """
        table = self.toArrow()
        if table.num_rows == 0:
            return DataFrame.fromArrow(table, numPartitions=1)
        seen = set()
        keep = []
        # sparkdl: allow(columnar-hot-path): distinct() hashes Python
        # values by design (documented metadata-frame cost note above)
        for i, row in enumerate(table.to_pylist()):
            key = tuple(_freeze_value(v) for v in row.values())
            if key not in seen:
                seen.add(key)
                keep.append(i)
        return DataFrame.fromArrow(
            table.take(pa.array(keep, type=pa.int64())),
            numPartitions=max(1, self.numPartitions))

    def sample(self, fraction: float, seed: int = 0) -> "DataFrame":
        """Seeded Bernoulli row sample without replacement (Spark's
        ``sample(fraction, seed)``; materializing)."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")
        table = self.toArrow()
        mask = np.random.default_rng(seed).random(table.num_rows) < fraction
        return DataFrame.fromArrow(
            table.take(pa.array(np.nonzero(mask)[0], type=pa.int64())),
            numPartitions=max(1, self.numPartitions))

    def randomSplit(self, weights: Sequence[float],
                    seed: int = 0) -> List["DataFrame"]:
        """Split rows into len(weights) disjoint frames (Spark's
        randomSplit: weights normalize; assignment is a seeded global
        permutation, so splits are deterministic, disjoint, exhaustive —
        the backbone of CrossValidator/TrainValidationSplit)."""
        if not weights or any(w <= 0 for w in weights):
            raise ValueError(f"weights must be positive, got {weights}")
        table = self.toArrow()
        n = table.num_rows
        perm = np.random.default_rng(seed).permutation(n)
        total = float(sum(weights))
        bounds = np.cumsum([w / total for w in weights])
        out: List["DataFrame"] = []
        start = 0
        for i, b in enumerate(bounds):
            stop = n if i == len(weights) - 1 else int(round(b * n))
            idx = np.sort(perm[start:stop])
            out.append(DataFrame.fromArrow(
                table.take(pa.array(idx, type=pa.int64())),
                numPartitions=max(1, self.numPartitions)))
            start = stop
        return out

    def cache(self) -> "DataFrame":
        self._materialize()
        return self

    # -- multi-host data plane (SURVEY.md §2.4/§2.5) -------------------------

    def processShard(self, process_id: Optional[int] = None,
                     num_processes: Optional[int] = None) -> "DataFrame":
        """This process's round-robin share of the partitions, lazily.

        The transform-side analog of ``streamPartitions(process_id=...)``
        (Spark assigned partitions to executors for exactly this path,
        SURVEY.md §3.1): host ``p`` keeps partitions ``p, p+n, p+2n, …``;
        the op chain (decode, model apply) then only ever runs on the
        local share. Defaults come from the jax process group. Idempotent:
        an already-sharded frame (or any lazy derivative of one) returns
        itself, so chained transformers never double-shard.
        """
        if num_processes is None or process_id is None:
            import jax

            num_processes = (jax.process_count() if num_processes is None
                             else num_processes)
            process_id = (jax.process_index() if process_id is None
                          else process_id)
        if not 0 <= process_id < max(1, num_processes):
            # validate BEFORE the no-op returns: a bad id on a
            # single-process run should fail here, not first on the
            # multi-host deployment
            raise ValueError(
                f"process_id must be in [0, {num_processes}), got "
                f"{process_id}")
        if num_processes <= 1 or self._process_shard is not None:
            return self
        out = DataFrame(self._partitions[process_id::num_processes],
                        self._schema, self._ops)
        if self._materialized is not None:
            out._materialized = self._materialized[process_id::num_processes]
        out._process_shard = (process_id, num_processes)
        return out

    def gatherProcesses(self) -> "DataFrame":
        """Allgather every host's shard into the FULL frame on all hosts.

        The opt-in assembly step after a multi-host transform (per-host
        output stays host-local by default, mirroring multi-host ``fit``):
        each host materializes its local partitions, ships them as Arrow
        IPC bytes through a jax process allgather, and every host
        reassembles the partitions in the ORIGINAL pre-shard order — so
        ``shard-transform-gather`` row order equals the single-process
        transform's. Requires shard provenance: call it on the (possibly
        lazily transformed) frame produced by :meth:`processShard`.
        """
        import jax

        if jax.process_count() <= 1:
            return self
        if self._process_shard is None:
            raise ValueError(
                "gatherProcesses needs shard provenance: call it on the "
                "frame produced by processShard (or a lazy transform of "
                "it) — materializing ops like repartition/union drop it")
        process_id, num_processes = self._process_shard
        if (num_processes != jax.process_count()
                or process_id != jax.process_index()):
            # the allgather below has exactly process_count participants;
            # a shard cut for a different topology would mis-index it
            raise ValueError(
                f"shard provenance (process {process_id} of "
                f"{num_processes}) does not match the live process group "
                f"({jax.process_index()} of {jax.process_count()}); "
                "gatherProcesses only reassembles shards cut for this "
                "group")
        batches = self._materialize()
        payload = _serialize_batches(batches, self._schema)
        from jax.experimental import multihost_utils

        data = np.frombuffer(payload, dtype=np.uint8)
        lengths = multihost_utils.process_allgather(
            np.asarray([len(data)], dtype=np.int64))
        max_len = int(lengths.max())
        padded = np.zeros(max_len, dtype=np.uint8)
        padded[:len(data)] = data
        gathered = multihost_utils.process_allgather(padded)
        per_host = [
            _deserialize_batches(gathered[p, :int(lengths[p])].tobytes())
            for p in range(num_processes)]
        parts, schema = _reinterleave_shards(per_host, self._schema)
        return DataFrame(parts, schema)


class GroupedData:
    """``df.groupBy(cols)`` result: Spark-shaped aggregations lowered onto
    pyarrow's native ``Table.group_by`` (columnar, no Python row loop)."""

    _AGGS = {"sum", "mean", "avg", "min", "max", "count"}

    def __init__(self, df: "DataFrame", cols: List[str]) -> None:
        self._df = df
        self._cols = cols

    def count(self) -> "DataFrame":
        grouped = self._df.toArrow().group_by(self._cols).aggregate(
            [([], "count_all")])
        # Rename by the grouped table's ACTUAL column names — pyarrow's
        # key/aggregate column order has differed across releases and
        # pyproject leaves pyarrow unpinned (ADVICE r4).
        return DataFrame.fromArrow(grouped.rename_columns(
            ["count" if n == "count_all" else n
             for n in grouped.column_names]))

    def agg(self, exprs: Dict[str, str]) -> "DataFrame":
        """``{"column": "sum"|"mean"|"avg"|"min"|"max"|"count"}`` →
        one row per group with ``<agg>(<column>)`` result columns
        (Spark's dict-form ``agg``)."""
        aggs = []
        rename = {}
        for col, fn in exprs.items():
            fn = fn.lower()
            if fn not in self._AGGS:
                raise ValueError(
                    f"Unsupported aggregate {fn!r}; supported: "
                    f"{sorted(self._AGGS)}")
            if col not in self._df.columns:
                raise KeyError(f"No such column: {col!r}")
            arrow_fn = {"avg": "mean"}.get(fn, fn)
            aggs.append((col, arrow_fn))
            rename[f"{col}_{arrow_fn}"] = f"{fn}({col})"
        grouped = self._df.toArrow().group_by(self._cols).aggregate(aggs)
        # Map pyarrow's deterministic result names ("<col>_<fn>") to
        # Spark's "<fn>(<col>)" by NAME, not position (ADVICE r4: older
        # pyarrow put aggregates before keys, silently mislabeling both).
        return DataFrame.fromArrow(grouped.rename_columns(
            [rename.get(n, n) for n in grouped.column_names]))

    def mean(self, *cols: str) -> "DataFrame":
        return self.agg({c: "mean" for c in cols})

    def sum(self, *cols: str) -> "DataFrame":
        return self.agg({c: "sum" for c in cols})


def _freeze_value(v):
    """Row value → hashable key for distinct(): lists/dicts/bytes nest
    arbitrarily in Arrow columns (image structs hold binary data fields)."""
    if isinstance(v, list):
        return tuple(_freeze_value(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze_value(x)) for k, x in v.items()))
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    return v


# ---------------------------------------------------------------------------
# Temp views + sql() (the reference's SQL serving entry, SURVEY.md §3.4)
# ---------------------------------------------------------------------------

_temp_views: Dict[str, "DataFrame"] = {}


def table(name: str) -> "DataFrame":
    """The frame registered under ``name`` (createOrReplaceTempView)."""
    try:
        return _temp_views[name]
    except KeyError:
        raise KeyError(
            f"No temp view {name!r}; registered: {sorted(_temp_views)}"
        ) from None


def sql(query: str) -> "DataFrame":
    """``SELECT <exprs> FROM <view> [WHERE <condition>]`` over temp views.

    The reference's serving story was literally
    ``spark.sql("SELECT my_udf(image) FROM images")`` after
    ``registerKerasImageUDF`` (SURVEY.md §3.4) — this makes that exact
    string work: expressions run through ``selectExpr`` (registered
    UDFs, nesting, aliases, literals, ``*``), the optional WHERE through
    :meth:`DataFrame.where`. Lazy like every engine transformation.
    """
    from sparkdl_tpu.engine import sql_expr

    parts = sql_expr.split_query(query)
    frame = table(parts["view"])
    if parts["where"]:
        frame = frame.where(parts["where"])
    return frame.selectExpr(*parts["select"])


# ---------------------------------------------------------------------------
# Multi-host gather helpers
# ---------------------------------------------------------------------------

def _serialize_batches(batches: Sequence[pa.RecordBatch],
                       fallback_schema: pa.Schema) -> bytes:
    """Partition batches → one Arrow IPC stream (batch == partition).

    Batches are cast to their permissively-unified schema first: ops
    without an explicit outputType only learn types at materialization,
    so sibling partitions can disagree (e.g. null vs float list).
    """
    import io

    if batches:
        schema = pa.unify_schemas([b.schema for b in batches],
                                  promote_options="permissive")
        batches = [b.cast(schema) for b in batches]
    else:
        schema = fallback_schema
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, schema) as writer:
        for b in batches:
            writer.write_batch(b)
    return sink.getvalue()


def _deserialize_batches(payload: bytes) -> List[pa.RecordBatch]:
    with pa.ipc.open_stream(payload) as reader:
        return list(reader)


def _reinterleave_shards(per_host: List[List[pa.RecordBatch]],
                         fallback_schema: pa.Schema
                         ) -> Tuple[List[pa.RecordBatch], pa.Schema]:
    """Invert round-robin sharding: global partition ``g`` was computed by
    host ``g % n`` at local position ``g // n``. Host schemas are unified
    permissively (hosts infer types independently)."""
    n = len(per_host)
    all_batches = [b for host in per_host for b in host]
    if not all_batches:
        return [], fallback_schema
    schema = pa.unify_schemas([b.schema for b in all_batches],
                              promote_options="permissive")
    parts: List[pa.RecordBatch] = []
    for g in range(n * max(len(h) for h in per_host)):
        host, pos = g % n, g // n
        if pos < len(per_host[host]):
            parts.append(per_host[host][pos].cast(schema))
    return parts, schema


# ---------------------------------------------------------------------------
# Arrow helpers
# ---------------------------------------------------------------------------

def _schema_with(schema: pa.Schema, name: str, dtype: pa.DataType) -> pa.Schema:
    """Declared schema after with-column: replace in place, append if new
    (must mirror _set_column's positional behavior)."""
    if name in schema.names:
        return pa.schema([pa.field(name, dtype) if f.name == name else f
                          for f in schema])
    return pa.schema(list(schema) + [pa.field(name, dtype)])


def _set_column(batch: pa.RecordBatch, name: str, arr) -> pa.RecordBatch:
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    names = batch.schema.names
    if name in names:
        idx = names.index(name)
        cols = list(batch.columns)
        cols[idx] = arr
        fields = [pa.field(n, cols[i].type) for i, n in enumerate(names)]
        return pa.RecordBatch.from_arrays(cols, schema=pa.schema(fields))
    cols = list(batch.columns) + [arr]
    fields = list(batch.schema) + [pa.field(name, arr.type)]
    return pa.RecordBatch.from_arrays(cols, schema=pa.schema(fields))


def to_arrow_array(values: Any) -> pa.Array:
    """Convert list/numpy to Arrow; N-D numpy → FixedSizeList of flattened rows."""
    if isinstance(values, pa.Array):
        return values
    if isinstance(values, np.ndarray) and values.ndim > 1:
        n = values.shape[0]
        flat = np.ascontiguousarray(values).reshape(n, -1)
        return fixed_size_list_array(flat)
    return pa.array(values)


def fixed_size_list_array(flat2d: np.ndarray) -> pa.FixedSizeListArray:
    """(N, K) numpy → Arrow FixedSizeList<item: dtype>[K], zero-copy values."""
    n, k = flat2d.shape
    values = pa.array(np.ascontiguousarray(flat2d).reshape(-1))
    return pa.FixedSizeListArray.from_arrays(values, k)


def column_to_numpy(arr, dtype=None) -> np.ndarray:
    """Arrow column (numeric / [FixedSize]List thereof) → numpy (N, ...) array."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if pa.types.is_fixed_size_list(arr.type):
        k = arr.type.list_size
        # flatten() (not .values): respects slice offsets — partition
        # batches are table slices, where .values spans the whole buffer.
        values = arr.flatten().to_numpy(zero_copy_only=False)
        out = values.reshape(len(arr), k)
    elif pa.types.is_list(arr.type) or pa.types.is_large_list(arr.type):
        # sparkdl: allow(columnar-hot-path): generic-list fallback for
        # ragged rows; uniform vector columns take list_column_to_numpy
        rows = arr.to_pylist()
        out = np.asarray(rows)
    else:
        out = arr.to_numpy(zero_copy_only=False)
    if dtype is not None:
        out = np.asarray(out, dtype=dtype)
    return out


def list_column_to_numpy(arr, element_nulls: str = "reject"
                         ) -> Optional[np.ndarray]:
    """Uniform-width list column → (n_valid, K) float64 matrix, no per-row
    Python (docs/PERF.md "Columnar data plane"): null ROWS drop via one
    vectorized filter, the element buffer flattens through numpy once.
    Returns None when the column is not list-typed, rows are ragged, or —
    under ``element_nulls="reject"`` — elements are null; callers fall
    back to their per-row path, so semantics for irregular data are
    unchanged. ``element_nulls="nan"`` maps null elements to NaN instead
    (the Imputer's missing-value convention)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    fixed = pa.types.is_fixed_size_list(arr.type)
    if not (fixed or pa.types.is_list(arr.type)
            or pa.types.is_large_list(arr.type)):
        return None
    if arr.null_count:
        arr = arr.drop_null()
    n = len(arr)
    if fixed:
        width = arr.type.list_size
    else:
        offsets = arr.offsets.to_numpy()
        widths = np.diff(offsets)
        if widths.size and not (widths == widths[0]).all():
            return None  # ragged vectors — per-row path validates/raises
        width = int(widths[0]) if widths.size else 0
    flat = arr.flatten()  # respects slice offsets and dropped rows
    if flat.null_count and element_nulls != "nan":
        return None
    values = flat.to_numpy(zero_copy_only=False)  # nulls → NaN (float64)
    return np.asarray(values, np.float64).reshape(n, width)
