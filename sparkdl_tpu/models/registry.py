"""Named-model registry — the reference's ``keras_applications.py`` +
Scala ``Models.scala`` rebuilt (SURVEY.md §2.1/§2.2).

Each entry carries: the Flax module builder, fixed input size, the
device-side preprocessing function (fused into the same XLA program as the
model — the ``buildSpImageConverter`` splice, SURVEY.md §3.2), feature
dimension, and how to obtain weights. Weight sources:

- ``"random"``: seeded init (tests / no-network environments),
- a Flax variables dict,
- a Keras model object or H5/.keras file (converted via models.convert),
- a msgpack/Orbax path saved by this framework.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec
from sparkdl_tpu.models.inception import InceptionV3
from sparkdl_tpu.models.mobilenet import MobileNetV2
from sparkdl_tpu.models.resnet import ResNet50, ResNet101, ResNet152
from sparkdl_tpu.models.testnet import TestNet
from sparkdl_tpu.models.vgg import VGG16, VGG19
from sparkdl_tpu.models.xception import Xception

# ---------------------------------------------------------------------------
# Device-side preprocessing (input: float32 RGB in [0, 255], NHWC)
# ---------------------------------------------------------------------------

_CAFFE_MEAN = (103.939, 116.779, 123.68)  # BGR means, keras 'caffe' mode


def preprocess_tf_mode(x: jnp.ndarray) -> jnp.ndarray:
    """keras 'tf' mode: scale to [-1, 1]."""
    return x / 127.5 - 1.0


def preprocess_caffe_mode(x: jnp.ndarray) -> jnp.ndarray:
    """keras 'caffe' mode: RGB->BGR, subtract ImageNet means."""
    x = x[..., ::-1]
    mean = jnp.asarray(_CAFFE_MEAN, dtype=x.dtype)
    return x - mean


def preprocess_identity(x: jnp.ndarray) -> jnp.ndarray:
    return x


_TORCH_MEAN = (0.485, 0.456, 0.406)
_TORCH_STD = (0.229, 0.224, 0.225)


def preprocess_torch_mode(x: jnp.ndarray) -> jnp.ndarray:
    """keras 'torch' mode: [0,1] scale then ImageNet RGB mean/std."""
    x = x / 255.0
    mean = jnp.asarray(_TORCH_MEAN, dtype=x.dtype)
    std = jnp.asarray(_TORCH_STD, dtype=x.dtype)
    return (x - mean) / std


# Normalize-mode catalog: every per-model-family preprocess the registry
# fuses into the device program (ModelFunction.with_preprocess). The
# columnar-plane equivalence tests sweep this map so a newly added mode
# is covered automatically (tests/image/test_columnar_plane.py).
PREPROCESS_MODES: Dict[str, Callable] = {
    "tf": preprocess_tf_mode,
    "caffe": preprocess_caffe_mode,
    "torch": preprocess_torch_mode,
    "identity": preprocess_identity,
}


@dataclass(frozen=True)
class ModelSpec:
    name: str
    builder: Callable[..., Any]          # kwargs -> flax Module
    input_size: Tuple[int, int]          # (H, W)
    preprocess: Callable                 # device-side, jax-traceable
    feature_dim: int
    classes: int = 1000
    # kwargs used to build the *featurize* (headless) variant
    featurize_kwargs: Optional[Dict[str, Any]] = None
    # Forward FLOPs per image (2·MACs at the native input size) — the
    # bench's MFU fallback when XLA cost_analysis is unavailable for a
    # compiled featurize program. None = unknown (MFU omitted).
    flops_per_image: Optional[float] = None


SUPPORTED_MODELS: Dict[str, ModelSpec] = {
    "InceptionV3": ModelSpec(
        "InceptionV3", InceptionV3, (299, 299), preprocess_tf_mode, 2048,
        flops_per_image=5.7e9),
    "ResNet50": ModelSpec(
        "ResNet50", ResNet50, (224, 224), preprocess_caffe_mode, 2048,
        flops_per_image=7.75e9),
    "ResNet101": ModelSpec(
        "ResNet101", ResNet101, (224, 224), preprocess_caffe_mode, 2048),
    "ResNet152": ModelSpec(
        "ResNet152", ResNet152, (224, 224), preprocess_caffe_mode, 2048),
    "Xception": ModelSpec(
        "Xception", Xception, (299, 299), preprocess_tf_mode, 2048),
    "VGG16": ModelSpec(
        "VGG16", VGG16, (224, 224), preprocess_caffe_mode, 4096,
        featurize_kwargs={"include_top": True, "features_at_fc2": True}),
    "VGG19": ModelSpec(
        "VGG19", VGG19, (224, 224), preprocess_caffe_mode, 4096,
        featurize_kwargs={"include_top": True, "features_at_fc2": True}),
    "MobileNetV2": ModelSpec(
        "MobileNetV2", MobileNetV2, (224, 224), preprocess_tf_mode, 1280),
    "TestNet": ModelSpec(
        "TestNet", TestNet, (32, 32), preprocess_tf_mode, 16, classes=10),
}

# Ingestion-backed named models (r4): families WITHOUT an in-repo Flax
# definition serve through the generic keras layer-DAG walker
# (models/keras_ingest.py, oracle-exact per family) — DeepImageFeaturizer/
# Predictor accept these names exactly like the Flax-native ones. Weights:
# "random" (keras init) or an .h5/.keras file. Device preprocess follows
# each family's keras contract (EfficientNet/MobileNetV3 normalize
# in-model, so identity).
_INGESTED_MODELS: Dict[str, ModelSpec] = {
    "DenseNet121": ModelSpec(
        "DenseNet121", None, (224, 224), preprocess_torch_mode, 1024,
        flops_per_image=5.7e9),
    "EfficientNetB0": ModelSpec(
        "EfficientNetB0", None, (224, 224), preprocess_identity, 1280,
        flops_per_image=0.78e9),
    "MobileNetV3Small": ModelSpec(
        "MobileNetV3Small", None, (224, 224), preprocess_identity, 576),
    "NASNetMobile": ModelSpec(
        "NASNetMobile", None, (224, 224), preprocess_tf_mode, 1056),
    # r5: the remaining oracle-verified ingestion families (README layer
    # contract) exposed as named models. ResNet50V2 preprocesses in tf
    # mode (resnet_v2 contract); EfficientNetV2/ConvNeXt normalize
    # in-model, so device preprocess is identity.
    "ResNet50V2": ModelSpec(
        "ResNet50V2", None, (224, 224), preprocess_tf_mode, 2048),
    "EfficientNetV2B0": ModelSpec(
        "EfficientNetV2B0", None, (224, 224), preprocess_identity, 1280),
    "ConvNeXtTiny": ModelSpec(
        "ConvNeXtTiny", None, (224, 224), preprocess_identity, 768),
    # size variants of the proven families (every family has a
    # keras-forward oracle test in tests/models/test_keras_oracle.py;
    # per-name dims validate against keras output_shape in
    # tests/ml/test_named_image.py)
    "DenseNet169": ModelSpec(
        "DenseNet169", None, (224, 224), preprocess_torch_mode, 1664),
    "DenseNet201": ModelSpec(
        "DenseNet201", None, (224, 224), preprocess_torch_mode, 1920),
    "ResNet101V2": ModelSpec(
        "ResNet101V2", None, (224, 224), preprocess_tf_mode, 2048),
    "ResNet152V2": ModelSpec(
        "ResNet152V2", None, (224, 224), preprocess_tf_mode, 2048),
    "EfficientNetB1": ModelSpec(
        "EfficientNetB1", None, (240, 240), preprocess_identity, 1280),
    "MobileNetV3Large": ModelSpec(
        "MobileNetV3Large", None, (224, 224), preprocess_identity, 960),
}

_INGESTED_BUILDERS = {
    "DenseNet121": ("densenet", "DenseNet121"),
    "EfficientNetB0": ("efficientnet", "EfficientNetB0"),
    "MobileNetV3Small": (None, "MobileNetV3Small"),  # top-level export only
    "NASNetMobile": ("nasnet", "NASNetMobile"),
    "ResNet50V2": ("resnet_v2", "ResNet50V2"),
    "EfficientNetV2B0": ("efficientnet_v2", "EfficientNetV2B0"),
    "ConvNeXtTiny": ("convnext", "ConvNeXtTiny"),
    "DenseNet169": ("densenet", "DenseNet169"),
    "DenseNet201": ("densenet", "DenseNet201"),
    "ResNet101V2": ("resnet_v2", "ResNet101V2"),
    "ResNet152V2": ("resnet_v2", "ResNet152V2"),
    "EfficientNetB1": ("efficientnet", "EfficientNetB1"),
    "MobileNetV3Large": (None, "MobileNetV3Large"),
}


def _resolve_keras_ctor(name: str):
    """keras.applications constructor for any supported named model
    (shared by the ingestion builder and build_keras_reference)."""
    import importlib

    import keras

    entry = _KERAS_BUILDERS.get(name) or _INGESTED_BUILDERS.get(name)
    if entry is None:
        raise ValueError(
            f"No keras.applications counterpart for {name!r}; available: "
            f"{sorted(set(_KERAS_BUILDERS) | set(_INGESTED_BUILDERS))}")
    module_name, attr = entry
    if module_name is None:
        return getattr(keras.applications, attr)
    return getattr(importlib.import_module(
        f"keras.applications.{module_name}"), attr)

SUPPORTED_MODEL_NAMES = sorted(SUPPORTED_MODELS) + sorted(_INGESTED_MODELS)

# keras.applications builders for weight-bearing named models (used when the
# user asks for keras-initialized weights, or in oracle tests).
_KERAS_BUILDERS = {
    "InceptionV3": ("inception_v3", "InceptionV3"),
    "ResNet50": ("resnet", "ResNet50"),
    "Xception": ("xception", "Xception"),
    "VGG16": ("vgg16", "VGG16"),
    "VGG19": ("vgg19", "VGG19"),
    "MobileNetV2": ("mobilenet_v2", "MobileNetV2"),
}


def get_model_spec(name: str) -> ModelSpec:
    spec = SUPPORTED_MODELS.get(name) or _INGESTED_MODELS.get(name)
    if spec is None:
        raise ValueError(
            f"Unsupported model {name!r}; supported: {SUPPORTED_MODEL_NAMES}")
    return spec


def is_ingested_model(name: str) -> bool:
    return name in _INGESTED_MODELS


def _build_ingested(name: str, weights, include_top: bool,
                    dtype) -> ModelFunction:
    """Named model via keras build + generic ingestion (no Flax def)."""
    from sparkdl_tpu.models.keras_ingest import keras_to_model_function

    spec = _INGESTED_MODELS[name]
    h, w = spec.input_size
    msgpack_path = None
    if isinstance(weights, str) and weights.endswith((".h5", ".keras")):
        from sparkdl_tpu.models.convert import load_keras_file

        model = load_keras_file(weights)
    elif hasattr(weights, "layers"):
        model = weights
    else:
        # "random" (keras-initialized architecture) or a msgpack weights
        # file saved by this framework (named-model persistence). Anything
        # else raises — a silent random fallback would discard the user's
        # weights (the Flax path raises the same way, _resolve_variables).
        if weights is not None and not isinstance(weights, str):
            raise TypeError(
                f"Cannot resolve weights for ingested model {name!r} from "
                f"{type(weights).__name__}; pass 'random', a Keras model "
                "object, an .h5/.keras file, or a msgpack file saved by "
                "this framework")
        if isinstance(weights, str) and weights not in ("random",):
            # Opening unknown strings blind surfaced typos (or the
            # upstream-conventional 'imagenet' marker, which needs a
            # network this env doesn't have) as raw flax/IO errors
            # (ADVICE r4) — state the accepted values instead.
            if not os.path.exists(weights):
                raise ValueError(
                    f"weights={weights!r} for ingested model {name!r} is "
                    "neither a supported marker nor an existing file. "
                    "Accepted: 'random' (fresh keras init), a Keras model "
                    "object, an .h5/.keras model file, or a msgpack "
                    "weights file saved by this framework ('imagenet' "
                    "downloads are not available without network access)")
            msgpack_path = weights
        ctor = _resolve_keras_ctor(name)
        kwargs = {"weights": None, "input_shape": (h, w, 3)}
        if include_top:
            kwargs["classes"] = spec.classes
        else:
            kwargs.update(include_top=False, pooling="avg")
        model = ctor(**kwargs)
    mf = keras_to_model_function(
        model, name=f"{name}_{'predict' if include_top else 'featurize'}")
    # A user-supplied model/file is ingested verbatim — verify its output
    # matches the requested role instead of silently serving a classifier
    # head as "features" (the Flax path re-builds the headless
    # architecture; ingestion cannot, so it checks).
    out = jax.eval_shape(mf.apply_fn, mf.variables,
                         jnp.zeros((1, h, w, 3), jnp.float32))
    if not hasattr(out, "ndim"):  # multi-output graph -> dict of outputs
        raise ValueError(
            f"Ingested {name!r} model has multiple outputs; named "
            "featurizers/predictors bind ONE output column — serve "
            "multi-IO models via TPUTransformer instead")
    if out.ndim != 2:
        raise ValueError(
            f"Ingested {name!r} model emits shape {out.shape}; expected a "
            "(batch, features) head — save the model with "
            "include_top=False, pooling='avg'"
            if not include_top else
            f"Ingested {name!r} model emits shape {out.shape}; expected "
            "(batch, classes) probabilities")
    if not include_top and out.shape[-1] != spec.feature_dim:
        raise ValueError(
            f"Ingested {name!r} model emits {out.shape[-1]}-dim output but "
            f"the featurizer contract for this name is {spec.feature_dim} "
            "features — pass a headless (include_top=False, pooling='avg') "
            "model")
    if msgpack_path is not None:
        import flax.serialization as fser

        with open(msgpack_path, "rb") as f:
            mf.variables = fser.from_bytes(mf.variables, f.read())
    if dtype is not None:
        mf = mf.with_compute_dtype(dtype)
    return mf


def _resolve_variables(spec: ModelSpec, module, weights, seed: int,
                       input_spec: TensorSpec):
    """Resolve the ``weights`` argument to a Flax variables pytree."""
    if weights is None or weights == "random":
        rng = jax.random.PRNGKey(seed)
        # jit the init: eager init dispatches one RPC per op, which is
        # pathological over a remote PJRT tunnel (measured 278s for
        # InceptionV3 eager vs seconds jitted — one compiled program).
        init = jax.jit(module.init)
        return init(rng, jnp.zeros(input_spec.with_batch(1),
                                   dtype=input_spec.dtype))
    if isinstance(weights, dict):
        return weights
    if isinstance(weights, str):
        if os.path.isdir(weights):
            import orbax.checkpoint as ocp

            template = jax.eval_shape(
                lambda: module.init(jax.random.PRNGKey(0),
                                    jnp.zeros(input_spec.with_batch(1),
                                              dtype=input_spec.dtype)))
            with ocp.StandardCheckpointer() as ckptr:
                return ckptr.restore(os.path.abspath(weights), template)
        if weights.endswith((".h5", ".keras")):
            from sparkdl_tpu.models.convert import (
                convert_keras_model, load_keras_file)

            return convert_keras_model(spec.name, load_keras_file(weights))
        # msgpack
        import flax.serialization as fser

        template = module.init(jax.random.PRNGKey(0),
                               jnp.zeros(input_spec.with_batch(1),
                                         dtype=input_spec.dtype))
        with open(weights, "rb") as f:
            return fser.from_bytes(template, f.read())
    # keras model object
    if hasattr(weights, "layers"):
        from sparkdl_tpu.models.convert import convert_keras_model

        return convert_keras_model(spec.name, weights)
    raise TypeError(f"Cannot resolve weights from {type(weights).__name__}")


def _spec_input(spec: ModelSpec) -> TensorSpec:
    h, w = spec.input_size
    return TensorSpec((None, h, w, 3), "float32")


def _fast_inference_apply(name: str, include_top: bool, dtype):
    """Inference-specialized apply for models that have one, else None.

    InceptionV3 has a fused fast path (BN folding + branch-fused 1x1 convs,
    ``models/inception_fast.py``) measured ~13% faster than the module
    apply on TPU (r3 profile: 9.4k vs 7.5k img/s at batch 128).
    """
    if name != "InceptionV3":
        return None
    from sparkdl_tpu.models.inception_fast import inception_v3_fast_apply

    compute_dtype = dtype or jnp.float32

    def apply_fn(vs, x):
        return inception_v3_fast_apply(vs, x, include_top=include_top,
                                       pooling="avg",
                                       compute_dtype=compute_dtype)

    return apply_fn


def build_featurizer(name: str, weights="random", seed: int = 0,
                     dtype=None, preprocess: bool = True,
                     fast: bool = True,
                     precision: Optional[str] = None) -> ModelFunction:
    """Headless named model as a ModelFunction emitting feature vectors.

    Input contract: float32 RGB [0,255] NHWC at the model's input size
    (host side resizes; scaling/mean-subtract runs on device, fused).
    ``fast=False`` forces the plain Flax-module apply even where an
    inference-specialized fast path exists. ``precision`` applies
    :meth:`ModelFunction.with_dtype` to the finished featurizer
    ("bfloat16" compute / "int8" weight-only PTQ; None or "float32"
    leaves it untouched) — note the engine's executor choke point applies
    ``EngineConfig.inference_precision`` itself, so this parameter is for
    standalone (non-engine) use of the registry.
    """
    spec = get_model_spec(name)
    if is_ingested_model(name):
        mf = _build_ingested(name, weights, include_top=False, dtype=dtype)
        if preprocess:
            mf = mf.with_preprocess(spec.preprocess)
        mf.fast_path = False
        return _apply_precision(mf, precision)
    kwargs = dict(spec.featurize_kwargs or {"include_top": False,
                                            "pooling": "avg"})
    kwargs["dtype"] = dtype
    module = spec.builder(**kwargs)
    input_spec = _spec_input(spec)
    variables = _resolve_variables(spec, module, weights, seed, input_spec)
    fast_apply = _fast_inference_apply(name, False, dtype) if fast else None
    if fast_apply is not None:
        mf = ModelFunction.fromFunction(fast_apply, variables, input_spec,
                                        name=f"{name}_featurize")
    else:
        mf = ModelFunction.fromFlax(module, variables, input_spec,
                                    name=f"{name}_featurize", train=False)
    if preprocess:
        mf = mf.with_preprocess(spec.preprocess)
    mf.fast_path = fast_apply is not None
    return _apply_precision(mf, precision)


def build_predictor(name: str, weights="random", seed: int = 0,
                    dtype=None, preprocess: bool = True,
                    fast: bool = True,
                    precision: Optional[str] = None) -> ModelFunction:
    """Full named model (softmax probabilities) as a ModelFunction.

    ``precision``: see :func:`build_featurizer`."""
    spec = get_model_spec(name)
    if is_ingested_model(name):
        mf = _build_ingested(name, weights, include_top=True, dtype=dtype)
        if preprocess:
            mf = mf.with_preprocess(spec.preprocess)
        mf.fast_path = False
        return _apply_precision(mf, precision)
    module = spec.builder(include_top=True, classes=spec.classes, dtype=dtype)
    input_spec = _spec_input(spec)
    variables = _resolve_variables(spec, module, weights, seed, input_spec)
    fast_apply = _fast_inference_apply(name, True, dtype) if fast else None
    if fast_apply is not None:
        mf = ModelFunction.fromFunction(fast_apply, variables, input_spec,
                                        name=f"{name}_predict")
    else:
        mf = ModelFunction.fromFlax(module, variables, input_spec,
                                    name=f"{name}_predict", train=False)
    if preprocess:
        mf = mf.with_preprocess(spec.preprocess)
    mf.fast_path = fast_apply is not None
    return _apply_precision(mf, precision)


def _apply_precision(mf: ModelFunction,
                     precision: Optional[str]) -> ModelFunction:
    """with_dtype pass-through keeping fast_path on the returned model."""
    if precision is None or precision == "float32":
        return mf
    fast_path = mf.fast_path
    out = mf.with_dtype(precision)
    out.fast_path = fast_path
    return out


def build_keras_reference(name: str):
    """Instantiate the same architecture in keras (weights=None) — used by
    oracle tests and by users wanting keras-side verification. Covers the
    Flax-native AND ingestion-backed named models."""
    return _resolve_keras_ctor(name)(weights=None)
