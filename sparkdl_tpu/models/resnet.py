"""ResNet50/101/152 (v1) in Flax — keras.applications.resnet parity.

Reference behavior (upstream ``sparkdl/transformers/keras_applications.py``
named-model registry, SURVEY.md §2.1): ResNet50 at 224x224, caffe-style
preprocessing, feature layer = global-average-pooled 2048-d vector.

Architecture matched op-for-op against keras.src.applications.resnet (BN
eps 1.001e-5, biased convs, stride-2 on the FIRST 1x1 of each downsampling
block, explicit 3px stem pad then VALID conv — not SAME).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from sparkdl_tpu.models.layers import (
    RESNET_BN_EPS, classifier_head, global_avg_pool, max_pool, pad2d,
)


class ResidualBlockV1(nn.Module):
    filters: int
    stride: int = 1
    conv_shortcut: bool = True
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, epsilon=RESNET_BN_EPS,
            momentum=0.99, dtype=self.dtype, name=name)
        if self.conv_shortcut:
            shortcut = nn.Conv(4 * self.filters, (1, 1),
                               strides=(self.stride, self.stride),
                               dtype=self.dtype, name="conv_0")(x)
            shortcut = bn("bn_0")(shortcut)
        else:
            shortcut = x
        y = nn.Conv(self.filters, (1, 1), strides=(self.stride, self.stride),
                    dtype=self.dtype, name="conv_1")(x)
        y = nn.relu(bn("bn_1")(y))
        y = nn.Conv(self.filters, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv_2")(y)
        y = nn.relu(bn("bn_2")(y))
        y = nn.Conv(4 * self.filters, (1, 1), dtype=self.dtype,
                    name="conv_3")(y)
        y = bn("bn_3")(y)
        return nn.relu(shortcut + y)


class ResNet(nn.Module):
    """ResNet v1 family. ``stack_sizes``: blocks per stage."""

    stack_sizes: Sequence[int] = (3, 4, 6, 3)
    include_top: bool = True
    classes: int = 1000
    classifier_activation: Optional[str] = "softmax"
    pooling: Optional[str] = "avg"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = pad2d(x, 3)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding="VALID",
                    dtype=self.dtype, name="conv1_conv")(x)
        x = nn.BatchNorm(use_running_average=not train,
                         epsilon=RESNET_BN_EPS, momentum=0.99,
                         dtype=self.dtype, name="conv1_bn")(x)
        x = nn.relu(x)
        x = pad2d(x, 1)
        x = max_pool(x, 3, 2)

        filters = (64, 128, 256, 512)
        for stage, (f, blocks) in enumerate(zip(filters, self.stack_sizes)):
            stride1 = 1 if stage == 0 else 2
            x = ResidualBlockV1(f, stride=stride1, dtype=self.dtype,
                                name=f"conv{stage + 2}_block1")(x, train)
            for i in range(2, blocks + 1):
                x = ResidualBlockV1(f, conv_shortcut=False, dtype=self.dtype,
                                    name=f"conv{stage + 2}_block{i}")(x, train)

        if self.include_top:
            x = global_avg_pool(x)
            return classifier_head(x, self.classes,
                                   self.classifier_activation, self.dtype)
        if self.pooling == "avg":
            return global_avg_pool(x)
        if self.pooling == "max":
            return jnp.max(x, axis=(1, 2))
        return x


def ResNet50(**kwargs) -> ResNet:
    return ResNet(stack_sizes=(3, 4, 6, 3), **kwargs)


def ResNet101(**kwargs) -> ResNet:
    return ResNet(stack_sizes=(3, 4, 23, 3), **kwargs)


def ResNet152(**kwargs) -> ResNet:
    return ResNet(stack_sizes=(3, 8, 36, 3), **kwargs)
