"""Fused inference fast path for the ResNet v1 family.

Same design as ``models/inception_fast.py`` (the definitional Flax module
stays in ``models/resnet.py``; this is a hand-written apply over the SAME
variables tree, equality-tested):

- **BN folding**: every conv here carries a bias and is followed by BN, so
  at inference ``BN(conv(x)+b)`` folds to one conv with
  ``k' = k * inv*scale`` and ``b' = (b - mean) * inv*scale + beta``.
- **Shortcut fusion**: in each stage's downsampling block the shortcut
  conv (4F out) and the main path's first conv (F out) share the input,
  kernel size (1x1) and stride — one 5F-wide conv computes both, read the
  block input from HBM once, split after.

MEASURED NEUTRAL (r3): the plain module path already reaches ~48% MFU at
b128/224 bf16 (12.2k img/s on a v5e-class chip) — ResNet's big uniform
convs are exactly what XLA tiles well, its BN is fused into conv epilogues
by XLA anyway, and only 4 blocks have a fusable shortcut pair. The fast
path measured within noise of the module (-1%), so the registry does NOT
select it; it stays as an equality-tested demonstration that the folding
technique generalizes (InceptionV3's fast path, by contrast, wins ~13%
because its many narrow branch convs underuse MXU lanes).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from sparkdl_tpu.models.layers import RESNET_BN_EPS, max_pool, pad2d

_DIMS = ("NHWC", "HWIO", "NHWC")


def _folded(params: Any, stats: Any, conv: str, bn: str, compute_dtype
            ) -> Tuple[jax.Array, jax.Array]:
    """BN-folded (kernel, bias) for a conv+BN pair (f32 math, one cast)."""
    k = jnp.asarray(params[conv]["kernel"], jnp.float32)
    b = jnp.asarray(params[conv]["bias"], jnp.float32)
    scale = jnp.asarray(params[bn]["scale"], jnp.float32)
    beta = jnp.asarray(params[bn]["bias"], jnp.float32)
    mean = jnp.asarray(stats[bn]["mean"], jnp.float32)
    var = jnp.asarray(stats[bn]["var"], jnp.float32)
    inv = jax.lax.rsqrt(var + RESNET_BN_EPS) * scale
    return ((k * inv).astype(compute_dtype),
            ((b - mean) * inv + beta).astype(compute_dtype))


def _conv(x, kernel, bias, strides=(1, 1), padding="SAME", relu=False):
    y = jax.lax.conv_general_dilated(
        x, kernel, window_strides=strides, padding=padding,
        dimension_numbers=_DIMS)
    y = y + bias
    return jax.nn.relu(y) if relu else y


def resnet_fast_apply(variables: Any, x: jax.Array,
                      stack_sizes: Sequence[int] = (3, 4, 6, 3),
                      include_top: bool = False,
                      pooling: Optional[str] = "avg",
                      compute_dtype=jnp.bfloat16) -> jax.Array:
    """Inference-only ResNet v1 forward over the standard variables tree.

    Mirrors ``models/resnet.py`` (stem pad+7x7 VALID, stride-2 on the
    first 1x1 of downsampling blocks, keras BN eps).
    """
    params = variables["params"]
    stats = variables["batch_stats"]
    x = x.astype(compute_dtype)

    k, b = _folded(params, stats, "conv1_conv", "conv1_bn", compute_dtype)
    x = _conv(pad2d(x, 3), k, b, strides=(2, 2), padding="VALID", relu=True)
    x = pad2d(x, 1)
    x = max_pool(x, 3, 2)

    for stage, blocks in enumerate(stack_sizes):
        stride = 1 if stage == 0 else 2
        for i in range(1, blocks + 1):
            name = f"conv{stage + 2}_block{i}"
            p = params[name]
            s = stats[name]
            if i == 1:
                # downsampling block: fuse shortcut conv_0 (4F) with main
                # conv_1 (F) — same input / kernel / stride
                k0, b0 = _folded(p, s, "conv_0", "bn_0", compute_dtype)
                k1, b1 = _folded(p, s, "conv_1", "bn_1", compute_dtype)
                wide = _conv(x, jnp.concatenate([k0, k1], axis=3),
                             jnp.concatenate([b0, b1], axis=0),
                             strides=(stride, stride))
                n0 = k0.shape[3]
                shortcut = wide[..., :n0]
                y = jax.nn.relu(wide[..., n0:])
            else:
                shortcut = x
                k1, b1 = _folded(p, s, "conv_1", "bn_1", compute_dtype)
                y = _conv(x, k1, b1, relu=True)
            k2, b2 = _folded(p, s, "conv_2", "bn_2", compute_dtype)
            y = _conv(y, k2, b2, relu=True)
            k3, b3 = _folded(p, s, "conv_3", "bn_3", compute_dtype)
            y = _conv(y, k3, b3)
            x = jax.nn.relu(shortcut + y)

    if include_top:
        x = jnp.mean(x, axis=(1, 2))
        p = params["predictions"]
        logits = x @ p["kernel"].astype(x.dtype) + p["bias"].astype(x.dtype)
        return jax.nn.softmax(logits)
    if pooling == "avg":
        return jnp.mean(x, axis=(1, 2))
    if pooling == "max":
        return jnp.max(x, axis=(1, 2))
    return x
