"""Generic Keras → ModelFunction ingestion (arbitrary user models).

Parity: the reference's ``GraphFunction.fromKeras`` / ``KerasTransformer``
path (SURVEY.md §2.1 ``graph/builder.py``, ``transformers/keras_tensor.py``)
accepted *arbitrary* user Keras models by exporting their TF graph. A TF
graph import makes no sense here; instead the Keras layer DAG is walked
once at ingestion time and compiled into a pure jax function over an
explicit params pytree — the idiomatic equivalent of graph freezing, and
the result jits into a single XLA program.

Supported layer set covers the reference's usage (Dense piles for
``KerasTransformer``, CNNs for the image paths); unsupported layers raise
at ingestion time with the layer name, never silently at run time.
Inference semantics throughout (BatchNorm uses moving stats, Dropout is
identity) — matching the reference, which always froze graphs for serving.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sparkdl_tpu.core.model_function import ModelFunction, TensorSpec

# Each converter: layer -> (needs_weights, fn(weights_list, *inputs) -> out)
# weights_list is the layer.get_weights() arrays (by position).

_ACTIVATIONS: Dict[str, Callable] = {
    "linear": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "softplus": jax.nn.softplus,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    # keras defaults differ from jax.nn defaults: keras gelu is exact
    # (approximate=False), keras leaky_relu slope is 0.2 (jax: 0.01)
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "exponential": jnp.exp,
    "hard_sigmoid": jax.nn.hard_sigmoid,
    # keras hard_silu/hard_swish = x * relu6(x+3)/6 — jax.nn.hard_silu's
    # exact definition (MobileNetV3's activation)
    "hard_silu": jax.nn.hard_silu,
    "hard_swish": jax.nn.hard_silu,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "leaky_relu": lambda x: jax.nn.leaky_relu(x, negative_slope=0.2),
}


def _activation_fn(activation) -> Callable:
    if activation is None:
        return _ACTIVATIONS["linear"]
    name = getattr(activation, "__name__", str(activation))
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(f"Unsupported activation {name!r}") from None


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    return tuple(int(x) for x in v)  # type: ignore[return-value]


def _require_channels_last(layer) -> None:
    """This module's converters are NHWC-only; reject channels_first at
    ingestion (the module contract: never silently wrong at run time)."""
    fmt = getattr(layer, "data_format", "channels_last")
    if fmt != "channels_last":
        raise ValueError(
            f"Unsupported data_format {fmt!r} on layer {layer.name!r} "
            f"({type(layer).__name__}); only channels_last is supported")


def _conv(x, kernel, strides, padding, dilation=(1, 1), groups=1):
    return jax.lax.conv_general_dilated(
        x, kernel, window_strides=strides, padding=padding.upper(),
        rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _depthwise(x, kernel, strides, padding, dilation=(1, 1)):
    kh, kw, cin, mult = kernel.shape
    kernel = kernel.reshape(kh, kw, 1, cin * mult)
    return _conv(x, kernel, strides, padding, dilation, groups=cin)


def _pool(x, pool, strides, padding, kind: str):
    dims = (1, pool[0], pool[1], 1)
    strides4 = (1, strides[0], strides[1], 1)
    pad = padding.upper()
    if kind == "max":
        return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims, strides4, pad)
    # avg: TF excludes padded positions from the divisor under SAME padding
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides4, pad)
    counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                   dims, strides4, pad)
    return summed / counts


def _convert_layer(layer, input_rank=None) -> Callable[[List[jnp.ndarray]], Callable]:
    """Return fn(weights, *inputs) implementing ``layer`` at inference.

    ``input_rank``: rank of the layer's input tensor at this graph node
    (layers can be shared across nodes, so rank is node context, not a
    layer attribute).
    """
    import keras

    cls = type(layer).__name__

    if cls == "InputLayer":
        return lambda w, x: x

    if cls == "Dense":
        act = _activation_fn(layer.activation)
        use_bias = layer.use_bias

        def dense(w, x):
            y = x @ w[0]
            if use_bias:
                y = y + w[1]
            return act(y)

        return dense

    if cls == "Conv2D":
        _require_channels_last(layer)
        act = _activation_fn(layer.activation)
        strides = _pair(layer.strides)
        padding = layer.padding
        dilation = _pair(layer.dilation_rate)
        use_bias = layer.use_bias
        groups = getattr(layer, "groups", 1)

        def conv(w, x):
            y = _conv(x, w[0], strides, padding, dilation, groups)
            if use_bias:
                y = y + w[1]
            return act(y)

        return conv

    if cls == "DepthwiseConv2D":
        _require_channels_last(layer)
        act = _activation_fn(layer.activation)
        strides = _pair(layer.strides)
        padding = layer.padding
        dilation = _pair(layer.dilation_rate)
        use_bias = layer.use_bias

        def dwconv(w, x):
            y = _depthwise(x, w[0], strides, padding, dilation)
            if use_bias:
                y = y + w[1]
            return act(y)

        return dwconv

    if cls == "SeparableConv2D":
        _require_channels_last(layer)
        act = _activation_fn(layer.activation)
        strides = _pair(layer.strides)
        padding = layer.padding
        dilation = _pair(layer.dilation_rate)
        use_bias = layer.use_bias

        def sepconv(w, x):
            y = _depthwise(x, w[0], strides, padding, dilation)
            y = _conv(y, w[1], (1, 1), "valid")
            if use_bias:
                y = y + w[2]
            return act(y)

        return sepconv

    if cls == "BatchNormalization":
        axis = layer.axis
        if isinstance(axis, (list, tuple)):
            axis = axis[0] if len(axis) == 1 else None
        # legacy serializations store the last axis positively (e.g. 3 for
        # NHWC); accept it whenever the node input rank confirms it is last
        if axis is None or (axis != -1 and (input_rank is None
                                            or axis != input_rank - 1)):
            raise ValueError(
                f"Unsupported BatchNormalization axis {layer.axis!r} on layer "
                f"{layer.name!r}; only the last (channel) axis is supported")
        eps = float(layer.epsilon)
        scale, center = layer.scale, layer.center

        def bn(w, x):
            i = 0
            gamma = w[i] if scale else None
            i += 1 if scale else 0
            beta = w[i] if center else None
            i += 1 if center else 0
            mean, var = w[i], w[i + 1]
            inv = jax.lax.rsqrt(var + eps)
            if gamma is not None:
                inv = inv * gamma
            y = (x - mean) * inv
            if beta is not None:
                y = y + beta
            return y

        return bn

    if cls == "Normalization":
        # keras preprocessing Normalization (EfficientNet/ConvNeXt stems):
        # (x - mean) / max(sqrt(var), eps), or the inverse map. mean/var
        # are fixed statistics (given at init or adapt()ed) — bake them at
        # ingestion; they're already reshaped broadcast-ready per axis.
        import keras as _keras

        mean = jnp.asarray(np.asarray(layer.mean), jnp.float32)
        std = jnp.maximum(
            jnp.sqrt(jnp.asarray(np.asarray(layer.variance), jnp.float32)),
            _keras.config.epsilon())
        # cast the baked constants to the INPUT dtype: f32 constants would
        # promote a bf16 activation back to f32 mid-graph
        # (with_compute_dtype inference) and break dtype-strict convs
        if bool(getattr(layer, "invert", False)):
            return lambda w, x: mean.astype(x.dtype) + x * std.astype(x.dtype)
        return lambda w, x: (x - mean.astype(x.dtype)) / std.astype(x.dtype)

    if cls == "LayerNormalization":
        axis = layer.axis
        axes = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
        eps = float(layer.epsilon)
        scale, center = layer.scale, layer.center
        if getattr(layer, "rms_scaling", False):
            raise ValueError(
                f"Unsupported LayerNormalization rms_scaling on layer "
                f"{layer.name!r}")

        def layernorm(w, x):
            mean = jnp.mean(x, axis=axes, keepdims=True)
            var = jnp.var(x, axis=axes, keepdims=True)
            y = (x - mean) * jax.lax.rsqrt(var + eps)
            i = 0
            if scale:
                y = y * w[i]
                i += 1
            if center:
                y = y + w[i]
            return y

        return layernorm

    if cls == "LayerScale":
        # keras.applications.convnext's per-channel learned scale
        return lambda w, x: x * w[0]

    if cls == "Activation":
        act = _activation_fn(layer.activation)
        return lambda w, x: act(x)

    if cls == "ReLU":
        max_value = layer.max_value
        neg = float(layer.negative_slope or 0.0)
        thresh = float(layer.threshold or 0.0)

        def relu(w, x):
            y = jnp.where(x >= thresh, x, neg * (x - thresh))
            if max_value is not None:
                y = jnp.minimum(y, float(max_value))
            return y

        return relu

    if cls == "LeakyReLU":
        alpha = float(layer.negative_slope)
        return lambda w, x: jax.nn.leaky_relu(x, alpha)

    if cls == "Softmax":
        axis = layer.axis
        return lambda w, x: jax.nn.softmax(x, axis=axis)

    if cls == "Flatten":
        return lambda w, x: x.reshape(x.shape[0], -1)

    if cls == "Reshape":
        target = tuple(layer.target_shape)
        return lambda w, x: x.reshape((x.shape[0],) + target)

    if cls in ("Dropout", "SpatialDropout1D", "SpatialDropout2D",
               "GaussianNoise", "GaussianDropout", "ActivityRegularization"):
        return lambda w, x: x

    if cls in ("MaxPooling2D", "AveragePooling2D"):
        _require_channels_last(layer)
        pool = _pair(layer.pool_size)
        strides = _pair(layer.strides or layer.pool_size)
        padding = layer.padding
        kind = "max" if cls == "MaxPooling2D" else "avg"
        return lambda w, x: _pool(x, pool, strides, padding, kind)

    if cls == "GlobalAveragePooling2D":
        _require_channels_last(layer)
        keepdims = getattr(layer, "keepdims", False)
        return lambda w, x: x.mean(axis=(1, 2), keepdims=keepdims)

    if cls == "GlobalMaxPooling2D":
        _require_channels_last(layer)
        keepdims = getattr(layer, "keepdims", False)
        return lambda w, x: x.max(axis=(1, 2), keepdims=keepdims)

    if cls == "ZeroPadding2D":
        _require_channels_last(layer)
        pad = layer.padding  # ((top, bottom), (left, right)) after keras norm
        if isinstance(pad, int):
            pad = ((pad, pad), (pad, pad))
        pad = tuple(_pair(p) for p in pad)
        cfg = ((0, 0), pad[0], pad[1], (0, 0))
        return lambda w, x: jnp.pad(x, cfg)

    if cls == "Cropping2D":
        _require_channels_last(layer)
        crop = tuple(_pair(p) for p in layer.cropping)

        def cropping(w, x):
            (t, b), (l, r) = crop
            return x[:, t:x.shape[1] - b or None, l:x.shape[2] - r or None, :]

        return cropping

    if cls == "UpSampling2D":
        _require_channels_last(layer)
        size = _pair(layer.size)
        interp = getattr(layer, "interpolation", "nearest")
        if interp == "nearest":
            return lambda w, x: jnp.repeat(jnp.repeat(x, size[0], axis=1),
                                           size[1], axis=2)
        if interp in ("bilinear", "bicubic"):
            method = {"bilinear": "linear", "bicubic": "cubic"}[interp]

            def upsample(w, x):
                shape = (x.shape[0], x.shape[1] * size[0],
                         x.shape[2] * size[1], x.shape[3])
                return jax.image.resize(x, shape, method=method)

            return upsample
        raise ValueError(
            f"Unsupported UpSampling2D interpolation {interp!r}")

    if cls == "Rescaling":
        scale = float(layer.scale)
        offset = float(layer.offset)
        return lambda w, x: x * scale + offset

    if cls == "Add":
        return lambda w, *xs: sum(xs[1:], xs[0])

    if cls == "Subtract":
        return lambda w, a, b: a - b

    if cls == "Multiply":
        def multiply(w, *xs):
            y = xs[0]
            for x in xs[1:]:
                y = y * x
            return y

        return multiply

    if cls == "Average":
        return lambda w, *xs: sum(xs[1:], xs[0]) / len(xs)

    if cls == "Maximum":
        def maximum(w, *xs):
            y = xs[0]
            for x in xs[1:]:
                y = jnp.maximum(y, x)
            return y

        return maximum

    if cls == "Concatenate":
        axis = layer.axis
        return lambda w, *xs: jnp.concatenate(xs, axis=axis)

    if isinstance(layer, keras.Model):
        steps, out_ids, in_ids = _walk_graph(layer)

        def nested(w, *xs):
            # nested model weights were flattened into one list per submodel
            return _run_steps(steps, dict(zip(in_ids, xs)), w, out_ids)[0]

        return nested

    raise ValueError(
        f"Unsupported Keras layer type {cls!r} (layer {layer.name!r}); "
        f"supported: Dense/Conv/BN/activations/pooling/merge/reshape layers")


# ---------------------------------------------------------------------------
# Graph walk
# ---------------------------------------------------------------------------

def _walk_graph(model):
    """Keras functional graph → ordered steps [(name, fn, in_ids, out_ids)].

    Uses ``_nodes_by_depth`` (depth-descending = topological order). Tensor
    identity is the KerasTensor object id — stable because the graph owns
    the tensor objects.
    """
    graph = getattr(model, "_functional", None) or model  # Sequential wraps
    steps = []
    for depth, nodes in sorted(graph._nodes_by_depth.items(), reverse=True):
        for node in nodes:
            op = node.operation
            in_tensors = node.input_tensors
            rank = len(in_tensors[0].shape) if in_tensors else None
            fn = _convert_layer(op, input_rank=rank)
            in_ids = [id(t) for t in in_tensors]
            out_ids = [id(t) for t in node.outputs]
            steps.append((op.name, fn, in_ids, out_ids))
    return (steps, [id(t) for t in graph.outputs],
            [id(t) for t in graph.inputs])


def _run_steps(steps, env: Dict[int, Any], weights: Dict[str, List], out_ids):
    for name, fn, in_ids, step_out_ids in steps:
        if all(i in env for i in step_out_ids):
            continue  # InputLayer outputs seeded by caller
        xs = [env[i] for i in in_ids]
        y = fn(weights.get(name, ()), *xs)
        outs = y if isinstance(y, (tuple, list)) else (y,)
        for i, v in zip(step_out_ids, outs):
            env[i] = v
    return [env[i] for i in out_ids]


def _collect_weights_and_mask(model):
    """One traversal → ({layer_name: [arrays]}, {layer_name: [bools]}).

    The two pytrees are leaf-for-leaf congruent BY CONSTRUCTION (one loop,
    one inclusion condition) — ``optax.multi_transform`` requires exact
    treedef match between params and the trainable mask. True = trainable;
    keras marks e.g. BatchNorm ``moving_mean``/``moving_variance`` (and any
    frozen layer's weights) non-trainable, and the Trainer freezes those so
    fine-tuning cannot corrupt normalization statistics.
    """
    import keras

    weights: Dict[str, List[np.ndarray]] = {}
    mask: Dict[str, List[bool]] = {}
    for layer in model.layers:
        if isinstance(layer, keras.Model):
            # nested models receive their whole dict as "weights"
            sub_w, sub_m = _collect_weights_and_mask(layer)
            weights[layer.name] = sub_w  # type: ignore[assignment]
            mask[layer.name] = sub_m  # type: ignore[assignment]
        elif layer.weights:
            weights[layer.name] = [np.asarray(v) for v in layer.weights]
            mask[layer.name] = [bool(v.trainable) for v in layer.weights]
    return weights, mask


def _io_name(tensor) -> str:
    """Stable IO key for a model boundary tensor: the owning layer's name
    (``keras.Input(name="a")`` → InputLayer "a"; outputs take the producing
    layer's name — the upstream ``TFTransformer`` mapped by the analogous
    TF tensor names, SURVEY.md §2.1)."""
    history = getattr(tensor, "_keras_history", None)
    op = getattr(history, "operation", None) if history is not None else None
    if op is not None:
        return op.name
    return getattr(tensor, "name", "tensor")


def keras_to_model_function(model, name: str = None) -> ModelFunction:
    """Ingest a built Keras model (Sequential or functional) as a
    ModelFunction; the layer DAG becomes one jax-traceable pure function.

    Multi-input models yield a ``{input-name: TensorSpec}`` dict spec and
    take a dict of arrays; multi-output models return
    ``{output-name: array}`` — feeding ``TPUTransformer``'s
    ``inputMapping``/``outputMapping`` path.
    """
    if not getattr(model, "built", True):
        raise ValueError("Keras model must be built (call it or pass Input)")

    steps, out_ids, in_ids = _walk_graph(model)
    weights, mask = _collect_weights_and_mask(model)

    def spec_of(t) -> TensorSpec:
        return TensorSpec(
            tuple(None if d is None else int(d) for d in t.shape), "float32")

    multi_out = len(model.outputs) > 1
    output_names = [_io_name(t) for t in model.outputs]
    if len(set(output_names)) != len(output_names):
        raise ValueError(
            f"Model output names are not unique ({output_names}); a shared "
            "layer producing several outputs needs distinct terminal "
            "layers (e.g. Identity/Activation with names) so outputs can "
            "be addressed by name")

    if len(model.inputs) == 1:
        spec = spec_of(model.inputs[0])

        def apply_fn(vs, x):
            outs = _run_steps(steps, {in_ids[0]: x}, vs, out_ids)
            if multi_out:
                return dict(zip(output_names, outs))
            return outs[0]
    else:
        input_names = [_io_name(t) for t in model.inputs]
        if len(set(input_names)) != len(input_names):
            raise ValueError(
                f"Model input names are not unique ({input_names}); name "
                "your keras.Input layers distinctly")
        spec = {n: spec_of(t) for n, t in zip(input_names, model.inputs)}

        def apply_fn(vs, x):
            env = {tid: x[n] for n, tid in zip(input_names, in_ids)}
            outs = _run_steps(steps, env, vs, out_ids)
            if multi_out:
                return dict(zip(output_names, outs))
            return outs[0]

    return ModelFunction(apply_fn, jax.tree.map(jnp.asarray, weights), spec,
                         name=name or model.name, trainable_mask=mask)
