"""MobileNetV2 in Flax — keras.applications.mobilenet_v2 parity.

The reference's fine-tune target (BASELINE.json config 4:
``KerasImageFileEstimator fine-tune MobileNetV2``): 224x224, [-1,1]
preprocessing, 1280-d features.

Inverted residual blocks per the Keras table; BN eps 1e-3 momentum .999;
ReLU6; stride-2 depthwise convs use keras ``correct_pad`` + VALID (NOT
SAME — the asymmetric pad differs).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from sparkdl_tpu.models.layers import (
    classifier_head, correct_pad, global_avg_pool, pad2d,
)

MNV2_BN_EPS = 1e-3


def _make_divisible(v: float, divisor: int = 8,
                    min_value: Optional[int] = None) -> int:
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def relu6(x):
    return jnp.minimum(jnp.maximum(x, 0.0), 6.0)


class InvertedResBlock(nn.Module):
    filters: int
    stride: int
    expansion: int
    alpha: float = 1.0
    block_id: int = 0
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, epsilon=MNV2_BN_EPS,
            momentum=0.999, dtype=self.dtype, name=name)
        inputs = x
        in_ch = x.shape[-1]
        pointwise = _make_divisible(int(self.filters * self.alpha))

        if self.block_id:
            x = nn.Conv(self.expansion * in_ch, (1, 1), use_bias=False,
                        dtype=self.dtype, name="expand")(x)
            x = relu6(bn("expand_bn")(x))

        if self.stride == 2:
            x = pad2d(x, correct_pad(x, 3))
            dw_pad = "VALID"
        else:
            dw_pad = "SAME"
        ch = x.shape[-1]
        x = nn.Conv(ch, (3, 3), strides=(self.stride, self.stride),
                    padding=dw_pad, feature_group_count=ch, use_bias=False,
                    dtype=self.dtype, name="depthwise")(x)
        x = relu6(bn("depthwise_bn")(x))

        x = nn.Conv(pointwise, (1, 1), use_bias=False, dtype=self.dtype,
                    name="project")(x)
        x = bn("project_bn")(x)

        if in_ch == pointwise and self.stride == 1:
            return inputs + x
        return x


# (filters, stride, expansion) per block, keras order.
MNV2_BLOCKS = (
    (16, 1, 1),
    (24, 2, 6), (24, 1, 6),
    (32, 2, 6), (32, 1, 6), (32, 1, 6),
    (64, 2, 6), (64, 1, 6), (64, 1, 6), (64, 1, 6),
    (96, 1, 6), (96, 1, 6), (96, 1, 6),
    (160, 2, 6), (160, 1, 6), (160, 1, 6),
    (320, 1, 6),
)


class MobileNetV2(nn.Module):
    alpha: float = 1.0
    include_top: bool = True
    classes: int = 1000
    classifier_activation: Optional[str] = "softmax"
    pooling: Optional[str] = "avg"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, epsilon=MNV2_BN_EPS,
            momentum=0.999, dtype=self.dtype, name=name)

        first = _make_divisible(32 * self.alpha)
        x = nn.Conv(first, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=self.dtype, name="Conv1")(x)
        x = relu6(bn("Conv1_bn")(x))

        for bid, (f, s, e) in enumerate(MNV2_BLOCKS):
            x = InvertedResBlock(f, s, e, alpha=self.alpha, block_id=bid,
                                 dtype=self.dtype, name=f"block_{bid}")(
                                     x, train)

        last = _make_divisible(1280 * self.alpha) if self.alpha > 1.0 else 1280
        x = nn.Conv(last, (1, 1), use_bias=False, dtype=self.dtype,
                    name="Conv_1")(x)
        x = relu6(bn("Conv_1_bn")(x))

        if self.include_top:
            x = global_avg_pool(x)
            return classifier_head(x, self.classes,
                                   self.classifier_activation, self.dtype)
        if self.pooling == "avg":
            return global_avg_pool(x)
        if self.pooling == "max":
            return jnp.max(x, axis=(1, 2))
        return x
