"""InceptionV3 in Flax — keras.applications.inception_v3 parity.

The reference's flagship featurizer model (``DeepImageFeaturizer
modelName="InceptionV3"``, SURVEY.md §3.1): 299x299 input, [-1,1]
preprocessing, 2048-d pre-logit features.

Every conv is ConvBN (no bias, BN scale=False, eps 1e-3); block structure
matched line-by-line to keras.src.applications.inception_v3 (mixed0..10).
ConvBN units are named ``cb{i}`` in call order — the weight converter maps
Keras's Conv2D/BatchNormalization build order onto the same indices.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from sparkdl_tpu.models.layers import (
    ConvBN, avg_pool_same, classifier_head, global_avg_pool, max_pool,
)


class InceptionV3(nn.Module):
    include_top: bool = True
    classes: int = 1000
    classifier_activation: Optional[str] = "softmax"
    pooling: Optional[str] = "avg"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        idx = [0]

        def cb(h, features, kh, kw, strides=(1, 1), padding="SAME"):
            # kernel_family opts eligible 1x1 units into the fused pw1x1
            # registry (core/kernels.py accept-if-faster autotune).
            m = ConvBN(features, (kh, kw), strides=strides, padding=padding,
                       bn_scale=False, dtype=self.dtype, name=f"cb{idx[0]}",
                       kernel_family="inception")
            idx[0] += 1
            return m(h, train)

        # Stem
        x = cb(x, 32, 3, 3, strides=(2, 2), padding="VALID")
        x = cb(x, 32, 3, 3, padding="VALID")
        x = cb(x, 64, 3, 3)
        x = max_pool(x, 3, 2)
        x = cb(x, 80, 1, 1, padding="VALID")
        x = cb(x, 192, 3, 3, padding="VALID")
        x = max_pool(x, 3, 2)

        # mixed 0..2: 35x35 inception-A blocks (pool branch 32, 64, 64)
        for pool_features in (32, 64, 64):
            b1 = cb(x, 64, 1, 1)
            b5 = cb(x, 48, 1, 1)
            b5 = cb(b5, 64, 5, 5)
            b3 = cb(x, 64, 1, 1)
            b3 = cb(b3, 96, 3, 3)
            b3 = cb(b3, 96, 3, 3)
            bp = avg_pool_same(x)
            bp = cb(bp, pool_features, 1, 1)
            x = jnp.concatenate([b1, b5, b3, bp], axis=-1)

        # mixed 3: 17x17 reduction
        b3 = cb(x, 384, 3, 3, strides=(2, 2), padding="VALID")
        bd = cb(x, 64, 1, 1)
        bd = cb(bd, 96, 3, 3)
        bd = cb(bd, 96, 3, 3, strides=(2, 2), padding="VALID")
        bp = max_pool(x, 3, 2)
        x = jnp.concatenate([b3, bd, bp], axis=-1)

        # mixed 4..7: 17x17 inception-B blocks (7x7 factorized)
        for c7 in (128, 160, 160, 192):
            b1 = cb(x, 192, 1, 1)
            b7 = cb(x, c7, 1, 1)
            b7 = cb(b7, c7, 1, 7)
            b7 = cb(b7, 192, 7, 1)
            bd = cb(x, c7, 1, 1)
            bd = cb(bd, c7, 7, 1)
            bd = cb(bd, c7, 1, 7)
            bd = cb(bd, c7, 7, 1)
            bd = cb(bd, 192, 1, 7)
            bp = avg_pool_same(x)
            bp = cb(bp, 192, 1, 1)
            x = jnp.concatenate([b1, b7, bd, bp], axis=-1)

        # mixed 8: 8x8 reduction
        b3 = cb(x, 192, 1, 1)
        b3 = cb(b3, 320, 3, 3, strides=(2, 2), padding="VALID")
        b7 = cb(x, 192, 1, 1)
        b7 = cb(b7, 192, 1, 7)
        b7 = cb(b7, 192, 7, 1)
        b7 = cb(b7, 192, 3, 3, strides=(2, 2), padding="VALID")
        bp = max_pool(x, 3, 2)
        x = jnp.concatenate([b3, b7, bp], axis=-1)

        # mixed 9..10: 8x8 inception-C blocks (split 3x3 branches)
        for _ in range(2):
            b1 = cb(x, 320, 1, 1)
            b3 = cb(x, 384, 1, 1)
            b3a = cb(b3, 384, 1, 3)
            b3b = cb(b3, 384, 3, 1)
            b3 = jnp.concatenate([b3a, b3b], axis=-1)
            bd = cb(x, 448, 1, 1)
            bd = cb(bd, 384, 3, 3)
            bda = cb(bd, 384, 1, 3)
            bdb = cb(bd, 384, 3, 1)
            bd = jnp.concatenate([bda, bdb], axis=-1)
            bp = avg_pool_same(x)
            bp = cb(bp, 192, 1, 1)
            x = jnp.concatenate([b1, b3, bd, bp], axis=-1)

        if self.include_top:
            x = global_avg_pool(x)
            return classifier_head(x, self.classes,
                                   self.classifier_activation, self.dtype)
        if self.pooling == "avg":
            return global_avg_pool(x)
        if self.pooling == "max":
            return jnp.max(x, axis=(1, 2))
        return x
