"""Keras → Flax weight conversion for the model zoo.

The reference consumed Keras models directly (``KerasImageFileTransformer``
takes an HDF5 model file; ``DeepImageFeaturizer`` ships frozen graphs —
SURVEY.md §2.1). The TPU rebuild runs Flax modules, so parity requires a
faithful weight converter. Layout facts making this mostly copy-through:

- Keras Conv2D kernels are HWIO — exactly flax ``nn.Conv``.
- Keras DepthwiseConv2D kernels are (H, W, C, mult); flax expresses
  depthwise as ``feature_group_count=C`` with kernel (H, W, 1, C*mult) —
  a reshape-transpose.
- Keras BatchNormalization weights are [gamma?, beta?, mean, var] by
  layer flags → flax params {scale, bias} + batch_stats {mean, var}.

Correspondence is by LAYER NAME for the families with deterministic
semantic names (ResNet, VGG, MobileNetV2, most of Xception) and by
build-order (the numeric suffix Keras appends to auto-generated names —
stable within one model instance) for InceptionV3 and Xception's unnamed
residual projections. Conversions are validated by the numerical oracle
tests in tests/models/ (same input through Keras and Flax, outputs equal).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

import numpy as np


def _suffix_order(name: str) -> int:
    m = re.search(r"_(\d+)$", name)
    return int(m.group(1)) if m else 0


def _ordered_auto(layers, base: str) -> List:
    """Layers whose name is ``base`` or ``base_N``, in build (suffix) order."""
    hits = [l for l in layers
            if l.name == base or re.fullmatch(re.escape(base) + r"_\d+", l.name)]
    return sorted(hits, key=lambda l: _suffix_order(l.name))


def _put(tree: Dict, path: Tuple[str, ...], leaf_name: str, value) -> None:
    node = tree
    for key in path:
        node = node.setdefault(key, {})
    node[leaf_name] = np.asarray(value)


class _Builder:
    """Accumulates params/batch_stats trees from keras layers."""

    def __init__(self) -> None:
        self.params: Dict[str, Any] = {}
        self.batch_stats: Dict[str, Any] = {}

    def conv(self, layer, *path: str) -> None:
        weights = layer.get_weights()
        _put(self.params, path, "kernel", weights[0])
        if layer.use_bias:
            _put(self.params, path, "bias", weights[1])

    def depthwise(self, layer, *path: str) -> None:
        (kernel,) = layer.get_weights()[:1]
        kh, kw, c, mult = kernel.shape
        flax_kernel = kernel.transpose(0, 1, 3, 2).reshape(kh, kw, 1, c * mult)
        _put(self.params, path, "kernel", flax_kernel)

    def separable(self, layer, *path: str) -> None:
        dw, pw = layer.get_weights()[:2]
        kh, kw, c, mult = dw.shape
        _put(self.params, path + ("depthwise",), "kernel",
             dw.transpose(0, 1, 3, 2).reshape(kh, kw, 1, c * mult))
        _put(self.params, path + ("pointwise",), "kernel", pw)

    def bn(self, layer, *path: str) -> None:
        weights = list(layer.get_weights())
        if layer.scale:
            _put(self.params, path, "scale", weights.pop(0))
        if layer.center:
            _put(self.params, path, "bias", weights.pop(0))
        _put(self.batch_stats, path, "mean", weights.pop(0))
        _put(self.batch_stats, path, "var", weights.pop(0))

    def dense(self, layer, *path: str) -> None:
        weights = layer.get_weights()
        _put(self.params, path, "kernel", weights[0])
        if layer.use_bias:
            _put(self.params, path, "bias", weights[1])

    def variables(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"params": self.params}
        if self.batch_stats:
            out["batch_stats"] = self.batch_stats
        return out


def _by_name(keras_model) -> Dict[str, Any]:
    return {l.name: l for l in keras_model.layers}


# ---------------------------------------------------------------------------
# Per-family converters
# ---------------------------------------------------------------------------

def convert_inception_v3(keras_model) -> Dict[str, Any]:
    """conv2d_N / batch_normalization_N build order ↔ cb{i} call order."""
    import keras as K

    convs = _ordered_auto(
        [l for l in keras_model.layers if isinstance(l, K.layers.Conv2D)],
        "conv2d")
    bns = _ordered_auto(
        [l for l in keras_model.layers
         if isinstance(l, K.layers.BatchNormalization)],
        "batch_normalization")
    if len(convs) != len(bns):
        raise ValueError(f"conv/bn count mismatch: {len(convs)} vs {len(bns)}")
    b = _Builder()
    for i, (conv, bn_layer) in enumerate(zip(convs, bns)):
        b.conv(conv, f"cb{i}", "conv")
        b.bn(bn_layer, f"cb{i}", "bn")
    layers = _by_name(keras_model)
    if "predictions" in layers:
        b.dense(layers["predictions"], "predictions")
    return b.variables()


def convert_resnet(keras_model, stack_sizes=(3, 4, 6, 3)) -> Dict[str, Any]:
    layers = _by_name(keras_model)
    b = _Builder()
    b.conv(layers["conv1_conv"], "conv1_conv")
    b.bn(layers["conv1_bn"], "conv1_bn")
    for stage, blocks in enumerate(stack_sizes):
        s = stage + 2
        for blk in range(1, blocks + 1):
            prefix = f"conv{s}_block{blk}"
            slots = [("0", True)] if blk == 1 else []
            slots += [("1", False), ("2", False), ("3", False)]
            for j, _is_shortcut in slots:
                b.conv(layers[f"{prefix}_{j}_conv"], prefix, f"conv_{j}")
                b.bn(layers[f"{prefix}_{j}_bn"], prefix, f"bn_{j}")
    if "predictions" in layers:
        b.dense(layers["predictions"], "predictions")
    return b.variables()


def convert_vgg(keras_model, convs_per_block=(2, 2, 3, 3, 3)) -> Dict[str, Any]:
    layers = _by_name(keras_model)
    b = _Builder()
    for blk, n in enumerate(convs_per_block, 1):
        for c in range(1, n + 1):
            name = f"block{blk}_conv{c}"
            b.conv(layers[name], name)
    for name in ("fc1", "fc2", "predictions"):
        if name in layers:
            b.dense(layers[name], name)
    return b.variables()


def convert_xception(keras_model) -> Dict[str, Any]:
    import keras as K

    layers = _by_name(keras_model)
    b = _Builder()
    b.conv(layers["block1_conv1"], "block1_conv1")
    b.bn(layers["block1_conv1_bn"], "block1_conv1_bn")
    b.conv(layers["block1_conv2"], "block1_conv2")
    b.bn(layers["block1_conv2_bn"], "block1_conv2_bn")
    # The four residual projection convs/bns are unnamed in keras source;
    # build order maps them to blocks 2, 3, 4, 13.
    res_convs = _ordered_auto(
        [l for l in keras_model.layers
         if isinstance(l, K.layers.Conv2D)
         and not isinstance(l, K.layers.SeparableConv2D)], "conv2d")
    res_bns = _ordered_auto(
        [l for l in keras_model.layers
         if isinstance(l, K.layers.BatchNormalization)],
        "batch_normalization")
    for block_id, conv, bn_layer in zip((2, 3, 4, 13), res_convs, res_bns):
        b.conv(conv, f"block{block_id}_res_conv")
        b.bn(bn_layer, f"block{block_id}_res_bn")
    sep_blocks = ([(i, ("sepconv1", "sepconv2")) for i in (2, 3, 4)]
                  + [(i, ("sepconv1", "sepconv2", "sepconv3"))
                     for i in range(5, 13)]
                  + [(13, ("sepconv1", "sepconv2")),
                     (14, ("sepconv1", "sepconv2"))])
    for block_id, seps in sep_blocks:
        for sep in seps:
            name = f"block{block_id}_{sep}"
            b.separable(layers[name], name)
            # flax SeparableConvBN nests its BatchNorm as <name>/bn
            b.bn(layers[f"{name}_bn"], name, "bn")
    if "predictions" in layers:
        b.dense(layers["predictions"], "predictions")
    return b.variables()


def convert_mobilenet_v2(keras_model, num_blocks: int = 17) -> Dict[str, Any]:
    layers = _by_name(keras_model)
    b = _Builder()
    b.conv(layers["Conv1"], "Conv1")
    b.bn(layers["bn_Conv1"], "Conv1_bn")
    for bid in range(num_blocks):
        prefix = "expanded_conv_" if bid == 0 else f"block_{bid}_"
        flax_block = f"block_{bid}"
        if bid:
            b.conv(layers[f"{prefix}expand"], flax_block, "expand")
            b.bn(layers[f"{prefix}expand_BN"], flax_block, "expand_bn")
        b.depthwise(layers[f"{prefix}depthwise"], flax_block, "depthwise")
        b.bn(layers[f"{prefix}depthwise_BN"], flax_block, "depthwise_bn")
        b.conv(layers[f"{prefix}project"], flax_block, "project")
        b.bn(layers[f"{prefix}project_BN"], flax_block, "project_bn")
    b.conv(layers["Conv_1"], "Conv_1")
    b.bn(layers["Conv_1_bn"], "Conv_1_bn")
    if "predictions" in layers:
        b.dense(layers["predictions"], "predictions")
    return b.variables()


_CONVERTERS = {
    "InceptionV3": convert_inception_v3,
    "ResNet50": convert_resnet,
    "Xception": convert_xception,
    "VGG16": lambda m: convert_vgg(m, (2, 2, 3, 3, 3)),
    "VGG19": lambda m: convert_vgg(m, (2, 2, 4, 4, 4)),
    "MobileNetV2": convert_mobilenet_v2,
}


def convert_keras_model(model_name: str, keras_model) -> Dict[str, Any]:
    """Convert a keras.applications-architecture model to Flax variables."""
    try:
        converter = _CONVERTERS[model_name]
    except KeyError:
        raise ValueError(
            f"No converter for {model_name!r}; supported: "
            f"{sorted(_CONVERTERS)}") from None
    return converter(keras_model)


def load_keras_file(path: str):
    """Load a Keras model file (H5 / .keras) using the in-env keras."""
    import keras

    return keras.models.load_model(path, compile=False)
