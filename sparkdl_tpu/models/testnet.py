"""TestNet — tiny deterministic CNN for fast tests.

Parity: the reference packaged a deterministic ``TestNet`` graph resource so
featurizer tests don't download weights (Scala ``Models.scala``, SURVEY.md
§2.2/§4). Same idea: a small fixed architecture, seeded init, 32x32 input.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from sparkdl_tpu.models.layers import classifier_head, global_avg_pool


class TestNet(nn.Module):
    include_top: bool = True
    classes: int = 10
    classifier_activation: Optional[str] = "softmax"
    pooling: Optional[str] = "avg"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(8, (3, 3), strides=(2, 2), padding="SAME",
                    dtype=self.dtype, name="conv1")(x)
        x = nn.relu(x)
        x = nn.Conv(16, (3, 3), strides=(2, 2), padding="SAME",
                    dtype=self.dtype, name="conv2")(x)
        x = nn.relu(x)
        if self.include_top:
            x = global_avg_pool(x)
            return classifier_head(x, self.classes,
                                   self.classifier_activation, self.dtype)
        if self.pooling == "avg":
            return global_avg_pool(x)
        if self.pooling == "max":
            return jnp.max(x, axis=(1, 2))
        return x
