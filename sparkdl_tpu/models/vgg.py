"""VGG16/VGG19 in Flax — keras.applications.vgg16/vgg19 parity.

Named models in the reference registry (SURVEY.md §2.1): 224x224,
caffe-style preprocessing. The reference's featurize layer for VGG is the
fc2 4096-d activation (not GAP), so ``include_top=False`` here supports
``pooling=None/'avg'/'max'`` like Keras, and the registry featurizes VGG
through the dense head (see registry.py).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from sparkdl_tpu.models.layers import classifier_head, global_avg_pool


class VGG(nn.Module):
    """``convs_per_block``: e.g. (2, 2, 3, 3, 3) for VGG16."""

    convs_per_block: Sequence[int] = (2, 2, 3, 3, 3)
    include_top: bool = True
    classes: int = 1000
    classifier_activation: Optional[str] = "softmax"
    pooling: Optional[str] = None
    # When True and include_top, stop after fc2 (the reference's VGG
    # featurize layer).
    features_at_fc2: bool = False
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        filters = (64, 128, 256, 512, 512)
        for b, (f, n) in enumerate(zip(filters, self.convs_per_block), 1):
            for c in range(1, n + 1):
                x = nn.Conv(f, (3, 3), padding="SAME", dtype=self.dtype,
                            name=f"block{b}_conv{c}")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))

        if self.include_top:
            x = x.reshape(x.shape[0], -1)  # Flatten, keras order (NHWC)
            x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc1")(x))
            x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc2")(x))
            if self.features_at_fc2:
                return x
            return classifier_head(x, self.classes,
                                   self.classifier_activation, self.dtype)
        if self.pooling == "avg":
            return global_avg_pool(x)
        if self.pooling == "max":
            return jnp.max(x, axis=(1, 2))
        return x


def VGG16(**kwargs) -> VGG:
    return VGG(convs_per_block=(2, 2, 3, 3, 3), **kwargs)


def VGG19(**kwargs) -> VGG:
    return VGG(convs_per_block=(2, 2, 4, 4, 4), **kwargs)
