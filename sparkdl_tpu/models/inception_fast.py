"""Fused inference fast path for InceptionV3.

``models/inception.py`` is the *definitional* implementation (Flax module,
keras.applications parity, used for training and weight conversion). This
module is a hand-written JAX apply over the SAME variables tree, optimized
for TPU inference:

- **BN folding**: at inference BatchNorm is an affine map, so it folds into
  the (bias-free) conv as ``k' = k * rsqrt(var+eps)``, ``b' = bias -
  mean * rsqrt(var+eps)`` — the conv epilogue becomes one bias-add + ReLU.
- **Branch fusion**: the parallel 1x1 convs at the head of every inception
  block consume the same input, so ``concat_F(conv_1, conv_2, conv_3)`` is
  rewritten as ONE conv with kernels concatenated along the output-channel
  axis. Each output channel's math is unchanged (bitwise, per channel, up
  to float reassociation); the MXU sees 176-1152 output lanes instead of
  three 48-448 passes, and the block input is read from HBM once instead
  of three times.
- **Pool-branch as conv — tried and REVERTED** (r4, measured): the
  ``avg_pool(3x3) -> 1x1 projection`` branch rewrites exactly as a dense
  3x3 conv (projection at all 9 taps + positional edge-count scale),
  which moves the HBM-roofline-bound ``reduce_window`` onto the MXU.
  Same-process A/B measured it 14% SLOWER whole-model (8,490 vs 9,815
  img/s): the 9x FLOPs on the small-output-channel projections (32-192)
  outweigh the pool's one HBM round trip. ``_cb_pool`` keeps the exact
  pool+project composition; see docs/PERF.md.

Parity with the module is asserted by ``tests/models/test_inception_fast.py``
(f32 CPU equality) and the call order mirrors ``inception.py`` cb-index for
cb-index — any architecture drift fails the test.

Reference parity note: the reference ran frozen TF graphs through
grappler's constant-folding/fusion (SURVEY.md §2.1 graph utils); this is
the TPU-native analog — an inference-specialized program over identical
weights.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from sparkdl_tpu.models.layers import (
    KERAS_BN_EPS, avg_pool_same, global_avg_pool, max_pool,
)

_DIMS = ("NHWC", "HWIO", "NHWC")


def _folded(variables: Any, idx: int, compute_dtype) -> Tuple[jax.Array, jax.Array]:
    """BN-folded (kernel, bias) for ConvBN unit ``cb{idx}``.

    Folding runs in f32 on weight-sized tensors (negligible next to the
    conv) and casts once to the compute dtype.
    """
    p = variables["params"][f"cb{idx}"]
    s = variables["batch_stats"][f"cb{idx}"]["bn"]
    k = jnp.asarray(p["conv"]["kernel"], jnp.float32)
    bias = jnp.asarray(p["bn"]["bias"], jnp.float32)
    scale = p["bn"].get("scale")
    inv = jax.lax.rsqrt(jnp.asarray(s["var"], jnp.float32) + KERAS_BN_EPS)
    if scale is not None:
        inv = inv * jnp.asarray(scale, jnp.float32)
    kf = k * inv  # [kh,kw,cin,F] * [F]
    bf = bias - jnp.asarray(s["mean"], jnp.float32) * inv
    return kf.astype(compute_dtype), bf.astype(compute_dtype)


def _conv(x, kernel, bias, strides=(1, 1), padding="SAME", relu=True):
    y = jax.lax.conv_general_dilated(
        x, kernel, window_strides=strides, padding=padding,
        dimension_numbers=_DIMS)
    y = y + bias
    return jax.nn.relu(y) if relu else y


def _cb(variables, x, idx, strides=(1, 1), padding="SAME"):
    k, b = _folded(variables, idx, x.dtype)
    return _conv(x, k, b, strides, padding)


def _cb_pool(variables, x, idx):
    """Inception pool branch: ``avg_pool_same(x)`` then 1x1 ConvBN.

    The pool-as-dense-3x3-conv rewrite was measured 14% slower
    whole-model (see module docstring) — keep the straightforward form.
    """
    return _cb(variables, avg_pool_same(x), idx)


def _cb_fused(variables, x, idxs: Sequence[int]) -> Tuple[jax.Array, ...]:
    """The parallel 1x1 ConvBN heads ``idxs`` as ONE conv; returns splits."""
    folded = [_folded(variables, i, x.dtype) for i in idxs]
    k = jnp.concatenate([f[0] for f in folded], axis=3)
    b = jnp.concatenate([f[1] for f in folded], axis=0)
    y = _conv(x, k, b)
    sizes = [f[0].shape[3] for f in folded]
    outs, off = [], 0
    for n in sizes:
        outs.append(y[..., off:off + n])
        off += n
    return tuple(outs)


def inception_v3_fast_apply(variables: Any, x: jax.Array,
                            include_top: bool = False,
                            pooling: Optional[str] = "avg",
                            compute_dtype=jnp.bfloat16) -> jax.Array:
    """Inference-only InceptionV3 forward over the standard variables tree.

    Call order mirrors ``models/inception.py`` exactly (cb0..cb93); see
    module docstring for the fusion rules applied.
    """
    x = x.astype(compute_dtype)

    # Stem
    x = _cb(variables, x, 0, strides=(2, 2), padding="VALID")
    x = _cb(variables, x, 1, padding="VALID")
    x = _cb(variables, x, 2)
    x = max_pool(x, 3, 2)
    x = _cb(variables, x, 3, padding="VALID")
    x = _cb(variables, x, 4, padding="VALID")
    x = max_pool(x, 3, 2)

    # mixed 0..2: 35x35 inception-A
    idx = 5
    for _ in range(3):
        b1, b5, b3 = _cb_fused(variables, x, (idx, idx + 1, idx + 3))
        b5 = _cb(variables, b5, idx + 2)                    # 5x5
        b3 = _cb(variables, b3, idx + 4)
        b3 = _cb(variables, b3, idx + 5)
        bp = _cb_pool(variables, x, idx + 6)
        x = jnp.concatenate([b1, b5, b3, bp], axis=-1)
        idx += 7

    # mixed 3: reduction (idx == 26)
    b3 = _cb(variables, x, idx, strides=(2, 2), padding="VALID")
    bd = _cb(variables, x, idx + 1)
    bd = _cb(variables, bd, idx + 2)
    bd = _cb(variables, bd, idx + 3, strides=(2, 2), padding="VALID")
    bp = max_pool(x, 3, 2)
    x = jnp.concatenate([b3, bd, bp], axis=-1)
    idx += 4

    # mixed 4..7: 17x17 inception-B (idx == 30)
    for _ in range(4):
        b1, b7, bd = _cb_fused(variables, x, (idx, idx + 1, idx + 4))
        b7 = _cb(variables, b7, idx + 2)                    # 1x7
        b7 = _cb(variables, b7, idx + 3)                    # 7x1
        bd = _cb(variables, bd, idx + 5)                    # 7x1
        bd = _cb(variables, bd, idx + 6)                    # 1x7
        bd = _cb(variables, bd, idx + 7)                    # 7x1
        bd = _cb(variables, bd, idx + 8)                    # 1x7
        bp = _cb_pool(variables, x, idx + 9)
        x = jnp.concatenate([b1, b7, bd, bp], axis=-1)
        idx += 10

    # mixed 8: reduction (idx == 70)
    b3, b7 = _cb_fused(variables, x, (idx, idx + 2))
    b3 = _cb(variables, b3, idx + 1, strides=(2, 2), padding="VALID")
    b7 = _cb(variables, b7, idx + 3)                        # 1x7
    b7 = _cb(variables, b7, idx + 4)                        # 7x1
    b7 = _cb(variables, b7, idx + 5, strides=(2, 2), padding="VALID")
    bp = max_pool(x, 3, 2)
    x = jnp.concatenate([b3, b7, bp], axis=-1)
    idx += 6

    # mixed 9..10: 8x8 inception-C (idx == 76)
    for _ in range(2):
        b1, b3, bd = _cb_fused(variables, x, (idx, idx + 1, idx + 4))
        b3a = _cb(variables, b3, idx + 2)                   # 1x3
        b3b = _cb(variables, b3, idx + 3)                   # 3x1
        b3 = jnp.concatenate([b3a, b3b], axis=-1)
        bd = _cb(variables, bd, idx + 5)                    # 3x3
        bda = _cb(variables, bd, idx + 6)                   # 1x3
        bdb = _cb(variables, bd, idx + 7)                   # 3x1
        bd = jnp.concatenate([bda, bdb], axis=-1)
        bp = _cb_pool(variables, x, idx + 8)
        x = jnp.concatenate([b1, b3, bd, bp], axis=-1)
        idx += 9

    if include_top:
        x = global_avg_pool(x)
        p = variables["params"]["predictions"]
        logits = x @ p["kernel"].astype(x.dtype) + p["bias"].astype(x.dtype)
        return jax.nn.softmax(logits)
    if pooling == "avg":
        return global_avg_pool(x)
    if pooling == "max":
        return jnp.max(x, axis=(1, 2))
    return x
