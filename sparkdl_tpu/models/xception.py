"""Xception in Flax — keras.applications.xception parity.

Named model in the reference registry (SURVEY.md §2.1
``keras_applications.py``): 299x299, [-1,1] preprocessing, 2048-d features.

Entry flow (blocks 1-4), middle flow (blocks 5-12, 728ch), exit flow
(blocks 13-14). SeparableConv = depthwise+pointwise, no bias; residual 1x1
convs stride 2; BN keras defaults (eps 1e-3). 'SAME'-padded max pools.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from sparkdl_tpu.models.layers import (
    KERAS_BN_EPS, SeparableConvBN, classifier_head, global_avg_pool,
)


class Xception(nn.Module):
    include_top: bool = True
    classes: int = 1000
    classifier_activation: Optional[str] = "softmax"
    pooling: Optional[str] = "avg"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        bn = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, epsilon=KERAS_BN_EPS,
            momentum=0.99, dtype=self.dtype, name=name)

        def sep(h, features, name):
            # kernel_family opts the block into the fused sep2d registry
            # (core/kernels.py accept-if-faster autotune); ineligible or
            # unadopted sites keep the plain Flax body.
            return SeparableConvBN(features, dtype=self.dtype, name=name,
                                   kernel_family="xception")(h, train)

        # Entry flow: block 1 (plain convs)
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="VALID",
                    use_bias=False, dtype=self.dtype, name="block1_conv1")(x)
        x = nn.relu(bn("block1_conv1_bn")(x))
        x = nn.Conv(64, (3, 3), padding="VALID", use_bias=False,
                    dtype=self.dtype, name="block1_conv2")(x)
        x = nn.relu(bn("block1_conv2_bn")(x))

        # Entry flow blocks 2-4: sepconv pairs with strided-pool residuals
        for i, features in zip((2, 3, 4), (128, 256, 728)):
            residual = nn.Conv(features, (1, 1), strides=(2, 2),
                               padding="SAME", use_bias=False,
                               dtype=self.dtype, name=f"block{i}_res_conv")(x)
            residual = bn(f"block{i}_res_bn")(residual)
            if i > 2:
                x = nn.relu(x)
            x = sep(x, features, f"block{i}_sepconv1")
            x = nn.relu(x)
            x = sep(x, features, f"block{i}_sepconv2")
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            x = x + residual

        # Middle flow: blocks 5-12
        for i in range(5, 13):
            residual = x
            x = nn.relu(x)
            x = sep(x, 728, f"block{i}_sepconv1")
            x = nn.relu(x)
            x = sep(x, 728, f"block{i}_sepconv2")
            x = nn.relu(x)
            x = sep(x, 728, f"block{i}_sepconv3")
            x = x + residual

        # Exit flow: block 13
        residual = nn.Conv(1024, (1, 1), strides=(2, 2), padding="SAME",
                           use_bias=False, dtype=self.dtype,
                           name="block13_res_conv")(x)
        residual = bn("block13_res_bn")(residual)
        x = nn.relu(x)
        x = sep(x, 728, "block13_sepconv1")
        x = nn.relu(x)
        x = sep(x, 1024, "block13_sepconv2")
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = x + residual

        # Exit flow: block 14
        x = sep(x, 1536, "block14_sepconv1")
        x = nn.relu(x)
        x = sep(x, 2048, "block14_sepconv2")
        x = nn.relu(x)

        if self.include_top:
            x = global_avg_pool(x)
            return classifier_head(x, self.classes,
                                   self.classifier_activation, self.dtype)
        if self.pooling == "avg":
            return global_avg_pool(x)
        if self.pooling == "max":
            return jnp.max(x, axis=(1, 2))
        return x
