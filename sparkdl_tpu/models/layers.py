"""Shared Flax building blocks for the model zoo.

These mirror the exact op semantics of the Keras reference architectures
(keras.src.applications — public code, inspected in-env) so that converted
Keras weights reproduce outputs bit-for-bit (up to float assoc). Notably:

- ``conv_bn``: Conv (no bias) + BatchNorm + ReLU, the InceptionV3 unit
  (BN scale=False, eps 1e-3 — Keras defaults).
- Keras's ZeroPadding2D + 'valid' conv differs from SAME for stride-2
  (symmetric pad vs XLA SAME's asymmetric); ``pad2d`` reproduces the
  explicit-pad variants.
- All modules take ``train``: BatchNorm uses batch stats + mutable
  ``batch_stats`` when training, running averages at inference.

Everything is NHWC with channels-last params (HWIO conv kernels — the same
layout Keras uses, so weight conversion is copy-through).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any

KERAS_BN_EPS = 1e-3          # keras BatchNormalization default
RESNET_BN_EPS = 1.001e-5     # keras resnet.py blocks


def _kernels_or_none():
    """``core.kernels`` iff ``EngineConfig.pallas_kernels`` is armed.

    Lazy and knob-gated so ``"off"`` (and a model zoo used without the
    engine) never even imports the Pallas machinery — the byte-identity
    pin asserts ``core.kernels`` is absent from ``sys.modules``."""
    try:
        from sparkdl_tpu.engine.dataframe import EngineConfig
    except Exception:
        return None
    if getattr(EngineConfig, "pallas_kernels", "off") == "off":
        return None
    from sparkdl_tpu.core import kernels
    return kernels


def pad2d(x: jnp.ndarray, pad: Union[int, Tuple[Tuple[int, int], Tuple[int, int]]]
          ) -> jnp.ndarray:
    """ZeroPadding2D equivalent on NHWC."""
    if isinstance(pad, int):
        pad = ((pad, pad), (pad, pad))
    return jnp.pad(x, ((0, 0), pad[0], pad[1], (0, 0)))


def correct_pad(x: jnp.ndarray, kernel_size: int
                ) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """keras imagenet_utils.correct_pad for stride-2 'valid' convs (NHWC)."""
    h, w = x.shape[1], x.shape[2]
    adjust = (1 - h % 2, 1 - w % 2)
    correct = kernel_size // 2
    return ((correct - adjust[0], correct), (correct - adjust[1], correct))


def max_pool(x, window: int, stride: int, padding="VALID"):
    # NOTE (profiled, r3): rewriting the overlapping pools as shifted strided
    # slices combined elementwise looked attractive (reduce_window is ~18%
    # of InceptionV3 device time) but measured SLOWER end-to-end on TPU —
    # the slice form degrades the layouts XLA picks for the downstream convs
    # (whole-model 7.3k -> 6.5k img/s). Keep reduce_window.
    return nn.max_pool(x, (window, window), strides=(stride, stride),
                       padding=padding)


def avg_pool_same(x, window: int = 3, stride: int = 1):
    """AveragePooling2D(padding='same') with Keras edge semantics.

    Keras/TF 'same' average pooling divides by the count of *valid* (non-pad)
    elements at the edges; naive mean-over-window with zero pads divides by
    the full window. Reproduce by average-pooling ones to get the count
    correction factor.
    """
    zero = jnp.asarray(0.0, x.dtype)  # init must match operand dtype (bf16)
    summed = nn.pool(x, zero, jnp.add, (window, window), (stride, stride),
                     "SAME")
    ones = jnp.ones(x.shape[1:3] + (1,), dtype=x.dtype)[None]
    counts = nn.pool(ones, zero, jnp.add, (window, window), (stride, stride),
                     "SAME")
    return summed / counts


class ConvBN(nn.Module):
    """Conv2D(use_bias=False) + BatchNorm + optional ReLU (InceptionV3 unit).

    Keras parity: BN epsilon defaults to 1e-3; InceptionV3 sets scale=False.
    """

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Union[str, Sequence[Tuple[int, int]]] = "SAME"
    bn_scale: bool = False
    bn_eps: float = KERAS_BN_EPS
    act: bool = True
    dtype: Optional[Dtype] = None
    # Structural opt-in to the fused-kernel registry (core/kernels.py):
    # a model that sets its family name lets eligible sites (1x1
    # stride-1 SAME, inference) route through the accept-if-faster
    # autotune. None (default) never consults the registry.
    kernel_family: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        # The Flax branch ALWAYS runs structurally — it is what creates
        # the param tree, so opted-in and opted-out models have
        # identical checkpoints; when the fused route wins, jit DCEs it.
        y = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype,
                    name="conv")(x)
        y = nn.BatchNorm(use_running_average=not train, epsilon=self.bn_eps,
                         use_scale=self.bn_scale, momentum=0.99,
                         dtype=self.dtype, name="bn")(y)
        if self.act:
            y = nn.relu(y)
        fused = self._fused(x, train)
        return y if fused is None else fused

    def _fused(self, x, train: bool):
        if train or self.kernel_family is None:
            return None
        if (tuple(self.strides) != (1, 1) or tuple(self.kernel) != (1, 1)
                or self.padding != "SAME"):
            return None
        kernels = _kernels_or_none()
        if kernels is None:
            return None
        params = self.variables.get("params", {})
        stats = self.variables.get("batch_stats", {})
        conv_p, bn_p = params.get("conv"), params.get("bn", {})
        bn_s = stats.get("bn")
        if conv_p is None or bn_s is None:
            return None
        return kernels.route_pw1x1(
            x, conv_p["kernel"], bn_p.get("scale"), bn_p.get("bias"),
            bn_s["mean"], bn_s["var"], self.bn_eps, relu=self.act,
            family=self.kernel_family)


class SeparableConvBN(nn.Module):
    """SeparableConv2D(use_bias=False) + BatchNorm (Xception unit).

    Keras SeparableConv2D = depthwise (H,W,1 per channel) then pointwise
    1x1; flax expresses depthwise as feature_group_count=C with C output
    features.
    """

    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    bn_eps: float = KERAS_BN_EPS
    dtype: Optional[Dtype] = None
    # Structural opt-in to the fused sep2d kernel (see ConvBN).
    kernel_family: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        y = nn.Conv(in_ch, self.kernel, strides=self.strides, padding="SAME",
                    feature_group_count=in_ch, use_bias=False,
                    dtype=self.dtype, name="depthwise")(x)
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype,
                    name="pointwise")(y)
        y = nn.BatchNorm(use_running_average=not train, epsilon=self.bn_eps,
                         momentum=0.99, dtype=self.dtype, name="bn")(y)
        fused = self._fused(x, train)
        return y if fused is None else fused

    def _fused(self, x, train: bool):
        if train or self.kernel_family is None:
            return None
        if tuple(self.strides) != (1, 1) or tuple(self.kernel) != (3, 3):
            return None
        kernels = _kernels_or_none()
        if kernels is None:
            return None
        params = self.variables.get("params", {})
        stats = self.variables.get("batch_stats", {})
        dw, pw = params.get("depthwise"), params.get("pointwise")
        bn_p, bn_s = params.get("bn", {}), stats.get("bn")
        if dw is None or pw is None or bn_s is None:
            return None
        return kernels.route_sep2d(
            x, dw["kernel"], pw["kernel"], bn_p.get("scale"),
            bn_p.get("bias"), bn_s["mean"], bn_s["var"], self.bn_eps,
            family=self.kernel_family)


def classifier_head(x, classes: int, activation: Optional[str],
                    dtype=None, name: str = "predictions"):
    x = nn.Dense(classes, dtype=dtype, name=name)(x)
    if activation == "softmax":
        x = nn.softmax(x)
    return x


def global_avg_pool(x):
    return jnp.mean(x, axis=(1, 2))
