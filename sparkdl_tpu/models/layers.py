"""Shared Flax building blocks for the model zoo.

These mirror the exact op semantics of the Keras reference architectures
(keras.src.applications — public code, inspected in-env) so that converted
Keras weights reproduce outputs bit-for-bit (up to float assoc). Notably:

- ``conv_bn``: Conv (no bias) + BatchNorm + ReLU, the InceptionV3 unit
  (BN scale=False, eps 1e-3 — Keras defaults).
- Keras's ZeroPadding2D + 'valid' conv differs from SAME for stride-2
  (symmetric pad vs XLA SAME's asymmetric); ``pad2d`` reproduces the
  explicit-pad variants.
- All modules take ``train``: BatchNorm uses batch stats + mutable
  ``batch_stats`` when training, running averages at inference.

Everything is NHWC with channels-last params (HWIO conv kernels — the same
layout Keras uses, so weight conversion is copy-through).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax.numpy as jnp

Dtype = Any

KERAS_BN_EPS = 1e-3          # keras BatchNormalization default
RESNET_BN_EPS = 1.001e-5     # keras resnet.py blocks


def pad2d(x: jnp.ndarray, pad: Union[int, Tuple[Tuple[int, int], Tuple[int, int]]]
          ) -> jnp.ndarray:
    """ZeroPadding2D equivalent on NHWC."""
    if isinstance(pad, int):
        pad = ((pad, pad), (pad, pad))
    return jnp.pad(x, ((0, 0), pad[0], pad[1], (0, 0)))


def correct_pad(x: jnp.ndarray, kernel_size: int
                ) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """keras imagenet_utils.correct_pad for stride-2 'valid' convs (NHWC)."""
    h, w = x.shape[1], x.shape[2]
    adjust = (1 - h % 2, 1 - w % 2)
    correct = kernel_size // 2
    return ((correct - adjust[0], correct), (correct - adjust[1], correct))


def max_pool(x, window: int, stride: int, padding="VALID"):
    # NOTE (profiled, r3): rewriting the overlapping pools as shifted strided
    # slices combined elementwise looked attractive (reduce_window is ~18%
    # of InceptionV3 device time) but measured SLOWER end-to-end on TPU —
    # the slice form degrades the layouts XLA picks for the downstream convs
    # (whole-model 7.3k -> 6.5k img/s). Keep reduce_window.
    return nn.max_pool(x, (window, window), strides=(stride, stride),
                       padding=padding)


def avg_pool_same(x, window: int = 3, stride: int = 1):
    """AveragePooling2D(padding='same') with Keras edge semantics.

    Keras/TF 'same' average pooling divides by the count of *valid* (non-pad)
    elements at the edges; naive mean-over-window with zero pads divides by
    the full window. Reproduce by average-pooling ones to get the count
    correction factor.
    """
    zero = jnp.asarray(0.0, x.dtype)  # init must match operand dtype (bf16)
    summed = nn.pool(x, zero, jnp.add, (window, window), (stride, stride),
                     "SAME")
    ones = jnp.ones(x.shape[1:3] + (1,), dtype=x.dtype)[None]
    counts = nn.pool(ones, zero, jnp.add, (window, window), (stride, stride),
                     "SAME")
    return summed / counts


class ConvBN(nn.Module):
    """Conv2D(use_bias=False) + BatchNorm + optional ReLU (InceptionV3 unit).

    Keras parity: BN epsilon defaults to 1e-3; InceptionV3 sets scale=False.
    """

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Union[str, Sequence[Tuple[int, int]]] = "SAME"
    bn_scale: bool = False
    bn_eps: float = KERAS_BN_EPS
    act: bool = True
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype,
                    name="conv")(x)
        x = nn.BatchNorm(use_running_average=not train, epsilon=self.bn_eps,
                         use_scale=self.bn_scale, momentum=0.99,
                         dtype=self.dtype, name="bn")(x)
        if self.act:
            x = nn.relu(x)
        return x


class SeparableConvBN(nn.Module):
    """SeparableConv2D(use_bias=False) + BatchNorm (Xception unit).

    Keras SeparableConv2D = depthwise (H,W,1 per channel) then pointwise
    1x1; flax expresses depthwise as feature_group_count=C with C output
    features.
    """

    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    bn_eps: float = KERAS_BN_EPS
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        x = nn.Conv(in_ch, self.kernel, strides=self.strides, padding="SAME",
                    feature_group_count=in_ch, use_bias=False,
                    dtype=self.dtype, name="depthwise")(x)
        x = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype,
                    name="pointwise")(x)
        x = nn.BatchNorm(use_running_average=not train, epsilon=self.bn_eps,
                         momentum=0.99, dtype=self.dtype, name="bn")(x)
        return x


def classifier_head(x, classes: int, activation: Optional[str],
                    dtype=None, name: str = "predictions"):
    x = nn.Dense(classes, dtype=dtype, name=name)(x)
    if activation == "softmax":
        x = nn.softmax(x)
    return x


def global_avg_pool(x):
    return jnp.mean(x, axis=(1, 2))
