"""Flax model zoo: the reference's named CNN families, TPU-native.

Parity: ``sparkdl/transformers/keras_applications.py`` + Scala
``Models.scala`` (SURVEY.md §2.1/§2.2). All models are NHWC flax.linen
modules with optional bf16 compute (``dtype=jnp.bfloat16`` — fp32 params,
MXU-friendly activations).
"""

from sparkdl_tpu.models.inception import InceptionV3
from sparkdl_tpu.models.mobilenet import MobileNetV2
from sparkdl_tpu.models.resnet import ResNet, ResNet50, ResNet101, ResNet152
from sparkdl_tpu.models.testnet import TestNet
from sparkdl_tpu.models.vgg import VGG, VGG16, VGG19
from sparkdl_tpu.models.xception import Xception
from sparkdl_tpu.models.registry import (
    SUPPORTED_MODELS,
    SUPPORTED_MODEL_NAMES,
    ModelSpec,
    build_featurizer,
    build_predictor,
    get_model_spec,
)

__all__ = [
    "InceptionV3", "MobileNetV2", "ResNet", "ResNet50", "ResNet101",
    "ResNet152", "TestNet", "VGG", "VGG16", "VGG19", "Xception",
    "SUPPORTED_MODELS", "SUPPORTED_MODEL_NAMES", "ModelSpec",
    "build_featurizer", "build_predictor", "get_model_spec",
]
