"""ctypes binding for the native C++ image loader (``libsparkdl_image.so``).

Falls back cleanly when the shared library has not been built — callers
check :func:`available` and use the PIL path otherwise. Build with
``sparkdl_tpu/native/build.sh`` (g++ + libjpeg + libpng, no extra deps).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Tuple

import numpy as np

_LIB_NAME = "libsparkdl_image.so"
_lib = None
_lib_lock = threading.Lock()
_load_attempted = False


def _library_path() -> str:
    return os.path.join(os.path.dirname(__file__), _LIB_NAME)


def _try_build() -> bool:
    """Best-effort one-shot build of the .so from the in-tree C++ source.

    Disable with SPARKDL_TPU_NO_NATIVE_BUILD=1 (tests of the PIL fallback,
    or environments without g++/libjpeg-dev).
    """
    if os.environ.get("SPARKDL_TPU_NO_NATIVE_BUILD"):
        return False
    script = os.path.join(os.path.dirname(__file__), "build.sh")
    if not os.path.exists(script):
        return False
    import subprocess

    try:
        # sparkdl: allow(blocking-under-lock): one-shot native build on first load; _lib_lock exists to serialize exactly this
        subprocess.run(["bash", script], check=True, capture_output=True,
                       timeout=120)
    except Exception:
        return False
    return os.path.exists(_library_path())


def _load():
    global _lib, _load_attempted
    with _lib_lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        path = _library_path()
        if not os.path.exists(path):
            if not _try_build():
                return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        # int sdl_decode(const uint8_t* data, size_t len, int target_h,
        #                int target_w, uint8_t* out, int* out_h, int* out_w,
        #                int* out_c)
        lib.sdl_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.sdl_decode.restype = ctypes.c_int
        lib.sdl_probe.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                  ctypes.POINTER(ctypes.c_int),
                                  ctypes.POINTER(ctypes.c_int),
                                  ctypes.POINTER(ctypes.c_int)]
        lib.sdl_probe.restype = ctypes.c_int
        lib.sdl_decode_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int),
            ctypes.c_int,
        ]
        lib.sdl_decode_batch.restype = ctypes.c_int
        if hasattr(lib, "sdl_resize_batch"):
            lib.sdl_resize_batch.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
                ctypes.c_int,
            ]
            lib.sdl_resize_batch.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def decode(data: bytes, target_size: Optional[Tuple[int, int]] = None
           ) -> Optional[np.ndarray]:
    """Decode (and optionally bilinear-resize) JPEG/PNG bytes → HWC uint8."""
    lib = _load()
    if lib is None:
        return None
    h = ctypes.c_int(0)
    w = ctypes.c_int(0)
    c = ctypes.c_int(0)
    if lib.sdl_probe(data, len(data), ctypes.byref(h), ctypes.byref(w),
                     ctypes.byref(c)) != 0:
        return None
    th, tw = (target_size if target_size is not None else (h.value, w.value))
    out = np.empty((th, tw, max(c.value, 1)), dtype=np.uint8)
    rc = lib.sdl_decode(
        data, len(data), th, tw,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.byref(h), ctypes.byref(w), ctypes.byref(c))
    if rc != 0:
        return None
    return out[:, :, :c.value] if out.shape[2] != c.value else out


def decode_batch(blobs, target_size: Tuple[int, int], channels: int = 3,
                 num_threads: int = 0) -> Optional[np.ndarray]:
    """Decode many blobs into one NHWC uint8 array (threaded in C++).

    Returns None if the native lib is missing or any blob fails to decode
    (callers then fall back to the per-image path to isolate the failure).
    """
    res = decode_batch_status(blobs, target_size, channels, num_threads)
    if res is None:
        return None
    out, ok = res
    if not ok.all():
        return None
    return out


def resize_batch(batch: np.ndarray, target_size: Tuple[int, int],
                 num_threads: int = 0) -> Optional[np.ndarray]:
    """Threaded bilinear resize of an NHWC uint8 batch (GIL released).

    Returns the resized (N, th, tw, C) uint8 array, or None when the
    native library is unavailable or lacks the entry point (older .so) —
    callers fall back to per-row/device resize.
    """
    lib = _load()
    if lib is None or not hasattr(lib, "sdl_resize_batch"):
        return None
    if batch.ndim != 4 or batch.dtype != np.uint8:
        return None
    batch = np.ascontiguousarray(batch)
    n, sh, sw, c = batch.shape
    th, tw = target_size
    out = np.empty((n, th, tw, c), dtype=np.uint8)
    rc = lib.sdl_resize_batch(
        batch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n, sh, sw, c,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        th, tw, num_threads)
    if rc != 0:
        return None
    return out


def decode_batch_status(blobs, target_size: Tuple[int, int],
                        channels: int = 3, num_threads: int = 0
                        ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Threaded batch decode with per-blob success flags.

    Returns ``(nhwc_uint8, ok_mask)`` — rows where ``ok_mask`` is False
    are undefined and the caller re-decodes only those per-image — or
    None when the native library is unavailable. The C call runs outside
    the GIL, so partition workers decode truly in parallel (the per-row
    Python loop the VERDICT flagged serialized on the GIL).
    """
    lib = _load()
    if lib is None or not blobs:
        return None
    n = len(blobs)
    th, tw = target_size
    ptrs = (ctypes.c_char_p * n)(*blobs)
    lens = (ctypes.c_size_t * n)(*[len(b) for b in blobs])
    out = np.empty((n, th, tw, channels), dtype=np.uint8)
    status = (ctypes.c_int * n)()
    lib.sdl_decode_batch(
        ptrs, lens, n, th, tw,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        status, num_threads)
    ok = np.frombuffer(status, dtype=np.int32) == 0
    return out, ok.copy()
