"""Native (C++) host-side components.

The reference's native substrate lived in its dependencies (TF C++ executor,
TensorFrames JNI, NCCL — SURVEY.md §2.3). The TPU rebuild's device-side
native layer is libtpu/XLA via PJRT; this package holds the *host-side*
native pieces we own: the image decode/resize data-loader
(libjpeg/libpng C++, see ``image_loader.cc``), bound via ctypes with a pure
PIL fallback so the framework works before/without the build step.
"""

from sparkdl_tpu.native import loader

__all__ = ["loader"]
