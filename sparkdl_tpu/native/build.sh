#!/usr/bin/env bash
# Build the native image loader (libjpeg + libpng, no other deps).
set -euo pipefail
cd "$(dirname "$0")"
g++ -O3 -march=native -fPIC -shared -std=c++17 \
    image_loader.cc -o libsparkdl_image.so \
    -ljpeg -lpng -lpthread
echo "built $(pwd)/libsparkdl_image.so"
