// Native host-side image data-loader for sparkdl_tpu.
//
// Role: the hot host path feeding TPU HBM. SURVEY.md §7 ranks host JPEG
// decode as the #2 hard part (MXU starvation); this replaces the reference's
// JVM-side decode (java.awt BufferedImage in ImageUtils.scala, SURVEY.md
// §2.2) and Python PIL with a threaded C++ decode+resize:
//   - libjpeg with DCT scaling (decode at 1/2, 1/4, 1/8 when the target is
//     much smaller than the source — most of the win for featurize inputs),
//   - libpng (palette/16-bit/alpha normalized to 8-bit),
//   - fused bilinear resize to the model's fixed input size,
//   - batch API decoding N blobs on a thread pool into ONE contiguous NHWC
//     uint8 buffer, so staging to the device is a single DMA.
//
// C ABI (ctypes-bound in loader.py):
//   int sdl_probe(const uint8_t* data, size_t len, int* h, int* w, int* c);
//   int sdl_decode(const uint8_t* data, size_t len, int th, int tw,
//                  uint8_t* out, int* h, int* w, int* c);
//   int sdl_decode_batch(const char** ptrs, const size_t* lens, int n,
//                        int th, int tw, uint8_t* out, int* status,
//                        int num_threads);
// All return 0 on success; sdl_decode_batch returns the failure count.

#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <algorithm>
#include <atomic>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <png.h>

namespace {

// ---------------------------------------------------------------------------
// Bilinear resize, interleaved uint8, C channels.
// ---------------------------------------------------------------------------
void resize_bilinear(const uint8_t* src, int sh, int sw, int c,
                     uint8_t* dst, int dh, int dw) {
  if (sh == dh && sw == dw) {
    std::memcpy(dst, src, static_cast<size_t>(sh) * sw * c);
    return;
  }
  const float sy = static_cast<float>(sh) / dh;
  const float sx = static_cast<float>(sw) / dw;
  for (int y = 0; y < dh; ++y) {
    // Pixel-center sampling (align with PIL's convention).
    float fy = (y + 0.5f) * sy - 0.5f;
    fy = std::max(0.0f, std::min(fy, static_cast<float>(sh - 1)));
    const int y0 = static_cast<int>(fy);
    const int y1 = std::min(y0 + 1, sh - 1);
    const float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      fx = std::max(0.0f, std::min(fx, static_cast<float>(sw - 1)));
      const int x0 = static_cast<int>(fx);
      const int x1 = std::min(x0 + 1, sw - 1);
      const float wx = fx - x0;
      const uint8_t* p00 = src + (static_cast<size_t>(y0) * sw + x0) * c;
      const uint8_t* p01 = src + (static_cast<size_t>(y0) * sw + x1) * c;
      const uint8_t* p10 = src + (static_cast<size_t>(y1) * sw + x0) * c;
      const uint8_t* p11 = src + (static_cast<size_t>(y1) * sw + x1) * c;
      uint8_t* q = dst + (static_cast<size_t>(y) * dw + x) * c;
      for (int k = 0; k < c; ++k) {
        const float top = p00[k] + (p01[k] - p00[k]) * wx;
        const float bot = p10[k] + (p11[k] - p10[k]) * wx;
        q[k] = static_cast<uint8_t>(top + (bot - top) * wy + 0.5f);
      }
    }
  }
}

// ---------------------------------------------------------------------------
// JPEG
// ---------------------------------------------------------------------------
struct JpegErr {
  jpeg_error_mgr mgr;
  std::jmp_buf jump;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  std::longjmp(err->jump, 1);
}

bool is_jpeg(const uint8_t* data, size_t len) {
  return len >= 3 && data[0] == 0xFF && data[1] == 0xD8 && data[2] == 0xFF;
}

bool is_png(const uint8_t* data, size_t len) {
  static const uint8_t sig[8] = {0x89, 'P', 'N', 'G', 0x0D, 0x0A, 0x1A, 0x0A};
  return len >= 8 && std::memcmp(data, sig, 8) == 0;
}

// Decode JPEG into `pixels` (interleaved). Chooses libjpeg DCT scaling so the
// decoded size is the smallest power-of-two scale still >= target (when a
// target is given). Returns false on corrupt input.
bool decode_jpeg(const uint8_t* data, size_t len, int target_h, int target_w,
                 std::vector<uint8_t>* pixels, int* h, int* w, int* c) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data), len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space =
      cinfo.num_components == 1 ? JCS_GRAYSCALE : JCS_RGB;
  if (target_h > 0 && target_w > 0) {
    // Largest denom in {1,2,4,8} with scaled dims still >= target.
    int denom = 1;
    while (denom < 8 &&
           static_cast<int>(cinfo.image_height) / (denom * 2) >= target_h &&
           static_cast<int>(cinfo.image_width) / (denom * 2) >= target_w) {
      denom *= 2;
    }
    cinfo.scale_num = 1;
    cinfo.scale_denom = denom;
  }
  jpeg_start_decompress(&cinfo);
  *h = cinfo.output_height;
  *w = cinfo.output_width;
  *c = cinfo.output_components;
  pixels->resize(static_cast<size_t>(*h) * *w * *c);
  const size_t stride = static_cast<size_t>(*w) * *c;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = pixels->data() + cinfo.output_scanline * stride;
    JSAMPROW rows[1] = {row};
    jpeg_read_scanlines(&cinfo, rows, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

bool probe_jpeg(const uint8_t* data, size_t len, int* h, int* w, int* c) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data), len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  *h = cinfo.image_height;
  *w = cinfo.image_width;
  *c = cinfo.num_components == 1 ? 1 : 3;
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// ---------------------------------------------------------------------------
// PNG
// ---------------------------------------------------------------------------
struct PngReadState {
  const uint8_t* data;
  size_t len;
  size_t pos;
};

void png_read_fn(png_structp png, png_bytep out, png_size_t count) {
  PngReadState* st = static_cast<PngReadState*>(png_get_io_ptr(png));
  if (st->pos + count > st->len) {
    png_error(png, "read past end");
  }
  std::memcpy(out, st->data + st->pos, count);
  st->pos += count;
}

bool decode_png(const uint8_t* data, size_t len, std::vector<uint8_t>* pixels,
                int* h, int* w, int* c) {
  png_structp png =
      png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  if (!png) return false;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return false;
  }
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return false;
  }
  PngReadState st{data, len, 0};
  png_set_read_fn(png, &st, png_read_fn);
  png_read_info(png, info);

  png_uint_32 width = 0, height = 0;
  int bit_depth = 0, color_type = 0;
  png_get_IHDR(png, info, &width, &height, &bit_depth, &color_type, nullptr,
               nullptr, nullptr);
  // Normalize to 8-bit gray / RGB / RGBA.
  if (color_type == PNG_COLOR_TYPE_PALETTE) png_set_palette_to_rgb(png);
  if (color_type == PNG_COLOR_TYPE_GRAY && bit_depth < 8)
    png_set_expand_gray_1_2_4_to_8(png);
  if (png_get_valid(png, info, PNG_INFO_tRNS)) png_set_tRNS_to_alpha(png);
  if (bit_depth == 16) png_set_strip_16(png);
  png_read_update_info(png, info);

  *h = static_cast<int>(height);
  *w = static_cast<int>(width);
  *c = static_cast<int>(png_get_channels(png, info));
  const size_t stride = png_get_rowbytes(png, info);
  pixels->resize(stride * height);
  std::vector<png_bytep> rows(height);
  for (png_uint_32 y = 0; y < height; ++y) {
    rows[y] = pixels->data() + y * stride;
  }
  png_read_image(png, rows.data());
  png_destroy_read_struct(&png, &info, nullptr);
  return true;
}

bool probe_png(const uint8_t* data, size_t len, int* h, int* w, int* c) {
  png_structp png =
      png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  if (!png) return false;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return false;
  }
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return false;
  }
  PngReadState st{data, len, 0};
  png_set_read_fn(png, &st, png_read_fn);
  png_read_info(png, info);
  png_uint_32 width = 0, height = 0;
  int bit_depth = 0, color_type = 0;
  png_get_IHDR(png, info, &width, &height, &bit_depth, &color_type, nullptr,
               nullptr, nullptr);
  *h = static_cast<int>(height);
  *w = static_cast<int>(width);
  // Must mirror decode_png's normalization: tRNS expands to an alpha
  // channel there, so probe must count it or the caller's buffer is
  // undersized (heap overflow in resize).
  const bool has_trns = png_get_valid(png, info, PNG_INFO_tRNS) != 0;
  switch (color_type) {
    case PNG_COLOR_TYPE_GRAY: *c = has_trns ? 2 : 1; break;
    case PNG_COLOR_TYPE_GRAY_ALPHA: *c = 2; break;
    case PNG_COLOR_TYPE_RGB_ALPHA: *c = 4; break;
    default: *c = has_trns ? 4 : 3; break;  // palette/RGB
  }
  png_destroy_read_struct(&png, &info, nullptr);
  return true;
}

// Channel conversion helper: any (1,2,3,4)-channel interleaved → 3ch RGB.
void to_rgb(const std::vector<uint8_t>& in, int h, int w, int c,
            std::vector<uint8_t>* out) {
  out->resize(static_cast<size_t>(h) * w * 3);
  const size_t n = static_cast<size_t>(h) * w;
  for (size_t i = 0; i < n; ++i) {
    const uint8_t* p = in.data() + i * c;
    uint8_t* q = out->data() + i * 3;
    switch (c) {
      case 1: q[0] = q[1] = q[2] = p[0]; break;
      case 2: q[0] = q[1] = q[2] = p[0]; break;  // gray+alpha: drop alpha
      case 3: q[0] = p[0]; q[1] = p[1]; q[2] = p[2]; break;
      default: q[0] = p[0]; q[1] = p[1]; q[2] = p[2]; break;  // drop alpha
    }
  }
}

bool decode_any(const uint8_t* data, size_t len, int target_h, int target_w,
                std::vector<uint8_t>* pixels, int* h, int* w, int* c) {
  if (is_jpeg(data, len)) {
    return decode_jpeg(data, len, target_h, target_w, pixels, h, w, c);
  }
  if (is_png(data, len)) {
    return decode_png(data, len, pixels, h, w, c);
  }
  return false;
}

}  // namespace

extern "C" {

int sdl_probe(const uint8_t* data, size_t len, int* h, int* w, int* c) {
  if (is_jpeg(data, len)) return probe_jpeg(data, len, h, w, c) ? 0 : 1;
  if (is_png(data, len)) return probe_png(data, len, h, w, c) ? 0 : 1;
  return 1;
}

// Decode + resize to (th, tw) preserving the image's own channel count
// (as reported by sdl_probe). `out` must hold th*tw*C bytes.
int sdl_decode(const uint8_t* data, size_t len, int th, int tw, uint8_t* out,
               int* h, int* w, int* c) {
  std::vector<uint8_t> pixels;
  int sh = 0, sw = 0, sc = 0;
  if (!decode_any(data, len, th, tw, &pixels, &sh, &sw, &sc)) return 1;
  if (th <= 0 || tw <= 0) {
    th = sh;
    tw = sw;
  }
  resize_bilinear(pixels.data(), sh, sw, sc, out, th, tw);
  *h = th;
  *w = tw;
  *c = sc;
  return 0;
}

// Batch decode into one contiguous NHWC uint8 buffer, forced to 3-channel
// RGB (model input convention). Threaded. Returns number of failures;
// status[i] != 0 marks blob i as failed.
int sdl_decode_batch(const char** ptrs, const size_t* lens, int n, int th,
                     int tw, uint8_t* out, int* status, int num_threads) {
  if (n <= 0) return 0;
  if (num_threads <= 0) {
    num_threads = static_cast<int>(std::thread::hardware_concurrency());
    if (num_threads <= 0) num_threads = 4;
  }
  num_threads = std::min(num_threads, n);
  const size_t img_bytes = static_cast<size_t>(th) * tw * 3;
  std::atomic<int> next(0);
  std::atomic<int> failures(0);

  auto worker = [&]() {
    std::vector<uint8_t> pixels, rgb, resized;
    for (;;) {
      const int i = next.fetch_add(1);
      if (i >= n) break;
      int sh = 0, sw = 0, sc = 0;
      const uint8_t* blob = reinterpret_cast<const uint8_t*>(ptrs[i]);
      if (!decode_any(blob, lens[i], th, tw, &pixels, &sh, &sw, &sc)) {
        status[i] = 1;
        failures.fetch_add(1);
        std::memset(out + static_cast<size_t>(i) * img_bytes, 0, img_bytes);
        continue;
      }
      const std::vector<uint8_t>* src = &pixels;
      if (sc != 3) {
        to_rgb(pixels, sh, sw, sc, &rgb);
        src = &rgb;
      }
      resize_bilinear(src->data(), sh, sw, 3,
                      out + static_cast<size_t>(i) * img_bytes, th, tw);
      status[i] = 0;
    }
  };

  std::vector<std::thread> threads;
  threads.reserve(num_threads);
  for (int t = 0; t < num_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
  return failures.load();
}

// Threaded bilinear resize of a contiguous NHWC uint8 batch (decoded image
// structs → model input size, before host→device transfer). Keeps the
// whole loop GIL-free and shrinks transfer bytes when downscaling.
//
// All images share one geometry, so the per-axis sample indices and
// fixed-point (8.8) weights are precomputed ONCE and shared across the
// batch — ~4x faster per image than the per-pixel float path above
// (which stays for the decode paths where geometry varies per image).
int sdl_resize_batch(const uint8_t* in, int n, int sh, int sw, int c,
                     uint8_t* out, int th, int tw, int num_threads) {
  if (n <= 0 || sh <= 0 || sw <= 0 || c <= 0 || th <= 0 || tw <= 0) return 1;
  if (num_threads <= 0) {
    num_threads = static_cast<int>(std::thread::hardware_concurrency());
    if (num_threads <= 0) num_threads = 4;
  }
  num_threads = std::min(num_threads, n);
  const size_t in_bytes = static_cast<size_t>(sh) * sw * c;
  const size_t out_bytes = static_cast<size_t>(th) * tw * c;

  // Per-axis tables: source index pair + 8.8 fixed-point lerp weight,
  // pixel-center convention matching resize_bilinear above.
  std::vector<int> yy0(th), yy1(th), xx0(tw), xx1(tw);
  std::vector<int> wy(th), wx(tw);
  const float sy = static_cast<float>(sh) / th;
  const float sx = static_cast<float>(sw) / tw;
  for (int y = 0; y < th; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    fy = std::max(0.0f, std::min(fy, static_cast<float>(sh - 1)));
    yy0[y] = static_cast<int>(fy);
    yy1[y] = std::min(yy0[y] + 1, sh - 1);
    wy[y] = static_cast<int>((fy - yy0[y]) * 256.0f + 0.5f);
  }
  for (int x = 0; x < tw; ++x) {
    float fx = (x + 0.5f) * sx - 0.5f;
    fx = std::max(0.0f, std::min(fx, static_cast<float>(sw - 1)));
    xx0[x] = static_cast<int>(fx);
    xx1[x] = std::min(xx0[x] + 1, sw - 1);
    wx[x] = static_cast<int>((fx - xx0[x]) * 256.0f + 0.5f);
  }

  std::atomic<int> next(0);
  auto worker = [&]() {
    for (;;) {
      const int i = next.fetch_add(1);
      if (i >= n) break;
      const uint8_t* src = in + static_cast<size_t>(i) * in_bytes;
      uint8_t* dst = out + static_cast<size_t>(i) * out_bytes;
      for (int y = 0; y < th; ++y) {
        const uint8_t* r0 = src + static_cast<size_t>(yy0[y]) * sw * c;
        const uint8_t* r1 = src + static_cast<size_t>(yy1[y]) * sw * c;
        const int vy = wy[y];
        uint8_t* q = dst + static_cast<size_t>(y) * tw * c;
        for (int x = 0; x < tw; ++x) {
          const uint8_t* p00 = r0 + static_cast<size_t>(xx0[x]) * c;
          const uint8_t* p01 = r0 + static_cast<size_t>(xx1[x]) * c;
          const uint8_t* p10 = r1 + static_cast<size_t>(xx0[x]) * c;
          const uint8_t* p11 = r1 + static_cast<size_t>(xx1[x]) * c;
          const int vx = wx[x];
          for (int k = 0; k < c; ++k) {
            const int top = (p00[k] << 8) + (p01[k] - p00[k]) * vx;
            const int bot = (p10[k] << 8) + (p11[k] - p10[k]) * vx;
            const int val = (top << 8) + (bot - top) * vy;  // 16.16
            q[k] = static_cast<uint8_t>((val + (1 << 15)) >> 16);
          }
          q += c;
        }
      }
    }
  };

  std::vector<std::thread> threads;
  threads.reserve(num_threads);
  for (int t = 0; t < num_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
  return 0;
}

}  // extern "C"
