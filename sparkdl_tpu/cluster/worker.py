"""Cluster worker process: one full per-process inference stack.

Each worker is a **spawn-context** process (never fork — the
coordinator owns a live JAX/PJRT runtime; a forked child inheriting
device handles is undefined behavior, the same rule
``core/decode_pool.py`` established) that hosts everything a
single-process run would: its own device runtime, its own
``DeviceExecutor`` + compiled-fn cache (reached through the op chain
exactly as inline execution reaches them), and its own
``Telemetry(run_id=...)`` scope pinned to the COORDINATOR's run id so
every worker's spans and metrics carry the same run identity the
merged report (``cluster/aggregate.py``) is keyed on.

Transport mirrors the decode pool: a PRIVATE task queue in and a
PRIVATE result pipe back per worker — one writer per pipe, so a worker
killed mid-delivery corrupts only its own channel and the router's
collector sees the death as EOF. Op chains ship once per distinct
chain as cloudpickle blobs keyed by the token
``cluster/router.py`` derives from ``core/durability.py``'s op-chain
canonicalization (``durability.ops_token``), then partitions reference
the token — model weights cross the pipe once, not per partition.

Boot order matters: the jax platform is pinned from the coordinator's
resolved backend BEFORE any backend initialization (a spawned
interpreter re-runs ``sitecustomize``/env resolution from scratch —
the coordinator's choice must win), then the coordinator's
``EngineConfig`` snapshot is restored with the cluster/durability/
decode-pool knobs forced off (a worker must never recurse into
another cluster, journal coordinator-owned state, or nest decode
pools under the coordinator's pool).

Protocol (parent -> worker queue):
  ``("ops", token, blob)``                      register an op chain
  ``("srv_*", ...)``                            cluster serving plane
      (``sparkdl_tpu/serving/cluster.py``): deploy/retire/pin fan-out,
      two-phase cutover prepares, and routed predicts. The first
      ``srv_*`` message lazily builds this worker's
      ``WorkerServingPlane`` (own ModelRegistry + residency budget) —
      a batch-only cluster run never imports the serving plane
  ``("task", task_id, index, token, ipc, crash, preempt, tenant,
  ctx)``  run one partition; ``ctx`` is the coordinator's
      dispatch-span ``SpanContext`` (None with tracing off) — the
      worker's ``sparkdl.cluster_task`` span parents under it;
      ``preempt`` (the armed ``cluster_worker_preempt`` marker)
      SIGTERMs this process BEFORE the task runs — the task still
      completes, the drain is zero-recompute; ``tenant`` is the job's
      fair-queueing tag (``EngineConfig.job_tenant``), entered as an
      ``executor.tenant_scope`` around the op chain
  ``("pull_ring",)``                            flight-recorder span
      pull: reply with the CURRENT span ring (rebased, non-draining —
      the worker keeps running) so a mid-run postmortem bundle carries
      a merged partial trace
  ``None``                                      poison pill
(worker -> parent pipe):
  ``("ok", task_id, ipc, meta)`` / ``("err", task_id, type, msg, kind)``
  ``("draining", worker_id)``                   SIGTERM-with-warning
      received (spot-VM preemption): the router stops dispatching here
      and pills this worker once its in-flight tasks finish — the
      worker NEVER self-exits on SIGTERM (a task sitting unread in the
      queue could be stranded otherwise; the drain is pill-driven)
  ``("frame", worker_id, frame)``               metrics-federation frame
      (``EngineConfig.cluster_federation_s`` armed): the bounded
      windowed-metrics export ``cluster/aggregate.build_frame`` makes,
      shipped at the federation cadence between tasks so the
      coordinator's live fold tracks this worker mid-run
  ``("ring", worker_id, ring)``                 ``pull_ring`` reply
  ``("final", worker_id, snapshot)``            last message before EOF
      (with tracing armed the snapshot carries this worker's span ring,
      rebased onto the coordinator's clock via the startup handshake on
      the dedicated clock pipe)
"""

from __future__ import annotations

import os
import signal
import time
from queue import Empty
from typing import Any, Dict

# Idle-worker orphan watch (same rationale as the decode pool): a
# kill -9'd coordinator can never deliver the poison pill, so
# reparenting is the worker's only death signal.
_ORPHAN_POLL_S = 5.0

# True inside a spawned cluster worker (set by _worker_main): a worker
# must never route its own partitions back into a router —
# ``router.maybe_router`` checks this, and the restored EngineConfig
# forces cluster_workers=0 anyway (belt and braces).
_IN_WORKER = False


def _ipc_bytes(batch: Any) -> bytes:
    """One-batch Arrow IPC stream — the partition wire format (the same
    encoding ``core/durability.py`` spills, so cluster transport and
    durable spills agree byte-for-byte on what a partition *is*)."""
    import io

    import pyarrow as pa

    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, batch.schema) as writer:
        writer.write_batch(batch)
    return sink.getvalue()


def _batch_from_ipc(payload: bytes) -> Any:
    import io

    import pyarrow as pa

    with pa.ipc.open_stream(io.BytesIO(payload)) as reader:
        batches = [b for b in reader]
    if len(batches) != 1:
        raise IOError(
            f"cluster task payload holds {len(batches)} batches, "
            "expected 1")
    return batches[0]


def _worker_main(worker_id: int, tasks: Any, conn: Any, owner_pid: int,
                 run_id: str, boot_blob: bytes,
                 clock_conn: Any = None) -> None:
    """Worker process loop: execute partition op chains until the
    ``None`` poison pill, then ship the end-of-run snapshot and EOF.

    Classified retry, hedging, quarantine, deadlines, and fault
    injection all stay COORDINATOR-side (the router routes through
    ``engine/supervisor.py``); this loop only executes one attempt's op
    chain and reports the outcome — an exception ships back typed with
    its ``resilience.classify`` kind so the coordinator's retry loop
    sees exactly what an in-process attempt would have raised. Only the
    armed ``cluster_worker_kill`` marker (evaluated coordinator-side,
    riding on the task message) kills the process — SIGKILL, no
    cleanup, exactly what the chaos leg needs.
    """
    global _IN_WORKER
    _IN_WORKER = True
    import cloudpickle

    boot = cloudpickle.loads(boot_blob)
    # pin the platform BEFORE anything can initialize the backend: the
    # spawned interpreter re-resolves platform selection from scratch
    # and must land where the coordinator landed
    import jax

    jax.config.update("jax_platforms", boot["platform"])
    from sparkdl_tpu.cluster import aggregate
    from sparkdl_tpu.core import (executor, health, profiling, resilience,
                                  telemetry)
    from sparkdl_tpu.engine.dataframe import EngineConfig

    EngineConfig.restore(boot["config"])
    name = f"sparkdl-cluster-{worker_id}"
    # SIGTERM-with-warning (spot-VM preemption): the handler ONLY sets a
    # flag — touching the result pipe from a signal frame could tear a
    # message mid-send. The loop notices the flag at its next iteration
    # (PEP 475: the signal interrupts a blocking queue get, which then
    # resumes — worst case one _ORPHAN_POLL_S when idle, instant when
    # busy) and notifies the router, which owns the drain.
    preempted = {"flag": False, "sent": False}

    def _on_sigterm(signum, frame):  # pragma: no cover - signal frame
        preempted["flag"] = True

    signal.signal(signal.SIGTERM, _on_sigterm)
    # the coordinator's root span context (None = tracing off) and the
    # clock offset that maps this process's perf_counter_ns onto the
    # coordinator's — together they let this worker's spans merge onto
    # the coordinator's timeline as ONE trace
    coord_root = boot.get("root_ctx")
    clock_offset = 0
    if clock_conn is not None:
        clock_offset = telemetry.clock_handshake(clock_conn)
        clock_conn.close()
    ops_cache: Dict[str, Any] = {}
    serving_plane = None
    tasks_done = 0
    rows_out = 0
    exec_s_total = 0.0
    snapshot: Dict[str, Any] = {}
    # monitor OUTSIDE the telemetry scope (the documented nesting that
    # folds health into reports); out_dir="" suppresses file export —
    # the snapshot ships over the pipe instead
    monitor = health.HealthMonitor(name)
    # metrics federation (docs/OBSERVABILITY.md "Cluster metrics
    # federation"): NOT forced off in the restored config — the worker
    # reads the coordinator's cadence here and ships bounded frames
    # between tasks; None keeps the loop (and the pipe traffic)
    # byte-identical to the pre-federation protocol
    fed_s = EngineConfig.cluster_federation_s
    frame_seq = 0
    next_frame = (time.monotonic() + fed_s) if fed_s else None
    with monitor, telemetry.Telemetry(
            name=name, out_dir="", run_id=run_id,
            process_scope=f"w{worker_id}",
            exemplar_k=int(boot.get("exemplar_k") or 0)) as tel:
        # ambient worker spans (compiles, executor launches) parent
        # under the coordinator's root rather than this worker's private
        # root — a no-op when tracing is off (coord_root is None)
        telemetry.attach(coord_root)

        def _ring():
            remap = ({tel.root_context.span_id: coord_root.span_id}
                     if coord_root is not None else None)
            return tel.tracer.export_ring(
                clock_offset_ns=clock_offset, process=name,
                parent_remap=remap)

        while True:
            if next_frame is not None and time.monotonic() >= next_frame:
                frame_seq += 1
                frame = aggregate.build_frame(
                    name, worker_id, frame_seq, tel,
                    clock_offset_ns=clock_offset)
                if frame is not None:
                    conn.send(("frame", worker_id, frame))
                next_frame = time.monotonic() + fed_s
            if preempted["flag"] and not preempted["sent"]:
                # tell the router we are draining, then KEEP processing:
                # in-flight and already-queued tasks run to completion
                # (zero re-execution); the router pills us once our
                # in-flight set empties
                preempted["sent"] = True
                health.record(health.CLUSTER_PREEMPTION_NOTICE,
                              worker=name)
                conn.send(("draining", worker_id))
            try:
                timeout = _ORPHAN_POLL_S
                if next_frame is not None:
                    # wake for the next frame even while idle (the
                    # cadence must not stall just because no task came)
                    timeout = min(timeout,
                                  max(0.01,
                                      next_frame - time.monotonic()))
                msg = tasks.get(timeout=timeout)
            except Empty:
                if os.getppid() != owner_pid:  # orphaned: owner died hard
                    conn.close()
                    return
                continue
            if msg is None:
                break
            if msg[0] == "ops":
                _, token, blob = msg
                ops_cache[token] = cloudpickle.loads(blob)
                continue
            if msg[0] == "pull_ring":
                # flight-recorder pull: ship the CURRENT ring (rebased,
                # re-parented like the final one) and keep running —
                # the postmortem must not disturb the stream
                conn.send(("ring", worker_id, _ring()))
                continue
            if isinstance(msg[0], str) and msg[0].startswith("srv_"):
                if serving_plane is None:
                    from sparkdl_tpu.serving.cluster import \
                        WorkerServingPlane

                    serving_plane = WorkerServingPlane(worker_id, name,
                                                       conn)
                serving_plane.handle(msg)
                continue
            _, task_id, index, token, payload, crash, preempt, tenant, \
                ctx = msg
            if crash:
                # injected worker death (chaos leg): die as hard as a
                # machine loss — no cleanup, no final snapshot
                os.kill(os.getpid(), signal.SIGKILL)
            if preempt:
                # injected SIGTERM-with-warning: the flag is set before
                # the task runs, so the drain notice goes out on the
                # NEXT loop iteration — this task still completes
                os.kill(os.getpid(), signal.SIGTERM)
            t0 = time.perf_counter()
            try:
                ops = ops_cache[token]
                out = _batch_from_ipc(payload)
                # parent = the coordinator's sparkdl.cluster_dispatch
                # span that shipped this task (ambient fallback when
                # tracing is off), so the cross-process parent link is
                # explicit, not inferred; the job's tenant tag scopes
                # the op chain so worker-side executor metrics stay
                # tenant-attributed
                with executor.tenant_scope(tenant), \
                        telemetry.span(telemetry.SPAN_CLUSTER_TASK,
                                       parent=ctx, partition=index,
                                       cluster_worker=worker_id):
                    for op in ops:
                        out = op(out)
                result = _ipc_bytes(out)
            # sparkdl: allow(broad-retry): not a retry — the error ships typed (with its classify kind) to the coordinator, whose supervisor owns the retry decision
            except Exception as e:  # noqa: BLE001 - re-raised parent-side
                conn.send(("err", task_id, type(e).__name__, str(e),
                           resilience.classify(e)))
                continue
            dt = time.perf_counter() - t0
            tasks_done += 1
            rows_out += out.num_rows
            exec_s_total += dt
            conn.send(("ok", task_id, result,
                       {"exec_s": dt, "rows": out.num_rows}))
        # end-of-run snapshot, built while the scopes are still active;
        # with tracing armed it carries this worker's span ring, rebased
        # onto the coordinator's clock, with spans still hanging off the
        # worker's (never-shipped, still-open) root re-parented onto the
        # coordinator's root
        span_ring = _ring() if coord_root is not None else None
        snapshot = aggregate.build_snapshot(
            name, os.getpid(), tel, monitor, tasks=tasks_done,
            rows=rows_out, exec_s=exec_s_total,
            phases=profiling.phase_stats(), span_ring=span_ring,
            serving=(serving_plane.stats()
                     if serving_plane is not None else None))
    conn.send(("final", worker_id, snapshot))
    conn.close()
