"""Partition router: one engine job fanned across N worker processes.

``engine/dataframe.py`` swaps its in-process ``_run_partition`` for
:meth:`ClusterRouter.run_partition` when ``EngineConfig.cluster_workers``
is set (the ONE knob; 0 keeps today's path byte-identical and never
imports this package). The router deliberately routes **through the
existing supervisor** — each partition still runs under
``engine/supervisor.py``'s classified retry, per-task deadline,
hedging, and quarantine; only the innermost "run the op chain" step is
replaced by a remote dispatch. That preserves every resilience
semantic across the process boundary for free:

- **retry**: a worker-side exception ships back typed with its
  ``resilience.classify`` kind and re-raises in the coordinator's
  retry loop — a retried attempt re-enters :meth:`run_partition`'s
  dispatch and picks a worker afresh.
- **hedging**: a hedge is just a second supervisor attempt; dispatch
  excludes workers already holding an in-flight attempt of the same
  partition, so the hedge lands on a *different* worker (a straggling
  worker cannot slow its own hedge).
- **quarantine**: FATAL confirmation replays route through dispatch
  like any retry; the partition-drop decision stays coordinator-side.
- **deadlines**: the supervisor watchdog's ``cancelled`` event makes
  the coordinator-side wait abandon (the worker's result, if it ever
  arrives, is dropped by the collector as an already-resolved task).

Assignment is load-aware on **outstanding rows** per worker (ties:
fewest in-flight tasks), the cluster analogue of the decode pool's
least-loaded pick but weighted by actual row counts so one huge
partition doesn't get a second one stacked behind it.

Worker death is detected as EOF on the dead worker's PRIVATE result
pipe (one writer per pipe — the decode-pool transport rationale). The
loss set is precise: exactly the dead worker's in-flight task ids,
re-dispatched to survivors (each re-dispatch is a
``cluster_redispatch`` health event + ``sparkdl.cluster.redispatch``
count; the death itself is ONE ``cluster_worker_lost``). With no
survivors the in-flight partitions fail with
:class:`~sparkdl_tpu.core.resilience.ClusterWorkerLost` — classified
RETRYABLE, so the supervisor's task retry re-dispatches once workers
are back (or fails the job with the full attempt history). With
``EngineConfig.durable_dir`` set, the PR 11 journal wraps OUTSIDE this
router (``dataframe._durable_runner``), so partitions committed before
a death are never re-dispatched at all — re-dispatch is zero-recompute
for them by construction.

At :meth:`close`, each worker ships its end-of-run snapshot
(``cluster/worker.py`` protocol), and the router merges them via
``cluster/aggregate.py`` into :attr:`cluster_report` (plus
:attr:`run_report` when a telemetry scope is active) — module-level
:func:`last_cluster_report` / :func:`last_run_report` keep the merged
view readable after :func:`shutdown`.
"""

from __future__ import annotations

import atexit
import hashlib
import itertools
import logging
import multiprocessing as mp
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from sparkdl_tpu.cluster import aggregate
from sparkdl_tpu.cluster import worker as _worker_mod
from sparkdl_tpu.core import durability, health, resilience, telemetry

logger = logging.getLogger(__name__)

# Flight-recorder bounds: how long a postmortem waits for on-demand
# span-ring pulls before bundling what it has, and how many bundles one
# router will write (a breach storm must not fill the disk).
_POSTMORTEM_RING_WAIT_S = 2.0
_POSTMORTEM_MAX = 8

# One spawn context for every router (module-level so the
# thread-lifecycle analyzer rule can resolve `_MP_CTX.Process(...)`).
_MP_CTX = mp.get_context("spawn")

# Waiter/submitter poll granularity (bounds close/cancel detection
# latency) and worker join budget at close.
_WAIT_POLL_S = 0.05
_JOIN_TIMEOUT_S = 10.0
# Autoscaler thread tick, and the grace a draining worker gets to finish
# its in-flight tasks before it is torn down hard (DrainTimeout: its
# tasks then take the ordinary lost-worker re-dispatch path).
_AUTOSCALE_TICK_S = 0.25
_DRAIN_GRACE_S = 60.0

_run_ids = itertools.count(1)


def _rebuild_error(type_name: str, msg: str, kind: str) -> BaseException:
    """Reconstruct a worker-side exception coordinator-side, preserving
    classification exactly: prefer the original type (builtin, then a
    ``resilience`` class) — but only if the rebuilt instance still
    classifies to the kind the worker computed; otherwise fall back to
    a RuntimeError carrying ``failure_kind``, the attribute
    ``resilience.classify`` trusts verbatim. Either way the
    coordinator's retry loop sees the kind an in-process attempt would
    have produced."""
    import builtins

    etype = getattr(builtins, type_name, None)
    if not (isinstance(etype, type) and issubclass(etype, Exception)):
        etype = getattr(resilience, type_name, None)
    if isinstance(etype, type) and issubclass(etype, Exception):
        try:
            err = etype(msg)
            if resilience.classify(err) == kind:
                return err
        except Exception:  # pragma: no cover - exotic ctor signature
            pass
    err = RuntimeError(f"{type_name}: {msg} (from cluster worker)")
    err.failure_kind = kind  # type: ignore[attr-defined]
    return err


class _Task:
    """One in-flight partition dispatch: the wire payload plus
    everything needed to re-dispatch it after a worker death."""

    __slots__ = ("task_id", "index", "token", "payload", "rows", "ctx",
                 "tenant", "event", "result", "error", "worker",
                 "redispatches")

    def __init__(self, index: int, token: str, payload: bytes,
                 rows: int, ctx=None, tenant: Optional[str] = None) -> None:
        self.task_id = 0
        self.index = index
        self.token = token
        self.payload = payload
        self.rows = rows
        # the job's tenant tag (EngineConfig.job_tenant): rides the task
        # message so worker-side executor metrics stay tenant-attributed
        self.tenant = tenant
        # the dispatch span's context, captured at submit: rides every
        # (re-)dispatch of this task so the worker-side span parents
        # under the SAME coordinator span a hedge/redispatch belongs to
        self.ctx = ctx
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.worker: Optional[int] = None
        self.redispatches = 0


class _Worker:
    """One worker process plus its PRIVATE task queue, its PRIVATE
    result pipe, the op-chain tokens already shipped to it, and its
    in-flight task ids / outstanding rows (the load signal)."""

    __slots__ = ("wid", "proc", "queue", "conn", "clock", "assigned",
                 "tokens", "outstanding_rows", "finished", "lost",
                 "draining", "drain_started", "drain_reason", "pilled",
                 "serving_assigned")

    def __init__(self, wid: int, proc: Any, queue: Any, conn: Any,
                 clock: Any) -> None:
        self.wid = wid
        self.proc = proc
        self.queue = queue
        self.conn = conn  # parent's read end; None once EOF-drained
        self.clock = clock  # clock-handshake pipe; None once answered
        self.assigned: Set[int] = set()
        # in-flight SERVING request ids (predicts + prepare acks) on this
        # worker — tracked separately from partition tasks so worker
        # death surfaces the precise set to re-admit, and a draining
        # worker is not pilled from under an unanswered predict
        self.serving_assigned: Set[int] = set()
        self.tokens: Set[str] = set()
        self.outstanding_rows = 0
        self.finished = False  # final snapshot received
        self.lost = False      # died without a final snapshot
        # WorkerDraining state: no new dispatches; in-flight tasks run
        # to completion, then the router pills the worker, which ships
        # its final snapshot and exits cleanly (never a worker-lost
        # re-dispatch). Entered on a preemption notice (worker-side
        # SIGTERM-with-warning) or an autoscaler scale-down order.
        self.draining = False
        self.drain_started = 0.0
        self.drain_reason = ""
        self.pilled = False    # poison pill already sent


class ClusterRouter:
    """N spawn-context cluster workers behind a load-aware dispatch.

    ::

        router = ClusterRouter(workers=2)
        try:
            out = router.run_partition(i, batch, ops)
        finally:
            router.close()   # joins workers, merges their snapshots

    ``run_partition`` is thread-safe (concurrent partition tasks share
    the router and the ``cluster_inflight_partitions`` backpressure
    bound) and is a drop-in for ``dataframe._run_partition`` — callers
    normally never construct one; :func:`maybe_router` manages the
    process-wide instance from ``EngineConfig.cluster_workers``. The
    coordinator's run id (from the active telemetry scope, if any) is
    pinned into every worker's ``Telemetry(run_id=...)`` at spawn.
    """

    def __init__(self, workers: int, inflight: Optional[int] = None,
                 run_id: Optional[str] = None,
                 autoscale: Optional[bool] = None,
                 federation_s: Optional[float] = None,
                 federation_rules: Optional[Sequence[Any]] = None) -> None:
        if workers < 1:
            raise ValueError(
                f"cluster router needs >= 1 worker, got {workers}")
        self.workers = int(workers)
        self.inflight = int(inflight) if inflight else 2 * self.workers
        if self.inflight < 1:
            raise ValueError(
                f"cluster_inflight_partitions must be >= 1, got "
                f"{inflight!r}")
        tel = telemetry.active()
        self.run_id = run_id or (
            tel.run_id if tel is not None
            else f"cluster-{os.getpid():x}-{next(_run_ids):04x}")
        # workers must land on the coordinator's RESOLVED backend and
        # config — a spawned interpreter re-derives both from scratch
        # otherwise (env vars, sitecustomize), and "cluster on" must
        # not change what runs
        import jax

        from sparkdl_tpu.engine.dataframe import EngineConfig

        config = EngineConfig.snapshot()
        # a worker must never recurse into its own cluster, journal
        # coordinator-owned state, nest a decode pool per worker, or run
        # its own autoscaler (elasticity is coordinator-owned)
        config.update(cluster_workers=0, cluster_inflight_partitions=None,
                      decode_workers=0, decode_pool_inflight=None,
                      durable_dir=None, cluster_autoscale=False,
                      serving_cluster=False)
        import cloudpickle

        # the coordinator's root span context ships in the boot blob:
        # worker-side ambient spans (compiles, executor launches) parent
        # under it instead of dangling off the worker's private root —
        # None (tracing off) keeps the worker's trace fully local
        self._boot_blob = cloudpickle.dumps(
            {"config": config, "platform": jax.default_backend(),
             "root_ctx": tel.root_context if tel is not None else None,
             # exemplar reservoirs are per-registry opt-in: workers arm
             # the SAME k as the coordinator, or federated breach events
             # would lose their resolvable exemplar trace ids
             "exemplar_k": (tel.metrics.exemplar_k
                            if tel is not None else 0)})
        self._lock = threading.Lock()
        # the attached cluster serving handler (serving/cluster.py), or
        # None while the serving plane is off — srv_* replies, precise
        # worker-loss request sets, and post-spawn replica top-ups route
        # to it. Lock order is always serving-handler lock -> router
        # lock: the router calls the handler with its own lock RELEASED.
        self._serving: Optional[Any] = None
        self._pending: Dict[int, _Task] = {}
        self._ids = itertools.count(1)
        self._ops_blobs: Dict[str, bytes] = {}
        self._token_cache: Dict[Tuple[int, str], str] = {}
        self._finals: List[Dict[str, Any]] = []
        self._sem = threading.BoundedSemaphore(self.inflight)
        self._closed = False
        # -- elastic capacity (docs/DISTRIBUTED.md "Elastic capacity") --
        # Live worker indices keep growing past the initial range, so a
        # replacement never reuses a retired worker's name; the event
        # history is merged into the cluster report at close().
        self._autoscale = (bool(EngineConfig.cluster_autoscale)
                           if autoscale is None else bool(autoscale))
        self._next_index = self.workers
        self._last_scale_ts = float("-inf")
        self.autoscale_events: List[Dict[str, Any]] = []
        self._autoscale_stop = threading.Event()
        self._autoscale_thread: Optional[threading.Thread] = None
        # -- metrics federation (docs/OBSERVABILITY.md "Cluster metrics
        # federation") — armed by EngineConfig.cluster_federation_s:
        # workers ship windowed delta frames on that cadence; the
        # collector folds them into the ClusterMetricsView and drives
        # the federated SLO watchdog against the merged fold
        fed_s = (EngineConfig.cluster_federation_s
                 if federation_s is None else federation_s)
        self._fed_view: Optional[aggregate.ClusterMetricsView] = None
        self._fed_watchdog: Optional[Any] = None
        self._fed_breached: Set[str] = set()
        self._fed_fresh: Set[str] = set()
        if fed_s:
            from sparkdl_tpu.core import slo as _slo

            self._fed_view = aggregate.ClusterMetricsView(float(fed_s))
            rules = (list(federation_rules)
                     if federation_rules is not None
                     else _default_federation_rules())
            self._fed_watchdog = _slo.SLOWatchdog(
                rules, attribution=self._fed_attribution)
        # flight recorder: breach/death/FATAL-triggered postmortem
        # bundles, written on short-lived daemon threads (the collector
        # must keep draining pipes — the bundle pulls span rings over
        # those same pipes, so writing in-collector would deadlock)
        self._pm_lock = threading.Lock()
        self._pm_seq = 0
        self._pm_threads: List[threading.Thread] = []
        self.postmortem_paths: List[str] = []
        self._ring_cond = threading.Condition()
        self._ring_box: Dict[int, Dict[str, Any]] = {}
        # bench accounting: wall time inside dispatch vs worker-measured
        # op-chain time (their gap is the router's overhead)
        self.dispatch_s_total = 0.0
        self.exec_s_total = 0.0
        self.worker_snapshots: List[Dict[str, Any]] = []
        self.cluster_report: Optional[Dict[str, Any]] = None
        self.run_report: Optional[Dict[str, Any]] = None
        # parent-internal wakeup pipe: nudges the collector out of its
        # connection.wait when the router closes
        self._wake_r, self._wake_w = _MP_CTX.Pipe(duplex=False)
        # incremental append (not a comprehension): a spawn failing at
        # worker k must leave workers 0..k-1 poisonable, not leaked
        self._workers: List[_Worker] = []
        try:
            for i in range(self.workers):
                self._workers.append(self._spawn(i))
        except BaseException:
            for worker in self._workers:
                worker.queue.put(None)
                worker.proc.join(timeout=_JOIN_TIMEOUT_S)
                worker.queue.cancel_join_thread()
                worker.queue.close()
                worker.conn.close()
                if worker.clock is not None:
                    worker.clock.close()
            self._wake_r.close()
            self._wake_w.close()
            self._closed = True
            raise
        self._collector = threading.Thread(
            target=self._collect, name="sparkdl-cluster-collector",
            daemon=True)
        self._collector.start()
        self._gauge_workers_locked_free()
        if self._autoscale:
            self._autoscale_thread = threading.Thread(
                target=self._autoscale_loop,
                name="sparkdl-cluster-autoscaler", daemon=True)
            self._autoscale_thread.start()

    def _spawn(self, index: int) -> _Worker:
        queue = _MP_CTX.Queue()
        recv_conn, send_conn = _MP_CTX.Pipe(duplex=False)
        # dedicated duplex pipe for the one-shot clock handshake: the
        # collector answers the worker's ping with perf_counter_ns so
        # remote span timestamps land on the coordinator's timeline
        clock_parent, clock_child = _MP_CTX.Pipe()
        proc = _MP_CTX.Process(
            target=_worker_mod._worker_main,
            args=(index, queue, send_conn, os.getpid(), self.run_id,
                  self._boot_blob, clock_child),
            name=f"sparkdl-cluster-{index}", daemon=True)
        proc.start()
        # drop the parent's copy of the write end: the worker owns the
        # only writer, so worker death shows up as EOF on recv_conn
        send_conn.close()
        clock_child.close()
        health.record(health.CLUSTER_WORKER_STARTED, worker=proc.name)
        return _Worker(index, proc, queue, recv_conn, clock_parent)

    @property
    def closed(self) -> bool:
        return self._closed

    # -- the public partition entry point ------------------------------------

    def run_partition(self, index: int, batch: Any,
                      ops: Sequence[Any],
                      cancelled: Optional[threading.Event] = None) -> Any:
        """Drop-in for ``dataframe._run_partition``: the same supervisor
        retry loop, with the op chain executed on a cluster worker
        instead of this thread. Row/byte counting mirrors the inline
        path exactly (supervised attempts are counted once per winning
        attempt by the supervisor's resolve)."""
        from sparkdl_tpu.engine import dataframe as _df
        from sparkdl_tpu.engine import supervisor as _sup

        cfg = _df.EngineConfig
        chain = [self._remote_op(index, ops, cancelled)]
        out = _sup.run_partition_task(
            index, batch, chain, policy=_df._task_policy(),
            deadline_s=cfg.task_timeout_s,
            legacy_injector=cfg.fault_injector,
            max_fatal_attempts=(cfg.quarantine_max_fatal
                                if cfg.quarantine else 1),
            cancelled=cancelled)
        if cancelled is None and telemetry.active() is not None:
            telemetry.count(telemetry.M_ENGINE_ROWS_OUT, out.num_rows)
            telemetry.count(telemetry.M_ENGINE_BYTES_OUT, out.nbytes)
        return out

    def _remote_op(self, index: int, ops: Sequence[Any],
                   cancelled: Optional[threading.Event]):
        """The one-op chain handed to the supervisor: each invocation
        (first attempt, classified retry, hedge, quarantine confirm) is
        a FRESH dispatch — worker selection happens per attempt, which
        is exactly what gives retries-after-death and hedges their
        anti-affinity."""
        token = self._ops_payload(ops)

        def dispatch(batch: Any) -> Any:
            t0 = time.monotonic()
            with telemetry.span(telemetry.SPAN_CLUSTER_DISPATCH,
                                partition=index):
                task = self._submit(index, batch, token)
                out = self._await(task, cancelled)
            dt = time.monotonic() - t0
            with self._lock:
                self.dispatch_s_total += dt
            if telemetry.active() is not None:
                telemetry.observe(telemetry.M_CLUSTER_DISPATCH_S, dt)
            return out

        return dispatch

    def _ops_payload(self, ops: Sequence[Any]) -> str:
        """Ship-once op-chain registration. The token is
        ``durability.ops_token`` (the same canonicalization ``job_id``
        hashes — cluster transport and durable journals agree on chain
        identity) suffixed with the pickled payload's digest, so two
        chains the repr-canonicalization cannot distinguish still get
        distinct cache slots."""
        base = durability.ops_token(ops)
        key = (id(ops), base)
        with self._lock:
            token = self._token_cache.get(key)
            if token is not None:
                return token
        import cloudpickle

        blob = cloudpickle.dumps(list(ops))
        token = f"{base}.{hashlib.sha256(blob).hexdigest()[:12]}"
        with self._lock:
            self._ops_blobs.setdefault(token, blob)
            if len(self._token_cache) > 256:  # id()s recycle across jobs
                self._token_cache.clear()
            self._token_cache[key] = token
        return token

    # -- submission / waiting ------------------------------------------------

    def _submit(self, index: int, batch: Any, token: str) -> _Task:
        payload = _worker_mod._ipc_bytes(batch)
        # bounded in-flight: backpressure here, with close detection so
        # a closed router cannot wedge a submitter forever
        while not self._sem.acquire(timeout=_WAIT_POLL_S):
            if self._closed:
                raise resilience.ClusterWorkerLost(
                    "cluster router closed while a dispatch was waiting "
                    "for an in-flight slot")
        from sparkdl_tpu.engine.dataframe import EngineConfig

        task = _Task(index, token, payload, batch.num_rows,
                     telemetry.current_context(),
                     tenant=EngineConfig.job_tenant)
        with self._lock:
            if self._closed:
                self._sem.release()
                raise resilience.ClusterWorkerLost(
                    "cluster router closed before the partition was "
                    "dispatched")
            task.task_id = next(self._ids)
            # hedge anti-affinity: a concurrent in-flight attempt of
            # the SAME partition must land on a different worker
            exclude = {t.worker for t in self._pending.values()
                       if t.index == index and t.worker is not None}
            self._pending[task.task_id] = task
            try:
                self._dispatch_locked(task, exclude)
            except BaseException:
                del self._pending[task.task_id]
                self._sem.release()
                raise
            total = self._outstanding_locked()
        self._gauge(total)
        return task

    def _dispatch_locked(self, task: _Task,
                         exclude: Set[Any] = frozenset()) -> None:
        """Hand a task to the least-loaded live worker (caller holds
        the lock). Load = outstanding rows (ties: in-flight task
        count). The armed ``cluster_worker_kill`` marker rides ON the
        task message, so the chosen worker dies holding exactly this
        partition — the precise re-dispatch path is what the injection
        exercises. Anti-affinity is best-effort: with every live worker
        excluded, landing somewhere beats failing the attempt."""
        live = [w for w in self._workers
                if not w.lost and not w.finished and not w.draining]
        candidates = [w for w in live if w.wid not in exclude] or live
        if not candidates:
            if any(w.draining and not w.lost and not w.finished
                   for w in self._workers):
                # every survivor is draining: the work itself is fine —
                # RETRYABLE, and a replacement/finished drain will take
                # the retry (never the worker-lost re-dispatch story)
                raise resilience.WorkerDraining(
                    f"every live cluster worker is draining; partition "
                    f"{task.index} must wait for a replacement")
            raise resilience.ClusterWorkerLost(
                f"no live cluster workers to run partition {task.index}")
        worker = min(candidates,
                     key=lambda w: (w.outstanding_rows, len(w.assigned)))
        if task.token not in worker.tokens:
            worker.queue.put(("ops", task.token,
                              self._ops_blobs[task.token]))
            worker.tokens.add(task.token)
        crash = resilience.should_fire("cluster_worker_kill",
                                       partition=task.index)
        # SIGTERM-with-warning (spot-VM preemption): the worker still
        # RUNS this task, then drains — zero re-execution by design
        preempt = resilience.should_fire("cluster_worker_preempt",
                                         partition=task.index)
        worker.queue.put(("task", task.task_id, task.index, task.token,
                          task.payload, crash, preempt, task.tenant,
                          task.ctx))
        worker.assigned.add(task.task_id)
        worker.outstanding_rows += task.rows
        task.worker = worker.wid

    def _await(self, task: _Task,
               cancelled: Optional[threading.Event]) -> Any:
        while not task.event.wait(_WAIT_POLL_S):
            if cancelled is not None and cancelled.is_set():
                # supervisor watchdog abandoned this attempt (deadline,
                # or a hedge already won): stop waiting; the worker's
                # late result resolves to an already-popped task and is
                # dropped by the collector
                self._abandon(task)
                raise resilience.ClusterWorkerLost(
                    f"partition {task.index} dispatch abandoned "
                    "(supervisor cancelled the attempt)")
        if task.error is not None:
            raise task.error
        return task.result

    def _abandon(self, task: _Task) -> None:
        with self._lock:
            if self._pending.pop(task.task_id, None) is None:
                return  # resolved concurrently; collector released
            self._discount_locked(task)
            total = self._outstanding_locked()
        self._sem.release()
        self._gauge(total)

    def _discount_locked(self, task: _Task) -> None:
        for worker in self._workers:
            if task.task_id in worker.assigned:
                worker.assigned.discard(task.task_id)
                worker.outstanding_rows = max(
                    0, worker.outstanding_rows - task.rows)

    def _outstanding_locked(self) -> int:
        return sum(w.outstanding_rows for w in self._workers)

    def _gauge(self, total: int) -> None:
        if telemetry.active() is not None:
            telemetry.gauge_set(telemetry.M_CLUSTER_OUTSTANDING_ROWS,
                                total)

    # -- the serving-plane transport (serving/cluster.py) --------------------

    def serving_attach(self, handler: Any) -> None:
        """Attach the cluster serving handler: ``srv_*`` worker replies
        (:meth:`on_message`), worker-loss notifications carrying the
        precise lost request ids (:meth:`on_worker_lost`), and
        post-spawn replica top-ups (:meth:`on_worker_spawn`) route to
        it. One handler per router; attaching replaces the previous."""
        with self._lock:
            self._serving = handler

    def serving_live_workers(self) -> List[int]:
        """Worker ids eligible for NEW serving dispatches: live and not
        draining — a draining worker finishes its in-flight predicts
        but admits no new ones (the same admission stance batch
        dispatch takes)."""
        with self._lock:
            return [w.wid for w in self._workers
                    if not w.lost and not w.finished and not w.draining]

    def serving_worker_name(self, wid: int) -> str:
        with self._lock:
            worker = self._worker_by_wid_locked(wid)
            return (worker.proc.name if worker is not None
                    else f"sparkdl-cluster-{wid}")

    def serving_send(self, wid: int, msg: Tuple,
                     req_id: Optional[int] = None) -> None:
        """Enqueue one serving-plane message on worker ``wid``'s private
        task queue (replies come back over its result pipe as ``srv_*``
        messages routed to the attached handler). ``req_id`` registers
        an expected reply under ``serving_assigned``: worker death then
        surfaces exactly this request for re-admission, and a draining
        worker is pilled only once it has answered."""
        with self._lock:
            worker = self._worker_by_wid_locked(wid)
            if (worker is None or worker.lost or worker.finished
                    or self._closed):
                raise resilience.ServingReplicaLost(
                    f"cluster worker {wid} is gone (or the router is "
                    "closed); cannot dispatch the serving message")
            if worker.draining and req_id is not None:
                raise resilience.WorkerDraining(
                    f"cluster worker {wid} is draining; it admits no "
                    "new serving requests")
            try:
                worker.queue.put(msg)
            except ValueError:
                raise resilience.ServingReplicaLost(
                    f"cluster worker {wid}'s task queue is closed"
                ) from None
            if req_id is not None:
                worker.serving_assigned.add(req_id)

    def serving_done(self, wid: int, req_id: int) -> None:
        """Discount one answered (or abandoned) serving request from its
        worker; a draining worker whose partition AND serving in-flight
        sets just emptied is pilled here — the serving analogue of the
        ``_on_message`` drain hook."""
        with self._lock:
            worker = self._worker_by_wid_locked(wid)
            if worker is None:
                return
            worker.serving_assigned.discard(req_id)
            if (worker.draining and not worker.assigned
                    and not worker.serving_assigned and not worker.pilled
                    and not self._closed):
                self._pill_locked(worker)

    def _worker_by_wid_locked(self, wid: int) -> Optional[_Worker]:
        for w in self._workers:
            if w.wid == wid:
                return w
        return None

    # -- the collector thread ------------------------------------------------

    def _collect(self) -> None:
        """Multiplex every worker's private result pipe. EOF on a pipe
        is the death (or clean-exit) signal; a dead worker's in-flight
        partitions are re-dispatched to survivors right here, so
        detection latency is one pipe wakeup, not a poll interval.
        Exits once the router is closed and every conn has EOF'd —
        which guarantees every final snapshot has been adopted."""
        from multiprocessing import connection as _mpc

        while True:
            with self._lock:
                conn_map = {w.conn: w for w in self._workers
                            if w.conn is not None}
                clock_map = {w.clock: w for w in self._workers
                             if w.clock is not None}
                done = self._closed and not conn_map and not clock_map
            if done:
                return
            for ready in _mpc.wait(list(conn_map) + list(clock_map)
                                   + [self._wake_r]):
                if ready is self._wake_r:
                    try:
                        self._wake_r.recv_bytes()
                    except (EOFError, OSError):  # pragma: no cover
                        pass
                    continue
                if ready in clock_map:
                    # one-shot clock handshake: answer the worker's ping
                    # with the coordinator's perf_counter_ns, then
                    # retire the pipe (EOF = the worker died first)
                    try:
                        ready.recv()
                        ready.send(time.perf_counter_ns())
                    except (EOFError, OSError):
                        pass
                    ready.close()
                    with self._lock:
                        clocked = clock_map[ready]
                        if clocked.clock is ready:
                            clocked.clock = None
                    continue
                worker = conn_map[ready]
                try:
                    msg = ready.recv()
                except (EOFError, OSError):
                    ready.close()
                    self._on_worker_eof(worker)
                    continue
                self._on_message(worker, msg)

    def _on_message(self, worker: _Worker, msg: Tuple) -> None:
        kind = msg[0]
        if isinstance(kind, str) and kind.startswith("srv_"):
            # serving-plane reply: the attached handler resolves its
            # waiter and discounts via serving_done (which owns the
            # drain-pill hook for serving in-flight sets)
            handler = self._serving
            if handler is not None:
                handler.on_message(worker.wid, msg)
            return
        if kind == "frame":
            # windowed metrics delta frame (the federation cadence):
            # fold it, then judge the merged fold
            self._on_frame(worker, msg[2])
            return
        if kind == "ring":
            # on-demand span-ring pull reply: route to the waiting
            # flight-recorder thread
            with self._ring_cond:
                self._ring_box[worker.wid] = msg[2]
                self._ring_cond.notify_all()
            return
        if kind == "final":
            with self._lock:
                worker.finished = True
                self._finals.append(msg[2])
            return
        if kind == "draining":
            # SIGTERM-with-warning reached the worker: stop dispatching
            # to it, let its in-flight tasks finish, pill it once empty
            # — a drain, never a ClusterWorkerLost re-dispatch storm
            self._begin_drain(worker, reason="preemption")
            return
        task_id = msg[1]
        with self._lock:
            task = self._pending.pop(task_id, None)
            if task is not None:
                self._discount_locked(task)
            total = self._outstanding_locked()
            if (worker.draining and not worker.assigned
                    and not worker.serving_assigned
                    and not worker.pilled and not self._closed):
                # last in-flight task just finished (and no serving
                # request is awaiting an answer): retire the worker (it
                # ships its final snapshot and EOFs cleanly)
                self._pill_locked(worker)
        if task is None:
            return  # re-dispatch duplicate or abandoned attempt
        if kind == "ok":
            _, _, payload, meta = msg
            task.result = _worker_mod._batch_from_ipc(payload)
            with self._lock:
                self.exec_s_total += float(meta.get("exec_s", 0.0))
        else:
            _, _, type_name, message, err_kind = msg
            task.error = _rebuild_error(type_name, message, err_kind)
            if err_kind == resilience.FATAL:
                # a FATAL task failure is a flight-recorder trigger: the
                # postmortem captures the cluster state AT the failure,
                # not whatever remains at end of run
                self._trigger_postmortem(
                    "fatal_task",
                    {"partition": task.index, "worker": worker.proc.name,
                     "error": f"{type_name}: {message}"})
        task.event.set()
        self._sem.release()
        self._gauge(total)

    def _pill_locked(self, worker: _Worker) -> None:
        """Send the poison pill to one worker (caller holds the lock).
        Drain is PILL-driven: the worker never self-exits on SIGTERM, so
        a task sitting unread in its queue can never be stranded — the
        pill goes out only once ``assigned`` is empty."""
        try:
            worker.queue.put(None)
        except ValueError:  # pragma: no cover - queue reaped concurrently
            return
        worker.pilled = True

    def _begin_drain(self, worker: _Worker, reason: str) -> None:
        """Move one worker into the WorkerDraining state (idempotent).
        Dispatch stops immediately; the pill goes out as soon as the
        worker holds no in-flight tasks. A preemption drain that would
        leave the live set below the floor spawns a replacement."""
        spawned: Optional[_Worker] = None
        with self._lock:
            if (worker.draining or worker.lost or worker.finished
                    or self._closed):
                return
            worker.draining = True
            worker.drain_started = time.monotonic()
            worker.drain_reason = reason
            if (not worker.assigned and not worker.serving_assigned
                    and not worker.pilled):
                self._pill_locked(worker)
            if reason == "preemption":
                spawned = self._ensure_capacity_locked()
        health.record(health.CLUSTER_WORKER_DRAINING,
                      worker=worker.proc.name, reason=reason)
        if reason == "preemption":
            health.record(health.CLUSTER_PREEMPTION_NOTICE,
                          worker=worker.proc.name)
        self._note_autoscale_event("draining", worker=worker.proc.name,
                                   reason=reason)
        logger.warning("cluster worker %s draining (%s): %d in-flight "
                       "task(s) to finish", worker.proc.name, reason,
                       len(worker.assigned))
        if spawned is not None:
            self._after_spawn(spawned, reason="replace_preempted")

    def _ensure_capacity_locked(self) -> Optional[_Worker]:
        """Spawn a replacement when a preemption drain would leave the
        live set below the floor (caller holds the lock). Floor =
        ``cluster_min_workers`` with the autoscaler armed, else the
        configured worker count (static capacity must stay static)."""
        from sparkdl_tpu.engine.dataframe import EngineConfig

        floor = (EngineConfig.cluster_min_workers if self._autoscale
                 else self.workers)
        live = sum(1 for w in self._workers
                   if not w.lost and not w.finished and not w.draining)
        if live >= floor:
            return None
        spawned = self._spawn(self._next_index)
        # sparkdl: allow(unguarded-shared-write): caller holds self._lock (the _locked-suffix contract)
        self._next_index += 1
        self._workers.append(spawned)
        return spawned

    def _after_spawn(self, worker: _Worker, reason: str) -> None:
        """Post-spawn bookkeeping done OUTSIDE the lock: wake the
        collector (it rebuilds its conn map per iteration, so the new
        worker's pipes join the multiplex on the next pass) and record
        the event."""
        try:
            self._wake_w.send_bytes(b"w")
        except (OSError, ValueError):  # pragma: no cover - closing
            pass
        self._gauge_workers_locked_free()
        self._note_autoscale_event("spawn", worker=worker.proc.name,
                                   reason=reason)
        handler = self._serving
        if handler is not None:
            # replica top-up: deployments fan out to the replacement so
            # the serving plane regains its replication factor
            handler.on_worker_spawn(worker.wid)

    def _gauge_workers_locked_free(self) -> None:
        if telemetry.active() is None:
            return
        with self._lock:
            live = sum(1 for w in self._workers
                       if not w.lost and not w.finished and not w.draining)
        telemetry.gauge_set(telemetry.M_CLUSTER_WORKERS, live)

    def _note_autoscale_event(self, action: str, **ctx: Any) -> None:
        with self._lock:
            self.autoscale_events.append(
                {"action": action, "t": time.monotonic(), **ctx})

    def _on_worker_eof(self, worker: _Worker) -> None:
        """A worker's pipe hit EOF. Clean exit (final already adopted,
        or the router is closing) just retires the conn; a DEATH marks
        the worker lost, abandons its queue, and re-dispatches exactly
        its in-flight task ids to survivors — one ``cluster_worker_lost``
        event per death, one ``cluster_redispatch`` per moved
        partition. No survivors: the partitions fail with a RETRYABLE
        ``ClusterWorkerLost`` and the supervisor's retry loop decides."""
        redispatched: List[_Task] = []
        failed: List[_Task] = []
        srv_lost: List[int] = []
        lost = False
        drained = False
        with self._lock:
            worker.conn = None
            if worker.draining and worker.finished:
                drained = True
            if not worker.finished and not self._closed:
                lost = True
                worker.lost = True
                # the precise serving loss set: exactly the request ids
                # awaiting an answer from this worker — handed to the
                # serving handler (outside the lock) for deadline-bounded
                # re-admission with exactly-once failover accounting
                srv_lost = sorted(worker.serving_assigned)
                worker.serving_assigned.clear()
                # abandon the dead worker's queue WITHOUT joining its
                # feeder thread (it may be blocked writing to a pipe
                # nobody will ever read — the decode-pool lesson)
                worker.queue.cancel_join_thread()
                worker.queue.close()
                held = sorted(worker.assigned)
                worker.assigned.clear()
                worker.outstanding_rows = 0
                for task_id in held:
                    task = self._pending.get(task_id)
                    if task is None:
                        continue  # delivered just before dying
                    task.redispatches += 1
                    try:
                        self._dispatch_locked(task, exclude={worker.wid})
                        redispatched.append(task)
                    except resilience.ClusterWorkerLost as e:
                        del self._pending[task_id]
                        task.error = e
                        failed.append(task)
        if drained:
            drain_s = time.monotonic() - worker.drain_started
            logger.info("cluster worker %s drained cleanly in %.3fs (%s)",
                        worker.proc.name, drain_s, worker.drain_reason)
            health.record(health.CLUSTER_WORKER_DRAINED,
                          worker=worker.proc.name,
                          reason=worker.drain_reason,
                          drain_s=round(drain_s, 4))
            if telemetry.active() is not None:
                telemetry.observe(telemetry.M_CLUSTER_DRAIN_S, drain_s)
            self._note_autoscale_event("drained", worker=worker.proc.name,
                                       reason=worker.drain_reason,
                                       drain_s=round(drain_s, 4))
            self._gauge_workers_locked_free()
        if lost:
            logger.warning(
                "cluster worker %s died; re-dispatched %d in-flight "
                "partition(s) to survivors (%d unplaceable)",
                worker.proc.name, len(redispatched), len(failed))
            health.record(health.CLUSTER_WORKER_LOST,
                          worker=worker.proc.name)
            view = self._fed_view
            if view is not None:
                # age the dead worker out of the federated fold NOW (no
                # more frames are coming) — its last shipped frame stays
                # retained for the postmortem bundle
                view.mark_dead(worker.proc.name)
                self._fed_fresh.discard(worker.proc.name)
                health.record(health.CLUSTER_METRICS_STALE,
                              worker=worker.proc.name,
                              reason="worker_lost")
                self._trigger_postmortem(
                    "worker_lost", {"worker": worker.proc.name})
            for task in redispatched:
                health.record(health.CLUSTER_REDISPATCH,
                              partition=task.index,
                              worker=worker.proc.name)
                if telemetry.active() is not None:
                    telemetry.count(telemetry.M_CLUSTER_REDISPATCH)
        for task in failed:
            task.event.set()
            self._sem.release()
        if lost:
            handler = self._serving
            if handler is not None:
                handler.on_worker_lost(worker.wid, srv_lost)

    # -- metrics federation + the flight recorder -----------------------------

    def _fed_attribution(self, rule: Any) -> Dict[str, Any]:
        """Per-worker observed values behind a federated breach (the
        SLOWatchdog attribution hook): which workers drove the merged
        verdict."""
        view = self._fed_view
        if view is None:
            return {}
        return view.attribution(rule.metric, rule.stat, rule.window_s)

    def _on_frame(self, worker: _Worker, frame: Dict[str, Any]) -> None:
        """Fold one worker's delta frame into the federated view, then
        evaluate the cluster SLO watchdog against the merged fold.
        Collector thread only — the watchdog's hold-down state is
        single-threaded by construction. A rule newly ENTERING breach
        trips the flight recorder (recoveries and still-breached rules
        do not: one bundle per incident, not per frame)."""
        view = self._fed_view
        if view is None:
            return
        view.ingest(frame)
        now = telemetry._monotonic()
        fresh = set(view.fresh_workers(now))
        for name in sorted(self._fed_fresh - fresh):
            # a worker stopped shipping frames without dying (wedged, or
            # a cadence stall): it silently left the fold — say so once
            health.record(health.CLUSTER_METRICS_STALE, worker=name,
                          reason="frames_stale")
        # sparkdl: allow(unguarded-shared-write): collector-thread-only state (_on_frame and _on_worker_eof both run on the collector) — single writer by construction
        self._fed_fresh = fresh
        wd = self._fed_watchdog
        if wd is None:
            return
        verdicts = wd.evaluate(view, now=now)
        active = {name for name, v in verdicts.items() if v["breached"]}
        view.note_timeline({
            "t": now, "workers_reporting": len(fresh),
            "slo": {name: {"observed": v["observed"],
                           "breached": v["breached"]}
                    for name, v in verdicts.items()
                    if v["observed"] is not None or v["breached"]}})
        for name in sorted(active - self._fed_breached):
            self._trigger_postmortem(
                "slo_breach", {"rule": name, **verdicts[name]})
        # sparkdl: allow(unguarded-shared-write): collector-thread-only state — single writer by construction
        self._fed_breached = active

    def _trigger_postmortem(self, trigger: str,
                            detail: Dict[str, Any]) -> None:
        """Arm one postmortem bundle write on a daemon thread. No
        federation, no active telemetry scope with an ``out_dir``,
        router closed, or the per-run bundle cap reached: no-op — the
        flight recorder never introduces artifacts (or blocking) into
        runs that didn't opt into observability."""
        if self._fed_view is None or self._closed:
            return
        tel = telemetry.active()
        out_dir = tel.out_dir if tel is not None else None
        if not out_dir:
            return
        with self._lock:
            if self._pm_seq >= _POSTMORTEM_MAX or self._closed:
                return
            self._pm_seq += 1
            seq = self._pm_seq
        recorder = threading.Thread(
            target=self._write_postmortem,
            args=(seq, trigger, dict(detail), out_dir),
            name=f"sparkdl-flight-recorder-{seq}", daemon=True)
        recorder.start()
        with self._lock:
            self._pm_threads.append(recorder)

    def _pull_rings(self) -> List[Dict[str, Any]]:
        """Fan an on-demand span-ring pull to every live worker and
        wait (bounded) for the replies — the collector routes each
        ``("ring", wid, ring)`` answer into the box. A worker that dies
        or stalls mid-pull just misses the bundle; the recorder ships
        what it has."""
        with self._lock:
            if self._closed:
                return []
            live = [w for w in self._workers
                    if not w.lost and not w.finished and not w.pilled]
            with self._ring_cond:
                self._ring_box = {}
            expect: Set[int] = set()
            for w in live:
                try:
                    w.queue.put(("pull_ring",))
                    expect.add(w.wid)
                except ValueError:  # queue reaped concurrently
                    pass
        deadline = time.monotonic() + _POSTMORTEM_RING_WAIT_S
        with self._ring_cond:
            while not expect <= set(self._ring_box):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                # sparkdl: allow(wait-holding-lock): the foreign lock is _pm_lock, the flight recorder's own serialization lock — only recorder threads take it, the wait is deadline-bounded, and no hot path can contend
                self._ring_cond.wait(remaining)
            return list(self._ring_box.values())

    def _write_postmortem(self, seq: int, trigger: str,
                          detail: Dict[str, Any], out_dir: str) -> None:
        try:
            self._write_postmortem_inner(seq, trigger, detail, out_dir)
        # sparkdl: allow(broad-retry): not a retry — the flight recorder is best-effort diagnostics and must never fail the run it is documenting
        except Exception:  # noqa: BLE001
            logger.exception("postmortem bundle %d failed; continuing",
                             seq)

    def _write_postmortem_inner(self, seq: int, trigger: str,
                                detail: Dict[str, Any],
                                out_dir: str) -> None:
        """One postmortem bundle: merged partial Chrome trace (live
        span-ring pulls), the last-K federated timeline, the health
        report, and the trigger's breach record — staged in a tmp dir
        and renamed into place, so ``postmortem_<run_id>_<seq>/`` is
        only ever observed complete."""
        import json

        # sparkdl: allow(wait-holding-lock): _pm_lock is the flight recorder's own serialization lock (only recorder threads ever take it) — holding it across the bounded ring wait is exactly its job; no hot path can contend
        with self._pm_lock:  # serialize pulls: the ring box is shared
            rings = self._pull_rings()
        view = self._fed_view
        tel = telemetry.active()
        bundle = f"postmortem_{self.run_id}_{seq:04d}"
        final_dir = os.path.join(out_dir, bundle)
        tmp_dir = final_dir + ".tmp"
        os.makedirs(tmp_dir, exist_ok=True)
        if tel is not None:
            trace = tel.tracer.merged_chrome_trace(rings)
            with open(os.path.join(tmp_dir, "trace.json"), "w",
                      encoding="utf-8") as f:
                json.dump(trace, f)
        if view is not None:
            with open(os.path.join(tmp_dir, "snapshots.jsonl"), "w",
                      encoding="utf-8") as f:
                for line in view.timeline():
                    f.write(json.dumps(line, default=repr) + "\n")
        mon = health.active_monitor()
        with open(os.path.join(tmp_dir, "health.json"), "w",
                  encoding="utf-8") as f:
            json.dump(mon.report() if mon is not None else None, f,
                      indent=2, default=repr)
        breach: Dict[str, Any] = {
            "trigger": trigger, "detail": detail,
            "run_id": self.run_id, "seq": seq,
            "rings_pulled": len(rings)}
        if view is not None:
            breach["federation"] = view.last_frames()
        with open(os.path.join(tmp_dir, "breach.json"), "w",
                  encoding="utf-8") as f:
            json.dump(breach, f, indent=2, default=repr)
        os.rename(tmp_dir, final_dir)
        with self._lock:
            self.postmortem_paths.append(final_dir)
        health.record(health.POSTMORTEM_DUMPED, trigger=trigger,
                      path=final_dir, seq=seq)
        logger.warning("flight recorder wrote postmortem bundle %s (%s)",
                       final_dir, trigger)

    # -- the autoscaler -------------------------------------------------------

    def _autoscale_loop(self) -> None:
        while not self._autoscale_stop.wait(_AUTOSCALE_TICK_S):
            if self._closed:
                return
            try:
                self.autoscale_tick()
            # sparkdl: allow(broad-retry): not a retry — a failed advisory tick is logged and the next tick re-evaluates from fresh telemetry
            except Exception:  # noqa: BLE001 - a tick must never kill the loop
                logger.exception("autoscale tick failed; continuing")

    def autoscale_tick(self, now: Optional[float] = None) -> Optional[str]:
        """One autoscaling decision (deterministically testable; the
        background thread just calls this on a short tick). Signals:
        the windowed queue-wait p99 from the live telemetry scope and
        outstanding rows per live worker. Hysteresis = the wide gap
        between the high and low water marks; anti-flap = the cooldown
        since the last action, plus at most ONE drain in flight. Also
        enforces the drain grace: a worker stuck draining past
        ``_DRAIN_GRACE_S`` is torn down hard (DrainTimeout — its tasks
        take the ordinary lost-worker re-dispatch path). Returns
        ``"up"``, ``"down"``, or ``None``."""
        from sparkdl_tpu.engine.dataframe import EngineConfig

        if not self._autoscale or self._closed:
            return None
        EngineConfig.validate()
        now = time.monotonic() if now is None else now
        p99: Optional[float] = None
        view = self._fed_view
        if view is not None:
            # federation armed: scale on the CLUSTER queue-wait p99 (the
            # merged-bucket fold over every reporting worker), not just
            # whatever the coordinator-local registry happened to see
            fed = view.window_snapshot(EngineConfig.autoscale_window_s)
            hist = fed["histograms"].get(telemetry.M_QUEUE_WAIT_S)
            p99 = hist.get("p99") if hist else None
        if p99 is None:
            tel = telemetry.active()
            if tel is not None:
                snap = tel.metrics.window_snapshot(
                    EngineConfig.autoscale_window_s)
                hist = snap["histograms"].get(telemetry.M_QUEUE_WAIT_S)
                p99 = hist.get("p99") if hist else None
        stuck: List[_Worker] = []
        with self._lock:
            if self._closed:
                return None
            live = [w for w in self._workers
                    if not w.lost and not w.finished and not w.draining]
            draining = [w for w in self._workers
                        if w.draining and not w.lost and not w.finished]
            for w in draining:
                if now - w.drain_started > _DRAIN_GRACE_S:
                    stuck.append(w)
            n_live = len(live)
            outstanding = sum(w.outstanding_rows for w in live)
            idle = [w for w in live
                    if not w.assigned and not w.outstanding_rows]
        for w in stuck:
            logger.warning(
                "cluster worker %s exceeded the %.0fs drain grace; "
                "terminating (DrainTimeout — in-flight tasks will "
                "re-dispatch)", w.proc.name, _DRAIN_GRACE_S)
            self._note_autoscale_event("drain_timeout",
                                       worker=w.proc.name,
                                       error="DrainTimeout")
            w.proc.terminate()  # EOF reap marks it lost + re-dispatches
        if now - self._last_scale_ts < EngineConfig.autoscale_cooldown_s:
            return None
        rows_per = (outstanding / n_live) if n_live else float("inf")
        hot = ((p99 is not None
                and p99 > EngineConfig.autoscale_queue_wait_high_s)
               or rows_per > EngineConfig.autoscale_rows_per_worker_high)
        cold = (p99 is None
                or p99 < EngineConfig.autoscale_queue_wait_low_s)
        if hot and n_live < EngineConfig.cluster_max_workers:
            with self._lock:
                if self._closed:
                    return None
                spawned = self._spawn(self._next_index)
                self._next_index += 1
                self._workers.append(spawned)
                self._last_scale_ts = now
            health.record(health.CLUSTER_SCALE_UP,
                          worker=spawned.proc.name, workers=n_live + 1,
                          queue_wait_p99_s=p99,
                          rows_per_worker=round(rows_per, 1))
            logger.warning(
                "cluster autoscaler scaling UP to %d worker(s) "
                "(queue-wait p99 %s, %.0f rows/worker)", n_live + 1,
                f"{p99:.4f}s" if p99 is not None else "n/a", rows_per)
            self._after_spawn(spawned, reason="scale_up")
            return "up"
        if (cold and not draining and idle
                and n_live > EngineConfig.cluster_min_workers):
            # retire the newest idle worker: drain is instant (nothing
            # in flight), so the pill goes out right away
            victim = max(idle, key=lambda w: w.wid)
            with self._lock:
                self._last_scale_ts = now
            health.record(health.CLUSTER_SCALE_DOWN,
                          worker=victim.proc.name, workers=n_live - 1,
                          queue_wait_p99_s=p99)
            logger.info(
                "cluster autoscaler scaling DOWN to %d worker(s) "
                "(queue-wait p99 %s; retiring idle %s)", n_live - 1,
                f"{p99:.4f}s" if p99 is not None else "n/a",
                victim.proc.name)
            self._begin_drain(victim, reason="scale_down")
            self._gauge_workers_locked_free()
            return "down"
        return None

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Poison, join, and reap every worker; drain every pipe to EOF
        (adopting the final snapshots); merge the snapshots into
        :attr:`cluster_report` / :attr:`run_report`. Idempotent; safe
        mid-stream (waiters fail with a RETRYABLE ClusterWorkerLost
        rather than hanging)."""
        self._autoscale_stop.set()
        with self._lock:
            if self._closed:
                return
            self._closed = True
            abandoned = list(self._pending.values())
            self._pending.clear()
            for worker in self._workers:
                worker.assigned.clear()
                worker.outstanding_rows = 0
            workers = list(self._workers)
        for worker in workers:
            try:
                worker.queue.put(None)  # poison pill per private queue
            except ValueError:  # queue closed by a concurrent EOF reap
                pass
        for worker in workers:
            worker.proc.join(timeout=_JOIN_TIMEOUT_S)
            if worker.proc.is_alive():  # pragma: no cover - wedged worker
                worker.proc.terminate()
                worker.proc.join(timeout=_JOIN_TIMEOUT_S)
            # a dead worker never consumed its pill; don't let the
            # queue's feeder thread block interpreter exit on it
            worker.queue.cancel_join_thread()
            worker.queue.close()
        # the joins closed every write end: the collector drains each
        # conn to EOF — adopting every final snapshot — then sees
        # closed + no live conns and exits; the wake byte covers it
        # being parked on an empty list
        self._wake_w.send_bytes(b"c")
        self._collector.join()
        if self._autoscale_thread is not None:
            self._autoscale_thread.join(timeout=_JOIN_TIMEOUT_S)
        with self._lock:
            recorders = list(self._pm_threads)
        for recorder in recorders:
            # in-flight postmortem bundles finish (their ring waits are
            # bounded) before the reports merge — a bundle must land
            # BEFORE the run ends, never race interpreter teardown
            recorder.join(timeout=_JOIN_TIMEOUT_S)
        for task in abandoned:
            task.error = resilience.ClusterWorkerLost(
                "cluster router closed mid-stream")
            task.event.set()
            self._sem.release()
        handler = self._serving
        if handler is not None:
            # serving requests still unanswered at this point are
            # orphans (their worker exited without replying): fail them
            # classified instead of letting a waiter spin to deadline
            handler.on_close()
        self._wake_w.close()
        self._wake_r.close()
        with self._lock:
            finals = list(self._finals)
        self.worker_snapshots = finals
        lost = [w.proc.name for w in workers if w.lost]
        tel = telemetry.active()
        if tel is not None:
            # merge the worker span rings into the coordinator's tracer
            # BEFORE building the reports, so the Chrome trace and the
            # trace summary both see every adopted span
            for snap in finals:
                ring = snap.get("span_ring")
                if ring is not None:
                    tel.tracer.adopt_remote_spans(ring["spans"])
        with self._lock:
            scale_events = list(self.autoscale_events)
        self.cluster_report = aggregate.merge_snapshots(
            finals, lost_workers=lost, autoscale_events=scale_events)
        self.run_report = (
            aggregate.merged_run_report(tel, finals, lost_workers=lost,
                                        autoscale_events=scale_events)
            if tel is not None else None)
        view = self._fed_view
        if view is not None:
            fed_sec = view.status()
            with self._lock:
                fed_sec["postmortems"] = list(self.postmortem_paths)
            self.cluster_report["federation"] = fed_sec
            if self.run_report is not None:
                self.run_report.setdefault(
                    "cluster", {})["federation"] = fed_sec
        if handler is not None:
            # the coordinator-side router view (replica map, failover
            # tallies, cutovers) joins the worker-side serving stats the
            # snapshot merge already folded in — one `serving` section
            # per report, both halves of the plane
            srv = handler.report_section()
            self.cluster_report.setdefault("serving", {})["router"] = srv
            if self.run_report is not None:
                cluster_sec = self.run_report.setdefault("cluster", {})
                cluster_sec.setdefault("serving", {})["router"] = srv
                self.run_report["serving"] = cluster_sec["serving"]

    def __enter__(self) -> "ClusterRouter":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def __del__(self) -> None:  # safety net only; callers use close()/with
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


# ---------------------------------------------------------------------------
# The process-wide router (EngineConfig.cluster_workers is the ONE knob)
# ---------------------------------------------------------------------------

_router_lock = threading.Lock()
_router: Optional[ClusterRouter] = None
_router_key: Optional[Tuple[int, Optional[int], bool,
                            Optional[float]]] = None
_last_router: Optional[ClusterRouter] = None


def _default_federation_rules() -> List[Any]:
    """The ruleset a router's federated watchdog runs when the caller
    supplied none: the ``cluster_``-prefixed copies of
    ``slo.default_rules``. Module-level so tests (and operators with a
    sitecustomize) can swap the default in ONE place."""
    from sparkdl_tpu.core import slo as _slo

    return list(_slo.federated_default_rules())


def exporter_status() -> Optional[Dict[str, Any]]:
    """Compact federated-view status for the snapshot exporter's
    ``cluster`` key — ``None`` unless a LIVE router has federation
    armed. The exporter probes this via ``sys.modules`` (it never
    imports the cluster plane), so a run that never armed it emits
    byte-identical artifacts."""
    router = _router
    if router is None or router.closed:
        return None
    view = router._fed_view
    if view is None:
        return None
    status = view.status()
    with router._lock:
        if router.postmortem_paths:
            status["postmortems"] = list(router.postmortem_paths)
    return status


def exporter_prometheus_text() -> str:
    """Federated ``sparkdl_cluster_*`` Prometheus families for the
    exporter's ``.prom`` file — ``""`` unless a live router has
    federation armed, so the off-path scrape text is unchanged."""
    router = _router
    if router is None or router.closed:
        return ""
    view = router._fed_view
    if view is None:
        return ""
    return view.prometheus_text()


def maybe_router() -> Optional[ClusterRouter]:
    """The process-wide router per ``EngineConfig.cluster_workers``, or
    ``None`` when the cluster plane is disabled (``cluster_workers=0``,
    the bit-identical in-process default) or when called from inside a
    cluster worker. Reconfiguring the knobs closes the old router (and
    merges its reports) before spawning the new one."""
    if _worker_mod._IN_WORKER:
        return None
    from sparkdl_tpu.engine.dataframe import EngineConfig

    EngineConfig.validate()
    workers = EngineConfig.cluster_workers
    if not workers:
        return None
    key = (workers, EngineConfig.cluster_inflight_partitions,
           EngineConfig.cluster_autoscale,
           EngineConfig.cluster_federation_s)
    global _router, _router_key, _last_router
    with _router_lock:
        stale = _router
        if stale is not None and _router_key == key and not stale.closed:
            return stale
        _router = None
    if stale is not None:
        stale.close()  # outside the lock: close() joins processes
        _last_router = stale
    with _router_lock:
        if _router is None or _router_key != key or _router.closed:
            _router = ClusterRouter(
                workers, inflight=EngineConfig.cluster_inflight_partitions)
            _router_key = key
        return _router


def shutdown() -> None:
    """Close the process-wide router (tests, bench legs, atexit) —
    this is the moment workers ship their snapshots and the merged
    reports land (readable via :func:`last_cluster_report`)."""
    global _router, _last_router
    with _router_lock:
        router, _router = _router, None
    if router is not None:
        router.close()
        _last_router = router


def last_cluster_report() -> Optional[Dict[str, Any]]:
    """The most recent merged per-worker snapshot section (survives
    :func:`shutdown` — reports are produced BY closing)."""
    router = _router if _router is not None else _last_router
    return router.cluster_report if router is not None else None


def last_run_report() -> Optional[Dict[str, Any]]:
    """The most recent merged ``RunReport`` (coordinator report + the
    ``cluster`` section), if a telemetry scope was active at close."""
    router = _router if _router is not None else _last_router
    return router.run_report if router is not None else None


atexit.register(shutdown)
