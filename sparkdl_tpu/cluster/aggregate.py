"""Cross-worker observability merge for the cluster inference plane.

A cluster run is N worker processes, each with its OWN telemetry scope
(``Telemetry(run_id=...)`` pinned to the coordinator's run id) and its
own :class:`~sparkdl_tpu.core.health.HealthMonitor`. Without a merge
step, the operator story regresses to N disjoint black boxes — the
exact failure mode the single-process ``RunReport`` was built to
prevent. This module is the merge step: each worker builds ONE
end-of-run snapshot (:func:`build_snapshot`, shipped over its private
result pipe as the last message before EOF) and the coordinator folds
the snapshots into a single ``cluster`` section
(:func:`merge_snapshots`) or a full merged run report
(:func:`merged_run_report`).

Two accounting paths exist for health counts — the worker's monitor
counters and the ``sparkdl.health.<event>`` metric mirrors
:func:`sparkdl_tpu.core.health.record` writes through one choke point —
and the merge cross-checks them (``health_consistent``): equality is
*proven* per merge, not assumed, so a divergence (a worker recording
outside its scopes) is visible in the report instead of silently
producing two different truths.

Stdlib + ``core.telemetry`` only — importable from a freshly spawned
worker without dragging in jax.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from sparkdl_tpu.core import telemetry

__all__ = ["build_snapshot", "merge_snapshots", "merged_run_report",
           "sum_canonical_counters", "sum_health_counters"]


def build_snapshot(worker: str, pid: int, tel: Any, monitor: Any, *,
                   tasks: int, rows: int, exec_s: float,
                   phases: Optional[Dict[str, Any]] = None,
                   span_ring: Optional[Dict[str, Any]] = None,
                   serving: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """One worker's end-of-run snapshot (worker-side, while its
    telemetry scope and health monitor are still active): the same
    ingredients ``RunReport.build`` uses, JSON-able, small enough to
    ship over the result pipe. With cross-process tracing armed,
    ``span_ring`` is :meth:`Tracer.export_ring`'s shippable view of the
    worker's spans (rebased onto the coordinator's clock); the key is
    absent entirely when tracing is off, keeping the off-path snapshot
    byte-identical. Same stance for ``serving``: a worker that hosted
    replicated deployments ships its ``WorkerServingPlane.stats()``
    here, and the key is absent when the serving plane never ran."""
    snap = {
        "worker": worker,
        "pid": pid,
        "run_id": tel.run_id,
        "tasks": tasks,
        "rows": rows,
        "exec_s": round(exec_s, 6),
        "metrics": tel.metrics.snapshot(),
        "health": monitor.report(),
        "trace": tel.tracer.summary(),
        "phases": dict(phases or {}),
    }
    tenants = _tenant_section(snap["metrics"])
    if tenants:
        snap["tenants"] = tenants
    if span_ring is not None:
        snap["span_ring"] = span_ring
    if serving is not None:
        snap["serving"] = serving
    return snap


def _tenant_section(metrics: Dict[str, Any]) -> Dict[str, Any]:
    """Per-tenant queue-wait view, derived from the per-tenant histogram
    series ``core/executor.py`` emits (``sparkdl.executor.queue_wait_s.
    <tenant>``). Empty — and the section absent — when no non-default
    tenant ran, keeping single-tenant snapshots byte-identical."""
    prefix = telemetry.M_QUEUE_WAIT_S + "."
    out: Dict[str, Any] = {}
    for name, hist in ((metrics or {}).get("histograms") or {}).items():
        if name.startswith(prefix):
            out[name[len(prefix):]] = {
                "count": hist.get("count", 0),
                "sum_s": hist.get("sum", 0.0),
                "p99_s": hist.get("p99"),
            }
    return dict(sorted(out.items()))


def sum_canonical_counters(snapshots: Sequence[Dict[str, Any]]
                           ) -> Dict[str, float]:
    """Sum each worker's counter metrics, restricted to the canonical
    catalog plus the ``sparkdl.health.*`` mirrors — ad-hoc counters stay
    in the per-worker sections, so the cluster-wide totals only ever
    contain names the taxonomy lint enforces."""
    totals: Dict[str, float] = {}
    for snap in snapshots:
        counters = (snap.get("metrics") or {}).get("counters") or {}
        for name, value in counters.items():
            if (name in telemetry.CANONICAL_METRIC_NAMES
                    or name.startswith(telemetry.HEALTH_METRIC_PREFIX)):
                totals[name] = totals.get(name, 0) + value
    return dict(sorted(totals.items()))


def sum_health_counters(snapshots: Sequence[Dict[str, Any]]
                        ) -> Dict[str, int]:
    """Sum the worker HealthMonitor counters across snapshots — the
    monitor-side accounting path, kept independent of the metric
    mirrors so :func:`merge_snapshots` can cross-check the two."""
    totals: Dict[str, int] = {}
    for snap in snapshots:
        counters = (snap.get("health") or {}).get("counters") or {}
        for name, value in counters.items():
            totals[name] = totals.get(name, 0) + value
    return dict(sorted(totals.items()))


def merge_snapshots(snapshots: Sequence[Dict[str, Any]],
                    lost_workers: Sequence[str] = (),
                    autoscale_events: Sequence[Dict[str, Any]] = ()
                    ) -> Dict[str, Any]:
    """Fold per-worker snapshots into ONE ``cluster`` report section.

    Per-worker sections survive verbatim under ``workers`` (debugging a
    sick worker needs its un-summed view), canonical counters are
    summed cluster-wide, and the merged health counters are the sum of
    the worker monitors — with ``health_consistent`` proving that sum
    equals the independently-accumulated ``sparkdl.health.*`` metric
    mirrors, event for event.

    With cross-process tracing armed (any snapshot carrying a
    ``span_ring``), a ``trace`` subsection records spans shipped and
    dropped PER WORKER — ring truncation is visible in the report, not
    silent — plus one ``span_rings_lost`` entry per worker that died
    without shipping its final snapshot (``lost_workers``, from the
    router). Off-path reports keep their exact pre-tracing shape.

    With the elastic-capacity plane active, ``autoscale_events`` (the
    router's ordered spawn/drain ledger) becomes an ``autoscale``
    subsection — the event list verbatim plus scale-up/scale-down/drain
    tallies — and any per-tenant queue-wait series in the worker
    snapshots merge into a ``tenants`` subsection (counts summed;
    ``p99_s`` is the WORST worker's p99, since percentiles cannot be
    merged exactly across independent histograms). Both keys are absent
    when the features are off.

    With the cluster serving plane active (any snapshot carrying a
    ``serving`` section), a ``serving`` subsection folds the per-worker
    replica stats together: predicts/errors summed, plus the
    worker-side replica map ``{model: {version: [workers deployed]}}``
    — the router enriches it at close with its coordinator-side view
    (``serving.router``: routing, failovers, cutovers). Absent when no
    worker served.
    """
    snapshots = [s for s in snapshots if s]
    health_totals = sum_health_counters(snapshots)
    counters = sum_canonical_counters(snapshots)
    prefix = telemetry.HEALTH_METRIC_PREFIX
    mirrored = {name[len(prefix):]: int(value)
                for name, value in counters.items()
                if name.startswith(prefix)}
    out = {
        "worker_count": len(snapshots),
        "workers": {s["worker"]: s for s in snapshots},
        "counters": counters,
        "health": {"counters": health_totals},
        "health_consistent": mirrored == health_totals,
        "tasks_per_worker": {s["worker"]: s.get("tasks", 0)
                             for s in snapshots},
        "rows_per_worker": {s["worker"]: s.get("rows", 0)
                            for s in snapshots},
        "exec_s_per_worker": {s["worker"]: s.get("exec_s", 0.0)
                              for s in snapshots},
    }
    if any(s.get("span_ring") is not None for s in snapshots):
        out["trace"] = {
            "workers": {
                s["worker"]: {
                    "shipped": len(s["span_ring"]["spans"]),
                    "dropped": s["span_ring"]["dropped"],
                    "clock_offset_ns": s["span_ring"]["clock_offset_ns"],
                }
                for s in snapshots if s.get("span_ring") is not None},
            "span_rings_lost": sorted(lost_workers),
        }
    tenants: Dict[str, Dict[str, Any]] = {}
    for s in snapshots:
        for tenant, view in (s.get("tenants") or {}).items():
            agg = tenants.setdefault(
                tenant, {"count": 0, "sum_s": 0.0, "p99_s": None})
            agg["count"] += view.get("count", 0)
            agg["sum_s"] = round(agg["sum_s"] + view.get("sum_s", 0.0), 9)
            p99 = view.get("p99_s")
            if p99 is not None and (agg["p99_s"] is None
                                    or p99 > agg["p99_s"]):
                agg["p99_s"] = p99
    if tenants:
        out["tenants"] = dict(sorted(tenants.items()))
    serving_workers = {s["worker"]: s["serving"] for s in snapshots
                       if s.get("serving") is not None}
    if serving_workers:
        replicas: Dict[str, Dict[str, List[str]]] = {}
        for wname, srv in serving_workers.items():
            for dep in srv.get("deployments", ()):
                versions = replicas.setdefault(dep["model"], {})
                versions.setdefault(dep["version"], []).append(wname)
        out["serving"] = {
            "workers": serving_workers,
            "predicts": sum(s.get("predicts", 0)
                            for s in serving_workers.values()),
            "errors": sum(s.get("errors", 0)
                          for s in serving_workers.values()),
            "replicas": {m: {v: sorted(ws) for v, ws in sorted(vs.items())}
                         for m, vs in sorted(replicas.items())},
        }
    if autoscale_events:
        events = [dict(e) for e in autoscale_events]
        out["autoscale"] = {
            "events": events,
            "scale_ups": sum(1 for e in events
                             if e.get("action") == "spawn"
                             and e.get("reason") == "scale_up"),
            "scale_downs": sum(1 for e in events
                               if e.get("action") == "draining"
                               and e.get("reason") == "scale_down"),
            "drained": sum(1 for e in events
                           if e.get("action") == "drained"),
        }
    return out


def merged_run_report(tel: Any, snapshots: Sequence[Dict[str, Any]],
                      health_monitor: Any = None,
                      lost_workers: Sequence[str] = (),
                      autoscale_events: Sequence[Dict[str, Any]] = ()
                      ) -> Dict[str, Any]:
    """The coordinator's normal ``RunReport`` plus the merged
    ``cluster`` section — one artifact for the whole cluster run."""
    report = telemetry.RunReport.build(tel, health_monitor)
    report["cluster"] = merge_snapshots(snapshots,
                                        lost_workers=lost_workers,
                                        autoscale_events=autoscale_events)
    return report
