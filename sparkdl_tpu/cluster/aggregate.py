"""Cross-worker observability merge for the cluster inference plane.

A cluster run is N worker processes, each with its OWN telemetry scope
(``Telemetry(run_id=...)`` pinned to the coordinator's run id) and its
own :class:`~sparkdl_tpu.core.health.HealthMonitor`. Without a merge
step, the operator story regresses to N disjoint black boxes — the
exact failure mode the single-process ``RunReport`` was built to
prevent. This module is the merge step: each worker builds ONE
end-of-run snapshot (:func:`build_snapshot`, shipped over its private
result pipe as the last message before EOF) and the coordinator folds
the snapshots into a single ``cluster`` section
(:func:`merge_snapshots`) or a full merged run report
(:func:`merged_run_report`).

Two accounting paths exist for health counts — the worker's monitor
counters and the ``sparkdl.health.<event>`` metric mirrors
:func:`sparkdl_tpu.core.health.record` writes through one choke point —
and the merge cross-checks them (``health_consistent``): equality is
*proven* per merge, not assumed, so a divergence (a worker recording
outside its scopes) is visible in the report instead of silently
producing two different truths.

Stdlib + ``core.telemetry`` only — importable from a freshly spawned
worker without dragging in jax.
"""

from __future__ import annotations

import bisect
import math
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from sparkdl_tpu.core import telemetry

__all__ = ["build_snapshot", "merge_snapshots", "merged_run_report",
           "sum_canonical_counters", "sum_health_counters",
           "build_frame", "ClusterMetricsView"]


def build_snapshot(worker: str, pid: int, tel: Any, monitor: Any, *,
                   tasks: int, rows: int, exec_s: float,
                   phases: Optional[Dict[str, Any]] = None,
                   span_ring: Optional[Dict[str, Any]] = None,
                   serving: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """One worker's end-of-run snapshot (worker-side, while its
    telemetry scope and health monitor are still active): the same
    ingredients ``RunReport.build`` uses, JSON-able, small enough to
    ship over the result pipe. With cross-process tracing armed,
    ``span_ring`` is :meth:`Tracer.export_ring`'s shippable view of the
    worker's spans (rebased onto the coordinator's clock); the key is
    absent entirely when tracing is off, keeping the off-path snapshot
    byte-identical. Same stance for ``serving``: a worker that hosted
    replicated deployments ships its ``WorkerServingPlane.stats()``
    here, and the key is absent when the serving plane never ran."""
    snap = {
        "worker": worker,
        "pid": pid,
        "run_id": tel.run_id,
        "tasks": tasks,
        "rows": rows,
        "exec_s": round(exec_s, 6),
        "metrics": tel.metrics.snapshot(),
        "health": monitor.report(),
        "trace": tel.tracer.summary(),
        "phases": dict(phases or {}),
    }
    tenants = _tenant_section(snap["metrics"])
    if tenants:
        snap["tenants"] = tenants
    if span_ring is not None:
        snap["span_ring"] = span_ring
    if serving is not None:
        snap["serving"] = serving
    return snap


def _tenant_section(metrics: Dict[str, Any]) -> Dict[str, Any]:
    """Per-tenant queue-wait view, derived from the per-tenant histogram
    series ``core/executor.py`` emits (``sparkdl.executor.queue_wait_s.
    <tenant>``). Empty — and the section absent — when no non-default
    tenant ran, keeping single-tenant snapshots byte-identical."""
    prefix = telemetry.M_QUEUE_WAIT_S + "."
    out: Dict[str, Any] = {}
    for name, hist in ((metrics or {}).get("histograms") or {}).items():
        if name.startswith(prefix):
            out[name[len(prefix):]] = {
                "count": hist.get("count", 0),
                "sum_s": hist.get("sum", 0.0),
                "p99_s": hist.get("p99"),
                # the raw per-bucket counts (plus the observed envelope)
                # ride along so the coordinator's merge can estimate the
                # CLUSTER p99 from one merged bucket array instead of
                # taking the worst worker's estimate
                "buckets": hist.get("buckets") or {},
                "min_s": hist.get("min"),
                "max_s": hist.get("max"),
            }
    return dict(sorted(out.items()))


def _merged_bucket_percentile(views: Sequence[Dict[str, Any]],
                              q: float = 0.99) -> Optional[float]:
    """Estimate one percentile over the SUM of several workers' bucket
    dicts (``Histogram.snapshot()`` format: per-bucket counts keyed by
    the ``repr`` of the upper bound, ``"+Inf"`` for overflow), assuming
    the default time ladder. Returns ``None`` when any view lacks
    buckets or carries a bound off the ladder — the caller falls back
    to the worst-worker estimate rather than merging unlike ladders."""
    bounds = telemetry.DEFAULT_TIME_BOUNDS
    counts = [0] * (len(bounds) + 1)
    count = 0
    vmin: Optional[float] = None
    vmax: Optional[float] = None
    for view in views:
        buckets = view.get("buckets")
        if not buckets:
            if view.get("count"):
                return None  # samples without bucket data: cannot merge
            continue
        for key, c in buckets.items():
            if key == "+Inf":
                idx = len(bounds)
            else:
                try:
                    bound = float(key)
                except (TypeError, ValueError):
                    return None
                idx = bisect.bisect_left(bounds, bound)
                if idx >= len(bounds) or bounds[idx] != bound:
                    return None  # off-ladder bound: unmergeable
            counts[idx] += int(c)
            count += int(c)
        lo, hi = view.get("min_s"), view.get("max_s")
        if lo is not None:
            vmin = lo if vmin is None else min(vmin, lo)
        if hi is not None:
            vmax = hi if vmax is None else max(vmax, hi)
    return telemetry._estimate_percentile(q, counts, count, bounds,
                                          vmin, vmax)


def sum_canonical_counters(snapshots: Sequence[Dict[str, Any]]
                           ) -> Dict[str, float]:
    """Sum each worker's counter metrics, restricted to the canonical
    catalog plus the ``sparkdl.health.*`` mirrors — ad-hoc counters stay
    in the per-worker sections, so the cluster-wide totals only ever
    contain names the taxonomy lint enforces."""
    totals: Dict[str, float] = {}
    for snap in snapshots:
        counters = (snap.get("metrics") or {}).get("counters") or {}
        for name, value in counters.items():
            if (name in telemetry.CANONICAL_METRIC_NAMES
                    or name.startswith(telemetry.HEALTH_METRIC_PREFIX)):
                totals[name] = totals.get(name, 0) + value
    return dict(sorted(totals.items()))


def sum_health_counters(snapshots: Sequence[Dict[str, Any]]
                        ) -> Dict[str, int]:
    """Sum the worker HealthMonitor counters across snapshots — the
    monitor-side accounting path, kept independent of the metric
    mirrors so :func:`merge_snapshots` can cross-check the two."""
    totals: Dict[str, int] = {}
    for snap in snapshots:
        counters = (snap.get("health") or {}).get("counters") or {}
        for name, value in counters.items():
            totals[name] = totals.get(name, 0) + value
    return dict(sorted(totals.items()))


def merge_snapshots(snapshots: Sequence[Dict[str, Any]],
                    lost_workers: Sequence[str] = (),
                    autoscale_events: Sequence[Dict[str, Any]] = ()
                    ) -> Dict[str, Any]:
    """Fold per-worker snapshots into ONE ``cluster`` report section.

    Per-worker sections survive verbatim under ``workers`` (debugging a
    sick worker needs its un-summed view), canonical counters are
    summed cluster-wide, and the merged health counters are the sum of
    the worker monitors — with ``health_consistent`` proving that sum
    equals the independently-accumulated ``sparkdl.health.*`` metric
    mirrors, event for event.

    With cross-process tracing armed (any snapshot carrying a
    ``span_ring``), a ``trace`` subsection records spans shipped and
    dropped PER WORKER — ring truncation is visible in the report, not
    silent — plus one ``span_rings_lost`` entry per worker that died
    without shipping its final snapshot (``lost_workers``, from the
    router). Off-path reports keep their exact pre-tracing shape.

    With the elastic-capacity plane active, ``autoscale_events`` (the
    router's ordered spawn/drain ledger) becomes an ``autoscale``
    subsection — the event list verbatim plus scale-up/scale-down/drain
    tallies — and any per-tenant queue-wait series in the worker
    snapshots merge into a ``tenants`` subsection (counts summed;
    ``p99_s`` is the WORST worker's p99, since percentiles cannot be
    merged exactly across independent histograms). Both keys are absent
    when the features are off.

    With the cluster serving plane active (any snapshot carrying a
    ``serving`` section), a ``serving`` subsection folds the per-worker
    replica stats together: predicts/errors summed, plus the
    worker-side replica map ``{model: {version: [workers deployed]}}``
    — the router enriches it at close with its coordinator-side view
    (``serving.router``: routing, failovers, cutovers). Absent when no
    worker served.
    """
    snapshots = [s for s in snapshots if s]
    health_totals = sum_health_counters(snapshots)
    counters = sum_canonical_counters(snapshots)
    prefix = telemetry.HEALTH_METRIC_PREFIX
    mirrored = {name[len(prefix):]: int(value)
                for name, value in counters.items()
                if name.startswith(prefix)}
    out = {
        "worker_count": len(snapshots),
        "workers": {s["worker"]: s for s in snapshots},
        "counters": counters,
        "health": {"counters": health_totals},
        "health_consistent": mirrored == health_totals,
        "tasks_per_worker": {s["worker"]: s.get("tasks", 0)
                             for s in snapshots},
        "rows_per_worker": {s["worker"]: s.get("rows", 0)
                            for s in snapshots},
        "exec_s_per_worker": {s["worker"]: s.get("exec_s", 0.0)
                              for s in snapshots},
    }
    if any(s.get("span_ring") is not None for s in snapshots):
        out["trace"] = {
            "workers": {
                s["worker"]: {
                    "shipped": len(s["span_ring"]["spans"]),
                    "dropped": s["span_ring"]["dropped"],
                    "clock_offset_ns": s["span_ring"]["clock_offset_ns"],
                }
                for s in snapshots if s.get("span_ring") is not None},
            "span_rings_lost": sorted(lost_workers),
        }
    tenants: Dict[str, Dict[str, Any]] = {}
    tenant_views: Dict[str, List[Dict[str, Any]]] = {}
    for s in snapshots:
        for tenant, view in (s.get("tenants") or {}).items():
            agg = tenants.setdefault(
                tenant, {"count": 0, "sum_s": 0.0, "p99_s": None,
                         "p99_worst_worker": None})
            agg["count"] += view.get("count", 0)
            agg["sum_s"] = round(agg["sum_s"] + view.get("sum_s", 0.0), 9)
            p99 = view.get("p99_s")
            if p99 is not None and (agg["p99_worst_worker"] is None
                                    or p99 > agg["p99_worst_worker"]):
                agg["p99_worst_worker"] = p99
            tenant_views.setdefault(tenant, []).append(view)
    for tenant, agg in tenants.items():
        # the cluster p99 is a REAL merged percentile (bucket counts
        # summed across workers, one estimate over the sum); the old
        # worst-worker value stays published as p99_worst_worker for one
        # release of comparability, and is the fallback when a worker
        # shipped no bucket data to merge
        merged = _merged_bucket_percentile(tenant_views[tenant], q=0.99)
        agg["p99_s"] = (merged if merged is not None
                        else agg["p99_worst_worker"])
    if tenants:
        out["tenants"] = dict(sorted(tenants.items()))
    serving_workers = {s["worker"]: s["serving"] for s in snapshots
                       if s.get("serving") is not None}
    if serving_workers:
        replicas: Dict[str, Dict[str, List[str]]] = {}
        for wname, srv in serving_workers.items():
            for dep in srv.get("deployments", ()):
                versions = replicas.setdefault(dep["model"], {})
                versions.setdefault(dep["version"], []).append(wname)
        out["serving"] = {
            "workers": serving_workers,
            "predicts": sum(s.get("predicts", 0)
                            for s in serving_workers.values()),
            "errors": sum(s.get("errors", 0)
                          for s in serving_workers.values()),
            "replicas": {m: {v: sorted(ws) for v, ws in sorted(vs.items())}
                         for m, vs in sorted(replicas.items())},
        }
    if autoscale_events:
        events = [dict(e) for e in autoscale_events]
        out["autoscale"] = {
            "events": events,
            "scale_ups": sum(1 for e in events
                             if e.get("action") == "spawn"
                             and e.get("reason") == "scale_up"),
            "scale_downs": sum(1 for e in events
                               if e.get("action") == "draining"
                               and e.get("reason") == "scale_down"),
            "drained": sum(1 for e in events
                           if e.get("action") == "drained"),
        }
    return out


def merged_run_report(tel: Any, snapshots: Sequence[Dict[str, Any]],
                      health_monitor: Any = None,
                      lost_workers: Sequence[str] = (),
                      autoscale_events: Sequence[Dict[str, Any]] = ()
                      ) -> Dict[str, Any]:
    """The coordinator's normal ``RunReport`` plus the merged
    ``cluster`` section — one artifact for the whole cluster run."""
    report = telemetry.RunReport.build(tel, health_monitor)
    report["cluster"] = merge_snapshots(snapshots,
                                        lost_workers=lost_workers,
                                        autoscale_events=autoscale_events)
    return report


# ---------------------------------------------------------------------------
# Live metrics federation (docs/OBSERVABILITY.md "Cluster metrics
# federation"): workers ship bounded windowed-metrics frames at the
# federation cadence; the coordinator folds them into ONE live view.
# ---------------------------------------------------------------------------


def build_frame(worker: str, wid: int, seq: int, tel: Any,
                clock_offset_ns: int = 0) -> Optional[Dict[str, Any]]:
    """One worker's metrics-federation frame (worker-side, between
    tasks): ``MetricsRegistry.export_frame()``'s canonical-name-filtered
    ring export plus the worker identity, a per-worker frame sequence
    number, and the clock-handshake offset the coordinator needs to
    rebase the slot epochs onto its own clock. ``None`` when the
    worker's registry has no windows (nothing to federate)."""
    frame = tel.metrics.export_frame()
    if frame is None:
        return None
    frame["worker"] = worker
    frame["wid"] = wid
    frame["seq"] = seq
    frame["clock_offset_ns"] = int(clock_offset_ns)
    return frame


class ClusterMetricsView:
    """The coordinator's live fold of worker metrics frames.

    Each :func:`build_frame` payload is the full state of one worker's
    metric rings (merge-by-replace per worker: a dropped frame heals on
    the next cadence). The fold happens at QUERY time —
    :meth:`window_snapshot` walks the retained frames, rebases every
    slot epoch onto the coordinator's clock (the per-worker slot shift
    is ``round(clock_offset / slot_span)``, from the PR 15 clock
    handshake, so a skewed worker's samples land in the coordinator
    slots they actually happened in: no double-count, no gap), sums
    counters, merges gauge envelopes, and SUMS histogram bucket arrays
    per slot — a cluster p99 is one estimate over the merged buckets,
    not a worst-worker guess.

    Staleness: a worker whose last frame is older than
    ``stale_factor × cadence_s`` — or that the router marked dead — is
    aged OUT of the fold, and ``workers_reporting`` says so explicitly.
    Its last frame is retained (not folded) so a postmortem bundle can
    still show the dead worker's final shipped state.

    The view quacks like a :class:`telemetry.MetricsRegistry` for the
    SLO watchdog: ``window_snapshot(window_s)`` returns the exact
    windowed shape ``SLOWatchdog.evaluate`` consumes, so a plain
    watchdog evaluates cluster-level rules against it unchanged.

    Thread-safe: the router's collector ingests while the exporter
    thread (and tests) query.
    """

    #: Exemplars kept per merged histogram window (the per-worker
    #: reservoirs are already tiny; the merge keeps the global tail).
    MERGED_EXEMPLAR_K = 8

    def __init__(self, cadence_s: float, stale_factor: float = 3.0,
                 timeline_max: int = 240) -> None:
        if cadence_s <= 0:
            raise ValueError(
                f"federation cadence_s must be > 0, got {cadence_s!r}")
        self.cadence_s = float(cadence_s)
        self.stale_after_s = float(stale_factor) * self.cadence_s
        self._lock = threading.Lock()
        # worker name -> {frame, shift, last_seen, alive}
        self._workers: Dict[str, Dict[str, Any]] = {}
        self._timeline: "deque[Dict[str, Any]]" = deque(
            maxlen=timeline_max)
        self.frames_ingested = 0
        self.window_s: Optional[float] = None  # ring capacity, learned

    # -- ingest (collector thread) -------------------------------------------

    def ingest(self, frame: Dict[str, Any],
               now: Optional[float] = None) -> None:
        """Fold one worker frame in (merge-by-replace for that worker).
        The slot shift is computed once here from the frame's shipped
        clock offset; sub-slot skew is absorbed by round-to-nearest."""
        span = float(frame.get("span_s") or 0.0)
        slots = int(frame.get("slots") or 0)
        worker = frame.get("worker")
        if span <= 0 or slots <= 0 or not worker:
            return
        if now is None:
            now = telemetry._monotonic()
        offset_s = float(frame.get("clock_offset_ns") or 0) / 1e9
        shift = int(math.floor(offset_s / span + 0.5))
        with self._lock:
            self._workers[worker] = {
                "frame": frame, "shift": shift, "last_seen": now,
                "alive": True}
            self.frames_ingested += 1
            self.window_s = span * slots

    def mark_dead(self, worker: str) -> None:
        """Age a dead worker out of the fold immediately (its pipe hit
        EOF — no more frames are coming); the last frame is retained
        for the flight recorder."""
        with self._lock:
            entry = self._workers.get(worker)
            if entry is not None:
                entry["alive"] = False

    # -- accounting ----------------------------------------------------------

    def _fresh_locked(self, now: float) -> List[Dict[str, Any]]:
        return [e for e in self._workers.values()
                if e["alive"] and now - e["last_seen"] <= self.stale_after_s]

    def workers_reporting(self, now: Optional[float] = None) -> int:
        """Workers currently IN the fold: alive (no EOF) and fresh
        (frame newer than the staleness horizon)."""
        if now is None:
            now = telemetry._monotonic()
        with self._lock:
            return len(self._fresh_locked(now))

    def fresh_workers(self, now: Optional[float] = None) -> List[str]:
        """The names behind :meth:`workers_reporting` — the router's
        collector diffs consecutive calls to emit one
        ``cluster_metrics_stale`` event per worker leaving the fold."""
        if now is None:
            now = telemetry._monotonic()
        with self._lock:
            return sorted(w for w, e in self._workers.items()
                          if e["alive"]
                          and now - e["last_seen"] <= self.stale_after_s)

    def last_frames(self) -> Dict[str, Dict[str, Any]]:
        """Every retained frame (fresh, stale, AND dead workers') with
        its accounting — the flight recorder's raw material."""
        with self._lock:
            return {w: {"frame": e["frame"], "alive": e["alive"],
                        "last_seen": e["last_seen"]}
                    for w, e in sorted(self._workers.items())}

    # -- the fold ------------------------------------------------------------

    def window_snapshot(self, window_s: Optional[float] = None,
                        now: Optional[float] = None) -> Dict[str, Any]:
        """The federated windowed view, in ``MetricsRegistry.
        window_snapshot`` shape (plus ``workers_reporting``) so the SLO
        watchdog, the autoscaler, and the exporter consume it like a
        local registry."""
        if now is None:
            now = telemetry._monotonic()
        with self._lock:
            entries = self._fresh_locked(now)
            reporting = len(entries)
            cap = self.window_s
        if window_s is None:
            window_s = cap
        if cap is not None and window_s is not None:
            window_s = min(float(window_s), cap)
        out = self._fold(entries, window_s, now)
        out["workers_reporting"] = reporting
        return out

    def attribution(self, metric: str, stat: str,
                    window_s: Optional[float] = None,
                    now: Optional[float] = None) -> Dict[str, Any]:
        """Per-worker observed values for one metric/stat over the
        window — what a federated breach event carries so the operator
        sees WHICH workers drove the cluster-wide verdict."""
        if now is None:
            now = telemetry._monotonic()
        with self._lock:
            entries = {w: e for w, e in sorted(self._workers.items())
                       if e["alive"]
                       and now - e["last_seen"] <= self.stale_after_s}
        out: Dict[str, Any] = {}
        for worker, entry in entries.items():
            folded = self._fold([entry], window_s, now)
            hist = folded["histograms"].get(metric)
            ctr = folded["counters"].get(metric)
            gauge = folded["gauges"].get(metric)
            if hist is not None and stat in hist:
                out[worker] = hist[stat]
            elif ctr is not None and stat in ctr:
                out[worker] = ctr[stat]
            elif gauge is not None and stat == "value":
                out[worker] = gauge["last"]
            else:
                out[worker] = None
        return out

    def _fold(self, entries: Sequence[Dict[str, Any]],
              window_s: Optional[float], now: float) -> Dict[str, Any]:
        if not entries or not window_s or window_s <= 0:
            return {"window_s": window_s if window_s else None,
                    "counters": {}, "gauges": {}, "histograms": {}}
        span = float(entries[0]["frame"]["span_s"])
        slots = int(entries[0]["frame"]["slots"])
        # the coordinator-clock window floor — the same arithmetic as
        # telemetry._window_floor, but over the query clock so fakes in
        # tests drive it deterministically
        k = min(slots, max(1, math.ceil(window_s / span)))
        floor = int(now / span) - k + 1
        counters: Dict[str, int] = {}
        gauges: Dict[str, List[Tuple[int, List[float]]]] = {}
        hists: Dict[str, Dict[str, Any]] = {}
        for entry in entries:
            frame, shift = entry["frame"], entry["shift"]
            for name, per_slot in (frame.get("counters") or {}).items():
                for epoch, c in per_slot.items():
                    if int(epoch) + shift >= floor:
                        counters[name] = counters.get(name, 0) + int(c)
            for name, per_slot in (frame.get("gauges") or {}).items():
                for epoch, env in per_slot.items():
                    if int(epoch) + shift >= floor:
                        gauges.setdefault(name, []).append(
                            (int(epoch) + shift, list(env)))
            for name, hist in (frame.get("histograms") or {}).items():
                bounds = tuple(float(b) for b in hist.get("bounds") or ())
                agg = hists.setdefault(name, {
                    "bounds": bounds,
                    "counts": [0] * (len(bounds) + 1),
                    "count": 0, "sum": 0.0, "min": None, "max": None,
                    "exemplars": []})
                if agg["bounds"] != bounds:
                    continue  # unlike ladders never merge
                for epoch, slot in (hist.get("slots") or {}).items():
                    if int(epoch) + shift < floor:
                        continue
                    bucket_counts, cnt, total, lo, hi = slot[:5]
                    for j, c in enumerate(bucket_counts):
                        agg["counts"][j] += c
                    agg["count"] += cnt
                    agg["sum"] += total
                    if lo is not None:
                        agg["min"] = (lo if agg["min"] is None
                                      else min(agg["min"], lo))
                    if hi is not None:
                        agg["max"] = (hi if agg["max"] is None
                                      else max(agg["max"], hi))
                    if len(slot) > 5:
                        agg["exemplars"].extend(
                            tuple(ex) for ex in slot[5])
        out_counters = {
            name: {"count": c, "rate_per_s": round(c / window_s, 9)}
            for name, c in sorted(counters.items())}
        out_gauges: Dict[str, Any] = {}
        for name, seen in sorted(gauges.items()):
            seen.sort(key=lambda ev: ev[0])
            out_gauges[name] = {
                "last": seen[-1][1][0],
                "min": min(env[1] for _, env in seen),
                "max": max(env[2] for _, env in seen)}
        out_hists: Dict[str, Any] = {}
        for name, agg in sorted(hists.items()):
            count = agg["count"]
            snap = {
                "count": count,
                "sum": round(agg["sum"], 9),
                "rate_per_s": round(count / window_s, 9),
                "min": agg["min"], "max": agg["max"],
            }
            for stat, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
                snap[stat] = telemetry._estimate_percentile(
                    q, agg["counts"], count, agg["bounds"],
                    agg["min"], agg["max"])
            if agg["exemplars"]:
                exemplars = sorted(agg["exemplars"], reverse=True)
                snap["exemplars"] = [
                    {"value": v, "trace_id": t, "span_id": s}
                    for v, t, s in exemplars[:self.MERGED_EXEMPLAR_K]]
            out_hists[name] = snap
        return {"window_s": float(window_s), "counters": out_counters,
                "gauges": out_gauges, "histograms": out_hists}

    # -- the bounded timeline the flight recorder dumps ----------------------

    def note_timeline(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            self._timeline.append(entry)

    def timeline(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._timeline)

    # -- exporter integration ------------------------------------------------

    def status(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The compact per-tick view the coordinator's snapshot exporter
        embeds (``cluster`` key of each JSONL line): accounting plus the
        non-empty folded instruments."""
        if now is None:
            now = telemetry._monotonic()
        snap = self.window_snapshot(now=now)
        with self._lock:
            known = len(self._workers)
            ingested = self.frames_ingested
        return {
            "workers_reporting": snap["workers_reporting"],
            "workers_known": known,
            "frames_ingested": ingested,
            "window_s": snap["window_s"],
            "counters": {k: v for k, v in snap["counters"].items()
                         if v["count"]},
            "gauges": snap["gauges"],
            "histograms": {
                k: {"count": v["count"], "p50": v["p50"],
                    "p99": v["p99"]}
                for k, v in snap["histograms"].items() if v["count"]},
        }

    def prometheus_text(self, now: Optional[float] = None) -> str:
        """Federated Prometheus series (``sparkdl_cluster_*`` prefix so
        they never collide with the coordinator's local families): the
        merged windowed percentiles/rates plus the reporting gauge —
        what makes a live scrape of the coordinator reflect the whole
        cluster."""
        import re as _re

        snap = self.window_snapshot(now=now)
        lines: List[str] = []

        def family(name: str, kind: str) -> str:
            n = "sparkdl_cluster:" + _re.sub(r"[^a-zA-Z0-9_:]", "_", name)
            lines.append(f"# HELP {n} federated cluster view of {name} "
                         f"(sparkdl_tpu {kind})")
            lines.append(f"# TYPE {n} {kind}")
            return n

        n = family("workers_reporting", "gauge")
        lines.append(f"{n} {snap['workers_reporting']}")
        for name, view in snap["counters"].items():
            n = family(name + ":window_rate_per_s", "gauge")
            lines.append(f"{n} {view['rate_per_s']}")
        for name, view in snap["gauges"].items():
            n = family(name, "gauge")
            lines.append(f"{n} {view['last']}")
        for name, view in snap["histograms"].items():
            for stat in ("p50", "p99"):
                if view[stat] is None:
                    continue
                n = family(f"{name}:window_{stat}", "gauge")
                lines.append(f"{n} {view[stat]}")
        return "\n".join(lines) + "\n"
