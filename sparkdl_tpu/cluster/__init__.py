"""Cluster inference plane: one engine job across N worker processes.

The reference scaled inference horizontally through Spark executors;
this package is that story rebuilt for the TPU-native engine. Three
modules:

- ``cluster/worker.py`` — spawn-context worker process hosting a full
  per-process stack (device runtime, ``DeviceExecutor`` + compiled-fn
  cache, ``Telemetry(run_id=...)`` pinned to the coordinator's run id).
- ``cluster/router.py`` — load-aware partition router for
  ``engine/dataframe.py`` materialize/stream, routed THROUGH the
  existing supervisor so deadlines, classified retry, hedging, and
  quarantine survive the process boundary; precise re-dispatch on
  worker death.
- ``cluster/aggregate.py`` — merges per-worker end-of-run snapshots
  into ONE ``RunReport`` ``cluster`` section.

Gated behind ``EngineConfig.cluster_workers`` (default 0 = in-process
path, byte-identical; this package is never imported). Deliberately no
eager submodule imports here: the gate in ``engine/dataframe.py`` must
stay the only importer, and a spawned worker reaching
``cluster.worker`` must not drag the router (or jax) into its boot.

Docs: docs/DISTRIBUTED.md "Cluster inference".
"""

__all__ = ["aggregate", "router", "worker"]
