"""Keras-name → optax optimizer/loss registry.

Parity: the reference's ``HasKerasOptimizer``/``HasKerasLoss`` params took
keras string names and compiled the keras model with them (SURVEY.md §3.3).
The rebuild keeps the spelling but lowers onto optax, the idiomatic JAX
optimizer library — update rules trace into the same XLA program as the
backward pass.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp
import optax

_OPTIMIZERS = {
    "adam": lambda lr, **kw: optax.adam(lr, **kw),
    "adamw": lambda lr, **kw: optax.adamw(lr, **kw),
    "sgd": lambda lr, **kw: optax.sgd(lr, **kw),
    "rmsprop": lambda lr, **kw: optax.rmsprop(lr, **kw),
    "adagrad": lambda lr, **kw: optax.adagrad(lr, **kw),
    "nadam": lambda lr, **kw: optax.nadam(lr, **kw),
    "adamax": lambda lr, **kw: optax.adamax(lr, **kw),
}

_DEFAULT_LR = {"sgd": 0.01, "adam": 1e-3, "adamw": 1e-3, "rmsprop": 1e-3,
               "adagrad": 1e-2, "nadam": 1e-3, "adamax": 1e-3}


def make_optimizer(name_or_tx: Union[str, optax.GradientTransformation],
                   learning_rate: float = None,
                   **kwargs) -> optax.GradientTransformation:
    """Resolve a keras-style optimizer name (or pass through an optax tx).

    Named optimizers are built through ``optax.inject_hyperparams`` so the
    learning rate lives in ``opt_state.hyperparams`` (a runtime value)
    rather than baked into the update program — one compiled train step
    then serves every learning rate (the Trainer's per-ModelFunction step
    cache; an HPO sweep over lr compiles once instead of once per map).
    """
    if not isinstance(name_or_tx, str):
        return name_or_tx
    name = name_or_tx.lower()
    try:
        ctor = _OPTIMIZERS[name]
    except KeyError:
        raise ValueError(
            f"Unsupported optimizer {name_or_tx!r}; supported: "
            f"{sorted(_OPTIMIZERS)}") from None
    lr = learning_rate if learning_rate is not None else _DEFAULT_LR[name]
    return optax.inject_hyperparams(
        lambda learning_rate: ctor(learning_rate, **kwargs))(
            learning_rate=lr)


# -- losses ------------------------------------------------------------------
# Each: fn(outputs, labels) -> scalar mean loss. Outputs follow the keras
# convention for the matching loss (probabilities for *_crossentropy, since
# keras models end in softmax/sigmoid activations; see from_logits below).

_EPS = 1e-7


def _align_ranks(outputs, labels):
    """keras ``squeeze_or_expand_dimensions``: make elementwise losses see
    matching ranks so (N,) labels vs (N, 1) sigmoid heads never broadcast
    to (N, N). (N, k>1) labels against a 1-unit head raise instead of
    silently broadcasting (ADVICE r2: one-hot labels into sigmoid BCE)."""
    labels = jnp.asarray(labels)
    if (outputs.ndim == labels.ndim and outputs.shape[-1] == 1
            and labels.shape[-1] > 1):
        raise ValueError(
            f"labels with trailing dim {labels.shape[-1]} cannot feed a "
            "1-unit (sigmoid) head; pass (N,) 0/1 labels or argmax the "
            "one-hot")
    if labels.ndim == outputs.ndim - 1 and outputs.shape[-1] == 1:
        labels = labels[..., None]
    elif outputs.ndim == labels.ndim - 1 and labels.shape[-1] == 1:
        outputs = outputs[..., None]
    return outputs, labels


def _categorical_crossentropy(probs, labels):
    probs = jnp.clip(probs, _EPS, 1.0 - _EPS)
    return -jnp.mean(jnp.sum(labels * jnp.log(probs), axis=-1))


def _sparse_categorical_crossentropy(probs, labels):
    probs = jnp.clip(probs, _EPS, 1.0 - _EPS)
    ll = jnp.take_along_axis(jnp.log(probs), labels[..., None].astype(jnp.int32),
                             axis=-1)
    return -jnp.mean(ll)


def _binary_crossentropy(probs, labels):
    probs, labels = _align_ranks(probs, labels)
    probs = jnp.clip(probs, _EPS, 1.0 - _EPS)
    return -jnp.mean(labels * jnp.log(probs)
                     + (1.0 - labels) * jnp.log(1.0 - probs))


def _mse(outputs, labels):
    outputs, labels = _align_ranks(outputs, labels)
    return jnp.mean((outputs - labels) ** 2)


def _mae(outputs, labels):
    outputs, labels = _align_ranks(outputs, labels)
    return jnp.mean(jnp.abs(outputs - labels))


_LOSSES = {
    "categorical_crossentropy": _categorical_crossentropy,
    "sparse_categorical_crossentropy": _sparse_categorical_crossentropy,
    "binary_crossentropy": _binary_crossentropy,
    "mse": _mse,
    "mean_squared_error": _mse,
    "mae": _mae,
    "mean_absolute_error": _mae,
}


def _sigmoid_bce_logits(logits, labels):
    logits, labels = _align_ranks(logits, labels)
    return optax.sigmoid_binary_cross_entropy(logits, labels).mean()


_LOGIT_LOSSES = {
    "categorical_crossentropy": (
        lambda logits, labels: optax.softmax_cross_entropy(logits, labels).mean()),
    "sparse_categorical_crossentropy": (
        lambda logits, labels: optax.softmax_cross_entropy_with_integer_labels(
            logits, labels.astype(jnp.int32)).mean()),
    "binary_crossentropy": _sigmoid_bce_logits,
}


def make_loss(name_or_fn: Union[str, Callable],
              from_logits: bool = False) -> Callable:
    """Resolve a keras-style loss name (or pass through a callable).

    ``from_logits=True`` swaps in the numerically-stable fused logit form
    (use when the model's head has no terminal activation).
    """
    if not isinstance(name_or_fn, str):
        return name_or_fn
    name = name_or_fn.lower()
    table = _LOGIT_LOSSES if from_logits and name in _LOGIT_LOSSES else _LOSSES
    try:
        return table[name]
    except KeyError:
        raise ValueError(
            f"Unsupported loss {name_or_fn!r}; supported: "
            f"{sorted(_LOSSES)}") from None


def accuracy_metric(outputs, labels, from_logits: bool = False) -> jax.Array:
    """Top-1 accuracy; labels may be one-hot or integer class ids.

    Binary heads (``outputs.shape[-1] == 1``) threshold the probability at
    0.5 — or the logit at 0 when ``from_logits`` — instead of argmax (which
    would always predict class 0). Argmax is logits/probs-invariant, so
    ``from_logits`` only matters for the binary path."""
    labels = jnp.asarray(labels)
    if outputs.shape[-1] == 1:
        threshold = 0.0 if from_logits else 0.5
        pred = (outputs[..., 0] >= threshold).astype(jnp.float32)
        if labels.ndim == outputs.ndim:
            # (N,1) labels squeeze; (N,k) one-hot argmaxes to class ids —
            # labels[...,0] would be the class-0 indicator, INVERTING the
            # metric (ADVICE r2)
            labels = (labels[..., 0] if labels.shape[-1] == 1
                      else jnp.argmax(labels, axis=-1))
        return jnp.mean((pred == labels.astype(jnp.float32))
                        .astype(jnp.float32))
    pred = jnp.argmax(outputs, axis=-1)
    if labels.ndim == outputs.ndim:
        labels = jnp.argmax(labels, axis=-1)
    return jnp.mean((pred == labels).astype(jnp.float32))
