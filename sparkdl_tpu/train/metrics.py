"""Structured per-step training/inference metrics.

Parity: SURVEY.md §5.5 — the reference had only Python logging + Spark UI.
Here: a metrics dict per step (loss, accuracy, examples/sec, HBM stats),
pluggable sinks (stdout JSONL first), consumed by bench.py for the
BASELINE-comparable numbers.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Callable, Dict, List, Optional

import jax


def hbm_stats(device=None) -> Dict[str, int]:
    """Bytes in use / limit for one device; {} where unsupported (CPU)."""
    device = device or jax.local_devices()[0]
    try:
        stats = device.memory_stats()
    except (AttributeError, RuntimeError, jax.errors.JaxRuntimeError):
        return {}
    if not stats:
        return {}
    out = {}
    for key in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use"):
        if key in stats:
            out[key] = int(stats[key])
    return out


class MetricsLogger:
    """Collects per-step metric dicts and forwards them to sinks.

    A sink is ``callable(record: dict) -> None``. ``jsonl`` writes one JSON
    object per record to the given stream (stdout default).
    """

    def __init__(self, sinks: Optional[List[Callable]] = None,
                 jsonl_stream=None, every: int = 1) -> None:
        self.sinks = list(sinks or [])
        if jsonl_stream is not None or not self.sinks:
            stream = jsonl_stream or sys.stdout
            self.sinks.append(
                lambda rec: print(json.dumps(rec, default=float), file=stream))
        self.every = max(1, every)
        self.history: List[Dict[str, Any]] = []
        self._t_last: Optional[float] = None

    def log_step(self, step: int, metrics: Dict[str, Any],
                 examples: Optional[int] = None) -> Dict[str, Any]:
        now = time.perf_counter()
        record = {"step": int(step)}
        for k, v in metrics.items():
            record[k] = float(v) if hasattr(v, "item") or isinstance(
                v, (int, float)) else v
        if examples is not None and self._t_last is not None:
            dt = now - self._t_last
            if dt > 0:
                record["examples_per_sec"] = examples / dt
        self._t_last = now
        self.history.append(record)
        if step % self.every == 0:
            for sink in self.sinks:
                sink(record)
        return record
