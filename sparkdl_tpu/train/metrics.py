"""Structured per-step training/inference metrics.

Parity: SURVEY.md §5.5 — the reference had only Python logging + Spark UI.
Here: a metrics dict per step (loss, accuracy, examples/sec, HBM stats),
pluggable sinks (stdout JSONL first), consumed by bench.py for the
BASELINE-comparable numbers.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Callable, Dict, List, Optional

import jax

from sparkdl_tpu.core import telemetry


def hbm_stats(device=None) -> Dict[str, int]:
    """Bytes in use / limit for one device; {} where unsupported (CPU)."""
    device = device or jax.local_devices()[0]
    try:
        stats = device.memory_stats()
    except (AttributeError, RuntimeError, jax.errors.JaxRuntimeError):
        return {}
    if not stats:
        return {}
    out = {}
    for key in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use"):
        if key in stats:
            out[key] = int(stats[key])
    return out


class MetricsLogger:
    """Collects per-step metric dicts and forwards them to sinks.

    A sink is ``callable(record: dict) -> None``. ``jsonl`` writes one JSON
    object per record to the given stream (stdout default).

    Async-pipeline contract (core/pipeline.py): ``log_step(...,
    defer=True)`` accepts STILL-ON-DEVICE metric values without touching
    them — converting a device scalar to float blocks until the step's
    XLA program finishes, which would re-serialize the pipelined train
    loop. Deferred records queue up and materialize in one batched fetch
    at :meth:`flush`, which ``Trainer.fit`` calls only at its designated
    sync points. ``examples_per_sec`` on deferred records is the
    steady-state rate over the flush window (examples since last flush /
    wall seconds since last flush) — per-step dispatch intervals would
    measure host loop time, not step time.
    """

    def __init__(self, sinks: Optional[List[Callable]] = None,
                 jsonl_stream=None, every: int = 1) -> None:
        self.sinks = list(sinks or [])
        if jsonl_stream is not None or not self.sinks:
            stream = jsonl_stream or sys.stdout
            self.sinks.append(
                lambda rec: print(json.dumps(rec, default=float), file=stream))
        self.every = max(1, every)
        self.history: List[Dict[str, Any]] = []
        self._t_last: Optional[float] = None
        self._pending: List[tuple] = []  # (step, device_metrics, examples)

    def _materialize(self, step: int, metrics: Dict[str, Any],
                     rate: Optional[float]) -> Dict[str, Any]:
        """Shared record building for the inline and deferred paths: float
        conversion, history append, and ``every``-gated sink dispatch."""
        record: Dict[str, Any] = {"step": int(step)}
        for k, v in metrics.items():
            record[k] = float(v) if hasattr(v, "item") or isinstance(
                v, (int, float)) else v
        if rate is not None:
            record["examples_per_sec"] = rate
        self.history.append(record)
        if step % self.every == 0:
            for sink in self.sinks:
                sink(record)
        return record

    def log_step(self, step: int, metrics: Dict[str, Any],
                 examples: Optional[int] = None,
                 defer: bool = False) -> Optional[Dict[str, Any]]:
        if defer:
            self._pending.append((int(step), metrics, examples))
            return None
        now = time.perf_counter()
        rate = None
        if examples is not None and self._t_last is not None:
            dt = now - self._t_last
            if dt > 0:
                rate = examples / dt
        self._t_last = now
        return self._materialize(step, metrics, rate)

    def flush(self) -> List[Dict[str, Any]]:
        """Materialize deferred records (ONE batched device fetch), append
        them to history in step order and forward due ones to sinks.
        Returns the flushed records. This is a device barrier for every
        step logged since the previous flush — call it at sync points."""
        if not self._pending:
            return []
        pending, self._pending = self._pending, []
        now = time.perf_counter()
        fetched = jax.device_get([m for _, m, _ in pending])
        window_examples = sum(e for _, _, e in pending if e is not None)
        rate = None
        if self._t_last is not None and window_examples:
            dt = now - self._t_last
            if dt > 0:
                rate = window_examples / dt
                # telemetry (docs/OBSERVABILITY.md): the flush-window
                # steady-state ingest rate (the steps/sec HISTOGRAM is
                # fed by Trainer.fit's sync points, which exist even
                # without a MetricsLogger)
                telemetry.gauge_set(telemetry.M_EXAMPLES_PER_SEC, rate)
        self._t_last = now
        return [self._materialize(step, metrics,
                                  rate if examples is not None else None)
                for (step, _, examples), metrics in zip(pending, fetched)]
