"""Training subsystem — pjit train steps, optax, Orbax resume, TPURunner.

Parity map (SURVEY.md §3.3, §3.5, §5.3–§5.5): the reference trained
driver-locally with keras ``model.fit`` after collecting features, and its
distributed story was HorovodRunner (Spark barrier mode + MPI + NCCL ring
all-reduce). Here:

- the train step is ONE jitted XLA program over a device mesh — batch
  sharded on ``data``, params replicated; XLA emits the gradient
  all-reduce over ICI/DCN (no NCCL, no hand-written collectives);
- checkpoint/resume is Orbax on ``{params, opt_state, step, rng,
  model_state}`` — the mid-training resume the reference lacked;
- ``TPURunner(np).run(train_fn)`` is the HorovodRunner-parity entry:
  gang semantics with restart-from-checkpoint on failure, and a fault
  injection hook to test it.
"""

from sparkdl_tpu.train.checkpoint import CheckpointManager
from sparkdl_tpu.train.metrics import MetricsLogger
from sparkdl_tpu.train.optimizers import make_loss, make_optimizer
from sparkdl_tpu.train.runner import TPURunner
from sparkdl_tpu.train.trainer import Trainer, TrainState

__all__ = [
    "CheckpointManager",
    "MetricsLogger",
    "TPURunner",
    "Trainer",
    "TrainState",
    "make_loss",
    "make_optimizer",
]
