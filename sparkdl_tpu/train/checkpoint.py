"""Orbax checkpoint/resume of full training state.

Parity: SURVEY.md §5.4 — the reference only *read* model-format
checkpoints (``TFInputGraph.fromCheckpoint``) and had **no mid-training
resume**; gang failure meant restarting the job. Here every training
state component ``{params, opt_state, model_state, rng, step}`` is saved
(optionally async) and restored exactly, which is what makes TPURunner's
restart-from-checkpoint gang semantics work (§5.3).

Resilience (docs/RESILIENCE.md): a synchronous ``save`` is atomic — Orbax
commits a step by writing to a temporary directory and renaming, and
``save(synchronous=True)`` verifies the step actually landed before
returning, so a crash mid-write can never leave a half-step that
``latest_step()`` would report. ``restore`` with no explicit step walks
retained steps newest-first and falls back past corrupt/partial ones
(bit rot, torn disks, the injected ``checkpoint_truncate`` fault) with a
warning naming each skipped step.

Crash consistency (docs/RESILIENCE.md "Durable recovery"):

- **Per-file checksums**: each finalized step gets a sha256 manifest
  (``sparkdl.sums.json`` inside the step directory, so Orbax's retention
  deletes it with the step); ``restore`` verifies the manifest before
  handing the bytes to Orbax, extending corruption detection from
  truncation (which Orbax's parsers catch) to silent bit rot (which they
  may not). Steps without a manifest (legacy, or written by another
  tool) skip verification.
- **Fencing token**: constructing a manager claims the next monotonic
  gang *incarnation* for the directory (``<directory>.fence.json``).
  Every ``save`` re-checks the token; a writer whose incarnation has
  been superseded — a zombie from a restarted gang attempt, still
  flushing async saves — is refused with
  :class:`~sparkdl_tpu.core.resilience.StaleCheckpointWriter` (FATAL:
  retrying would be refused again) instead of clobbering its
  successor's checkpoints.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, Dict, List, Optional

import jax

from sparkdl_tpu.core import health, resilience

logger = logging.getLogger(__name__)

# Checksum manifest filename, stored INSIDE the step directory (written
# only after Orbax finalizes the step's rename-commit).
_SUMS_NAME = "sparkdl.sums.json"


class CheckpointManager:
    """Step-indexed Orbax checkpoints under one directory.

    ``keep`` bounds retained steps; ``save`` is async (overlaps the next
    train steps) unless ``synchronous=True`` is passed.
    """

    def __init__(self, directory: str, keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep,
                                                 create=True),
        )
        # Steps THIS manager wrote in-session: re-saving one (e.g. fit's
        # final synchronous save right after the per-step save of the same
        # step) is a no-op, not an overwrite.
        self._saved_steps: set = set()
        # Steps saved but not yet checksummed: manifests can only be
        # computed once the (possibly async) write finalizes, so they
        # flush at the wait_until_finished barriers.
        self._pending_sums: set = set()
        self._fence_path = self.directory + ".fence.json"
        self._incarnation = self._claim_fence()

    # -- fencing -------------------------------------------------------------

    def _claim_fence(self) -> int:
        """Claim the next gang incarnation of this directory.

        Best-effort monotonic token (read-increment-replace): concurrent
        claims within one host are serialized by the atomic replace, and
        the zombie-writer scenario this fences — an old gang attempt
        outliving the restart that superseded it — is sequential by
        construction (the new attempt starts after the old one's crash).
        """
        current = 0
        try:
            with open(self._fence_path, encoding="utf-8") as f:
                current = int(json.load(f)["incarnation"])
        except (OSError, ValueError, KeyError, TypeError):
            current = 0
        incarnation = current + 1
        tmp = f"{self._fence_path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"incarnation": incarnation}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._fence_path)
        return incarnation

    def _check_fence(self, step: int) -> None:
        try:
            with open(self._fence_path, encoding="utf-8") as f:
                latest = int(json.load(f)["incarnation"])
        except (OSError, ValueError, KeyError, TypeError):
            return  # unreadable token never blocks a save
        if latest > self._incarnation:
            health.record(health.CHECKPOINT_FENCED, step=step,
                          incarnation=self._incarnation, latest=latest)
            raise resilience.StaleCheckpointWriter(
                f"checkpoint save of step {step} refused: this writer "
                f"holds incarnation {self._incarnation} of "
                f"{self.directory} but incarnation {latest} has claimed "
                "it — a superseded gang attempt must not clobber its "
                "successor's checkpoints")

    def save(self, step: int, state: Any, synchronous: bool = False) -> None:
        import orbax.checkpoint as ocp

        self._check_fence(step)
        if step in self._saved_steps:
            pass  # already written by this manager; nothing new to persist
        elif step in self._mgr.all_steps():
            # Committed by a PREVIOUS gang attempt: the restarted run
            # recomputed this step (bit-identical replay) — or restore
            # fell back past a CORRUPT copy of it and the replay
            # reproduced it. Orbax refuses to re-save an existing step
            # (should_save() false → silent skip, or
            # StepAlreadyExistsError under force), which would drop the
            # recomputed step on the floor; delete-then-save instead.
            logger.warning(
                "checkpoint step %d already exists under %s (gang restart "
                "recomputed it); overwriting", step, self.directory)
            self._overwrite(step, state)
            self._saved_steps.add(step)
        else:
            try:
                self._mgr.save(step, args=ocp.args.StandardSave(state))
            except Exception as e:  # StepAlreadyExistsError is not a
                # ValueError in every orbax version; match the message
                if "already exists" not in str(e):
                    raise
                # Race backstop: an abandoned async writer from a dead
                # attempt committed this step between our check and now.
                logger.warning(
                    "checkpoint step %d landed concurrently under %s; "
                    "overwriting", step, self.directory)
                self._overwrite(step, state)
            self._saved_steps.add(step)
        self._pending_sums.add(step)
        if synchronous:
            self._mgr.wait_until_finished()
            # Atomicity check: Orbax finalizes a step by renaming its tmp
            # dir; a step missing from all_steps() after the barrier means
            # the commit never happened — fail HERE, not at some future
            # restore of a checkpoint that silently doesn't exist.
            if step not in self._mgr.all_steps():
                raise IOError(
                    f"checkpoint step {step} under {self.directory} was not "
                    "committed (crash/IO failure mid-write?)")
            self._flush_sums()
        if resilience.should_fire("checkpoint_truncate", step=step):
            # Fault injection: corrupt the just-written step in place
            # (truncate every file to half) to model bit rot / torn writes
            # on a COMMITTED checkpoint — exercises restore's fallback.
            self._mgr.wait_until_finished()
            self._truncate_step(step)

    def _overwrite(self, step: int, state: Any) -> None:
        """Replace an existing step: orbax has no in-place overwrite, so
        delete the committed copy and re-save (the new write is itself
        atomic via the tmp-dir + rename commit)."""
        import orbax.checkpoint as ocp

        self._mgr.wait_until_finished()
        try:
            self._mgr.delete(step)
        except Exception as e:  # noqa: BLE001 - a corrupt step may fail
            # structured deletion; fall back to removing the directory
            logger.warning("orbax delete of step %d failed (%s); removing "
                           "its directory", step, e)
            import shutil

            shutil.rmtree(os.path.join(self.directory, str(step)),
                          ignore_errors=True)
            self._mgr.reload()
        self._mgr.save(step, args=ocp.args.StandardSave(state))

    # -- checksums -----------------------------------------------------------

    def _step_file_sums(self, step: int) -> Dict[str, str]:
        """sha256 of every file in the step directory (manifest itself
        excluded), keyed by step-relative path."""
        step_dir = os.path.join(self.directory, str(step))
        sums: Dict[str, str] = {}
        for root, _dirs, files in os.walk(step_dir):
            for name in sorted(files):
                path = os.path.join(root, name)
                rel = os.path.relpath(path, step_dir)
                if rel == _SUMS_NAME:
                    continue
                h = hashlib.sha256()
                with open(path, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
                sums[rel] = h.hexdigest()
        return sums

    def _flush_sums(self) -> None:
        """Write the checksum manifest for every finalized pending step.

        Called at the wait_until_finished barriers — the first moment
        the step's files are final. The manifest write is itself atomic
        (tmp + ``os.replace``): a crash mid-manifest leaves the step
        manifest-less (verification skipped), never half-trusted.
        """
        live = set(self._mgr.all_steps())
        for step in sorted(self._pending_sums):
            self._pending_sums.discard(step)
            if step not in live:  # retention already dropped it
                continue
            payload = json.dumps(
                {"step": step, "files": self._step_file_sums(step)},
                sort_keys=True).encode()
            path = os.path.join(self.directory, str(step), _SUMS_NAME)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

    def _verify_sums(self, step: int) -> None:
        """Refuse a restore whose bytes don't match the step's manifest.

        A missing or unreadable manifest skips verification (legacy
        steps; truncation also shreds the in-step manifest, and Orbax's
        own parse failures catch that) — the manifest extends detection
        to SILENT corruption, it is not a gate on old checkpoints.
        """
        path = os.path.join(self.directory, str(step), _SUMS_NAME)
        try:
            with open(path, encoding="utf-8") as f:
                recorded = json.load(f)["files"]
        except (OSError, ValueError, KeyError, TypeError):
            return
        if not isinstance(recorded, dict):
            return
        actual = self._step_file_sums(step)
        mismatched = sorted(k for k in set(recorded) | set(actual)
                            if recorded.get(k) != actual.get(k))
        if mismatched:
            health.record(health.CHECKPOINT_CHECKSUM_REJECTED, step=step,
                          files=len(mismatched))
            raise IOError(
                f"checkpoint step {step} under {self.directory} failed "
                f"checksum verification ({len(mismatched)} file(s), e.g. "
                f"{mismatched[0]!r}) — refusing to restore corrupted "
                "state")

    def _truncate_step(self, step: int) -> None:
        step_dir = os.path.join(self.directory, str(step))
        for root, _dirs, files in os.walk(step_dir):
            for name in files:
                path = os.path.join(root, name)
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.truncate(size // 2)
        logger.warning("FaultInjector: truncated checkpoint step %d files "
                       "under %s", step, step_dir)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(self._mgr.all_steps())

    def restore(self, state_template: Any, step: Optional[int] = None) -> Any:
        """Restore into the template's pytree structure.

        With an explicit ``step``, exactly that step is restored (a
        failure raises). With ``step=None``, retained steps are tried
        newest-first: a corrupt/partial step logs a warning naming it and
        falls back to the previous retained step; only when every
        retained step fails does the last error propagate.
        """
        if self._pending_sums:
            # async saves from THIS manager not yet manifested: finalize
            # them now so verification sees current bytes, not stale sums
            self._mgr.wait_until_finished()
            self._flush_sums()
        if step is not None:
            return self._restore_step(step, state_template)
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(
                f"No checkpoint found under {self.directory}")
        first_err: Optional[BaseException] = None
        for i, candidate in enumerate(steps):
            try:
                return self._restore_step(candidate, state_template)
            except Exception as e:  # noqa: BLE001 - corrupt data raises
                # anything (JSONDecodeError, OSError, Orbax internals)
                first_err = first_err or e
                if i + 1 >= len(steps):
                    # Every retained step failed — a systemic problem
                    # (e.g. a train-state format change hits ALL steps
                    # equally), so report the NEWEST step's error, not
                    # whichever happened to be oldest.
                    raise first_err
                logger.warning(
                    "checkpoint step %d under %s failed to restore "
                    "(%s: %s); falling back to step %d", candidate,
                    self.directory, type(e).__name__, e, steps[i + 1])

    def _restore_step(self, step: int, state_template: Any) -> Any:
        import orbax.checkpoint as ocp

        self._verify_sums(step)
        template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
            if hasattr(x, "shape") and hasattr(x, "dtype") else x,
            state_template)
        try:
            return self._mgr.restore(step,
                                     args=ocp.args.StandardRestore(template))
        except (ValueError, KeyError) as e:
            import json

            if isinstance(e, json.JSONDecodeError):
                # Truncated/corrupt metadata, not a structure mismatch —
                # let restore()'s newest-first fallback handle it under
                # its own (accurate) warning.
                raise
            # Most common cause: the checkpoint predates a change in the
            # train-state pytree — e.g. named optimizers now wrap in
            # optax.inject_hyperparams (r4), which changed the opt_state
            # structure — so a bare Orbax structure-mismatch would be
            # undebuggable (ADVICE r4).
            raise ValueError(
                f"Checkpoint under {self.directory} (step {step}) does not "
                "match the current train-state structure. It was likely "
                "written by an earlier version with a different "
                "optimizer-state format; delete the checkpoint_dir to "
                f"restart training from scratch. Original error: {e}"
            ) from e

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()
        self._flush_sums()

    def close(self) -> None:
        self._mgr.close()
