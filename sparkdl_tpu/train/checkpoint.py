"""Orbax checkpoint/resume of full training state.

Parity: SURVEY.md §5.4 — the reference only *read* model-format
checkpoints (``TFInputGraph.fromCheckpoint``) and had **no mid-training
resume**; gang failure meant restarting the job. Here every training
state component ``{params, opt_state, model_state, rng, step}`` is saved
(optionally async) and restored exactly, which is what makes TPURunner's
restart-from-checkpoint gang semantics work (§5.3).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


class CheckpointManager:
    """Step-indexed Orbax checkpoints under one directory.

    ``keep`` bounds retained steps; ``save`` is async (overlaps the next
    train steps) unless ``synchronous=True`` is passed.
    """

    def __init__(self, directory: str, keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep,
                                                 create=True),
        )

    def save(self, step: int, state: Any, synchronous: bool = False) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if synchronous:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state_template: Any, step: Optional[int] = None) -> Any:
        """Restore into the abstract/concrete template's pytree structure."""
        import orbax.checkpoint as ocp

        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"No checkpoint found under {self.directory}")
        template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
            if hasattr(x, "shape") and hasattr(x, "dtype") else x,
            state_template)
        try:
            return self._mgr.restore(step,
                                     args=ocp.args.StandardRestore(template))
        except (ValueError, KeyError) as e:
            # Most common cause: the checkpoint predates a change in the
            # train-state pytree — e.g. named optimizers now wrap in
            # optax.inject_hyperparams (r4), which changed the opt_state
            # structure — so a bare Orbax structure-mismatch would be
            # undebuggable (ADVICE r4).
            raise ValueError(
                f"Checkpoint under {self.directory} (step {step}) does not "
                "match the current train-state structure. It was likely "
                "written by an earlier version with a different "
                "optimizer-state format; delete the checkpoint_dir to "
                f"restart training from scratch. Original error: {e}"
            ) from e

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
