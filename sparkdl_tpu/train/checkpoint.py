"""Orbax checkpoint/resume of full training state.

Parity: SURVEY.md §5.4 — the reference only *read* model-format
checkpoints (``TFInputGraph.fromCheckpoint``) and had **no mid-training
resume**; gang failure meant restarting the job. Here every training
state component ``{params, opt_state, model_state, rng, step}`` is saved
(optionally async) and restored exactly, which is what makes TPURunner's
restart-from-checkpoint gang semantics work (§5.3).

Resilience (docs/RESILIENCE.md): a synchronous ``save`` is atomic — Orbax
commits a step by writing to a temporary directory and renaming, and
``save(synchronous=True)`` verifies the step actually landed before
returning, so a crash mid-write can never leave a half-step that
``latest_step()`` would report. ``restore`` with no explicit step walks
retained steps newest-first and falls back past corrupt/partial ones
(bit rot, torn disks, the injected ``checkpoint_truncate`` fault) with a
warning naming each skipped step.
"""

from __future__ import annotations

import logging
import os
from typing import Any, List, Optional

import jax

from sparkdl_tpu.core import resilience

logger = logging.getLogger(__name__)


class CheckpointManager:
    """Step-indexed Orbax checkpoints under one directory.

    ``keep`` bounds retained steps; ``save`` is async (overlaps the next
    train steps) unless ``synchronous=True`` is passed.
    """

    def __init__(self, directory: str, keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep,
                                                 create=True),
        )
        # Steps THIS manager wrote in-session: re-saving one (e.g. fit's
        # final synchronous save right after the per-step save of the same
        # step) is a no-op, not an overwrite.
        self._saved_steps: set = set()

    def save(self, step: int, state: Any, synchronous: bool = False) -> None:
        import orbax.checkpoint as ocp

        if step in self._saved_steps:
            pass  # already written by this manager; nothing new to persist
        elif step in self._mgr.all_steps():
            # Committed by a PREVIOUS gang attempt: the restarted run
            # recomputed this step (bit-identical replay) — or restore
            # fell back past a CORRUPT copy of it and the replay
            # reproduced it. Orbax refuses to re-save an existing step
            # (should_save() false → silent skip, or
            # StepAlreadyExistsError under force), which would drop the
            # recomputed step on the floor; delete-then-save instead.
            logger.warning(
                "checkpoint step %d already exists under %s (gang restart "
                "recomputed it); overwriting", step, self.directory)
            self._overwrite(step, state)
            self._saved_steps.add(step)
        else:
            try:
                self._mgr.save(step, args=ocp.args.StandardSave(state))
            except Exception as e:  # StepAlreadyExistsError is not a
                # ValueError in every orbax version; match the message
                if "already exists" not in str(e):
                    raise
                # Race backstop: an abandoned async writer from a dead
                # attempt committed this step between our check and now.
                logger.warning(
                    "checkpoint step %d landed concurrently under %s; "
                    "overwriting", step, self.directory)
                self._overwrite(step, state)
            self._saved_steps.add(step)
        if synchronous:
            self._mgr.wait_until_finished()
            # Atomicity check: Orbax finalizes a step by renaming its tmp
            # dir; a step missing from all_steps() after the barrier means
            # the commit never happened — fail HERE, not at some future
            # restore of a checkpoint that silently doesn't exist.
            if step not in self._mgr.all_steps():
                raise IOError(
                    f"checkpoint step {step} under {self.directory} was not "
                    "committed (crash/IO failure mid-write?)")
        if resilience.should_fire("checkpoint_truncate", step=step):
            # Fault injection: corrupt the just-written step in place
            # (truncate every file to half) to model bit rot / torn writes
            # on a COMMITTED checkpoint — exercises restore's fallback.
            self._mgr.wait_until_finished()
            self._truncate_step(step)

    def _overwrite(self, step: int, state: Any) -> None:
        """Replace an existing step: orbax has no in-place overwrite, so
        delete the committed copy and re-save (the new write is itself
        atomic via the tmp-dir + rename commit)."""
        import orbax.checkpoint as ocp

        self._mgr.wait_until_finished()
        try:
            self._mgr.delete(step)
        except Exception as e:  # noqa: BLE001 - a corrupt step may fail
            # structured deletion; fall back to removing the directory
            logger.warning("orbax delete of step %d failed (%s); removing "
                           "its directory", step, e)
            import shutil

            shutil.rmtree(os.path.join(self.directory, str(step)),
                          ignore_errors=True)
            self._mgr.reload()
        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def _truncate_step(self, step: int) -> None:
        step_dir = os.path.join(self.directory, str(step))
        for root, _dirs, files in os.walk(step_dir):
            for name in files:
                path = os.path.join(root, name)
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.truncate(size // 2)
        logger.warning("FaultInjector: truncated checkpoint step %d files "
                       "under %s", step, step_dir)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(self._mgr.all_steps())

    def restore(self, state_template: Any, step: Optional[int] = None) -> Any:
        """Restore into the template's pytree structure.

        With an explicit ``step``, exactly that step is restored (a
        failure raises). With ``step=None``, retained steps are tried
        newest-first: a corrupt/partial step logs a warning naming it and
        falls back to the previous retained step; only when every
        retained step fails does the last error propagate.
        """
        if step is not None:
            return self._restore_step(step, state_template)
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(
                f"No checkpoint found under {self.directory}")
        first_err: Optional[BaseException] = None
        for i, candidate in enumerate(steps):
            try:
                return self._restore_step(candidate, state_template)
            except Exception as e:  # noqa: BLE001 - corrupt data raises
                # anything (JSONDecodeError, OSError, Orbax internals)
                first_err = first_err or e
                if i + 1 >= len(steps):
                    # Every retained step failed — a systemic problem
                    # (e.g. a train-state format change hits ALL steps
                    # equally), so report the NEWEST step's error, not
                    # whichever happened to be oldest.
                    raise first_err
                logger.warning(
                    "checkpoint step %d under %s failed to restore "
                    "(%s: %s); falling back to step %d", candidate,
                    self.directory, type(e).__name__, e, steps[i + 1])

    def _restore_step(self, step: int, state_template: Any) -> Any:
        import orbax.checkpoint as ocp

        template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
            if hasattr(x, "shape") and hasattr(x, "dtype") else x,
            state_template)
        try:
            return self._mgr.restore(step,
                                     args=ocp.args.StandardRestore(template))
        except (ValueError, KeyError) as e:
            import json

            if isinstance(e, json.JSONDecodeError):
                # Truncated/corrupt metadata, not a structure mismatch —
                # let restore()'s newest-first fallback handle it under
                # its own (accurate) warning.
                raise
            # Most common cause: the checkpoint predates a change in the
            # train-state pytree — e.g. named optimizers now wrap in
            # optax.inject_hyperparams (r4), which changed the opt_state
            # structure — so a bare Orbax structure-mismatch would be
            # undebuggable (ADVICE r4).
            raise ValueError(
                f"Checkpoint under {self.directory} (step {step}) does not "
                "match the current train-state structure. It was likely "
                "written by an earlier version with a different "
                "optimizer-state format; delete the checkpoint_dir to "
                f"restart training from scratch. Original error: {e}"
            ) from e

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
