"""TPURunner — HorovodRunner-parity distributed training entry point.

Parity (SURVEY.md §3.5): ``HorovodRunner(np=N).run(train_fn)`` launched a
Spark barrier-mode gang, MPI ranks, and a NCCL ring. On TPU the whole
apparatus collapses: ``jax.distributed.initialize`` joins the per-host
processes (multi-host), the device mesh spans all chips, and the train
step's shardings make XLA emit the all-reduce over ICI/DCN. What survives
is the *runner* contract:

- ``TPURunner(np=N).run(train_fn, **kwargs)`` builds an N-chip ``data``
  mesh and calls ``train_fn(mesh=mesh, **kwargs)``;
- gang failure semantics (§5.3): if ``train_fn`` raises, the runner
  restarts it up to ``max_restarts`` times — train fns that checkpoint
  via Trainer.fit resume from the last saved step, reproducing barrier
  mode's "fail the gang, rerun" with far less lost work.
"""

from __future__ import annotations

import inspect
import logging
import os
import time
from typing import Any, Callable, Optional

import jax

from sparkdl_tpu.core import health, resilience, telemetry
from sparkdl_tpu.core.mesh import MeshConfig, make_mesh

logger = logging.getLogger(__name__)


def maybe_initialize_distributed() -> bool:
    """Join the multi-host process group when coordinator env vars are set.

    Single-host (this environment) is a no-op. Multi-host: set
    ``SPARKDL_COORDINATOR``, ``SPARKDL_NUM_PROCESSES``,
    ``SPARKDL_PROCESS_ID`` (the jax.distributed triple) on every host.
    """
    coordinator = os.environ.get("SPARKDL_COORDINATOR")
    if not coordinator:
        return False
    # read the configured platform WITHOUT touching jax.default_backend()
    # — that would initialize the backend, which initialize() forbids
    platforms = (jax.config.jax_platforms
                 or os.environ.get("JAX_PLATFORMS", ""))
    if platforms.split(",")[0].strip() == "cpu":
        # CPU multi-process collectives need the gloo transport — the
        # default XFER implementation raises INVALID_ARGUMENT
        # ("Multiprocess computations aren't implemented on the CPU
        # backend") the moment a psum crosses processes. Real TPU/GPU
        # gangs never enter this branch.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        # sparkdl: allow(broad-retry): not a retry — config flag probe; jax versions without the flag fall through to the default transport
        except Exception:  # noqa: BLE001
            logger.warning("jax_cpu_collectives_implementation=gloo not "
                           "available in this jax; CPU multi-process "
                           "collectives may be unsupported")
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(os.environ["SPARKDL_NUM_PROCESSES"]),
        process_id=int(os.environ["SPARKDL_PROCESS_ID"]))
    return True


class TPURunner:
    """Run a training function over an ``np``-device data-parallel mesh.

    Restart semantics (core.resilience): a failed ``main`` is classified —
    only RETRYABLE errors (preemption, transient runtime errors — the
    gang-failure class) restart, up to ``max_restarts`` times with
    exponential backoff and deterministic jitter instead of a fixed
    delay. FATAL errors (shape/dtype/``ValueError``: deterministic, a
    restart replays them) and OOM (a same-shape replay reproduces it;
    the batch-shrink response lives in core.batching, not here) raise
    immediately with zero restart attempts. Train fns that
    checkpoint via ``Trainer.fit(checkpoint=...)`` resume from
    ``CheckpointManager.latest_step()``, not step 0.

    ``retry_policy`` overrides the backoff schedule; when omitted, one is
    built from ``restart_delay_s`` (kept as the base delay for
    compatibility with the original fixed-delay API).
    """

    def __init__(self, np: int = -1, max_restarts: int = 0,
                 restart_delay_s: float = 0.0,
                 mesh_config: Optional[MeshConfig] = None,
                 retry_policy: Optional[resilience.RetryPolicy] = None
                 ) -> None:
        self.np = np
        self.max_restarts = max_restarts
        self.restart_delay_s = restart_delay_s
        self.mesh_config = mesh_config
        self.retry_policy = retry_policy or resilience.RetryPolicy(
            max_retries=max_restarts, base_delay_s=restart_delay_s,
            max_delay_s=max(restart_delay_s * 8, 60.0))

    def _build_mesh(self):
        maybe_initialize_distributed()
        if self.mesh_config is not None:
            return make_mesh(self.mesh_config)
        n = self.np if self.np != -1 else len(jax.devices())
        if n > len(jax.devices()):
            raise ValueError(
                f"np={n} but only {len(jax.devices())} devices visible")
        return make_mesh(MeshConfig(data=n), devices=jax.devices()[:n])

    def run(self, main: Callable, **kwargs) -> Any:
        """Call ``main`` with the mesh; restart on failure up to the cap.

        ``main`` receives ``mesh=`` iff its signature accepts it (keyword
        or **kwargs), matching HorovodRunner's convention of passing
        through user kwargs untouched.
        """
        mesh = self._build_mesh()
        sig = inspect.signature(main)
        accepts_mesh = ("mesh" in sig.parameters or any(
            p.kind is inspect.Parameter.VAR_KEYWORD
            for p in sig.parameters.values()))
        call_kwargs = dict(kwargs)
        if accepts_mesh:
            call_kwargs["mesh"] = mesh

        attempts = self.max_restarts + 1
        last_err: Optional[BaseException] = None
        for attempt in range(attempts):
            try:
                # telemetry: one span per gang attempt — the fit span
                # (and everything under it) nests here, so a restarted
                # run's trace shows attempt 1 vs attempt 2 side by side
                with telemetry.span(telemetry.SPAN_RUNNER_ATTEMPT,
                                    attempt=attempt):
                    return main(**call_kwargs)
            except Exception as e:  # noqa: BLE001 - gang boundary
                kind = resilience.classify(e)
                if kind != resilience.RETRYABLE:
                    # FATAL: deterministic — a restart replays it from the
                    # checkpoint and fails again. OOM: a same-shape replay
                    # reproduces it too, and the runner has no batch-shrink
                    # response (that lives in core.batching) — surface
                    # both unretried.
                    health.record(health.GANG_FATAL, kind=kind,
                                  error=type(e).__name__)
                    logger.error(
                        "TPURunner: attempt %d failed with a %s error "
                        "(%s: %s); not restarting", attempt + 1, kind,
                        type(e).__name__, e)
                    raise
                last_err = e
                if attempt + 1 < attempts:
                    delay = self.retry_policy.delay(attempt + 1)
                    health.record(health.GANG_RESTART, attempt=attempt + 1,
                                  error=type(e).__name__)
                    logger.warning(
                        "TPURunner: attempt %d/%d failed (%s: %s); "
                        "restarting in %.2fs", attempt + 1, attempts,
                        type(e).__name__, e, delay)
                    if delay > 0:
                        time.sleep(delay)
        health.record(health.GANG_FAILED, attempts=attempts,
                      error=type(last_err).__name__
                      if last_err is not None else None)
        raise RuntimeError(
            f"TPURunner: train fn failed after {attempts} attempts"
        ) from last_err
