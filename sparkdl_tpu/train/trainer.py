"""Trainer — the pjit training engine.

The reference's training path (SURVEY.md §3.3) collected data to the
driver and called keras ``model.fit`` locally; distributed training meant
Horovod's NCCL ring (§3.5). Here one jitted train step does forward,
backward, all-reduce and update in a single XLA program:

- with a mesh: batch arrays are sharded over the ``data`` axis, state is
  replicated — XLA emits the gradient all-reduce over ICI/DCN from those
  shardings (the HorovodRunner-parity layout, no NCCL);
- state buffers are donated, so params/opt_state update in place in HBM;
- models with mutable normalization state (Flax ``batch_stats``) update it
  in the same program; stateless models (ingested Keras DAGs) skip it.
"""

from __future__ import annotations

import math
import sys
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from sparkdl_tpu.core import health, pipeline, profiling, resilience, telemetry
from sparkdl_tpu.core.mesh import batch_sharding, replicated
from sparkdl_tpu.train.checkpoint import CheckpointManager
from sparkdl_tpu.train.metrics import MetricsLogger
from sparkdl_tpu.train.optimizers import (
    accuracy_metric,
    make_loss,
    make_optimizer,
)


class TrainState(struct.PyTreeNode):
    """Full training state — everything checkpoint/resume needs (§5.4)."""

    step: jax.Array
    params: Any
    opt_state: Any
    model_state: Any  # e.g. {'batch_stats': ...}; {} when stateless
    rng: jax.Array


@dataclass
class Trainer:
    """Builds and runs the jitted train step for one model.

    ``apply_fn(variables, x, train, rngs) -> out | (out, new_model_state)``
    where ``variables = {'params': ..., **model_state}``. Use the
    constructors ``from_flax`` / ``from_model_function`` instead of filling
    this in by hand.
    """

    apply_fn: Callable
    loss: Callable
    optimizer: optax.GradientTransformation
    mesh: Any = None
    has_model_state: bool = False
    compute_accuracy: bool = True
    accuracy_from_logits: bool = False
    # Mixed precision (keras mixed_precision parity, TPU-native form):
    # forward/backward run in this dtype (bf16 keeps f32's exponent range,
    # so no loss scaling is needed on TPU) while master params, optimizer
    # state and the update stay float32. None = full precision.
    #
    # NOTE on gradient checkpointing: a Trainer-level whole-model
    # jax.checkpoint was tried and REMOVED — one monolithic checkpoint
    # does not reduce peak HBM (the backward's recompute materializes the
    # same residual set before transposing; it only adds ~1 forward of
    # FLOPs). Memory-bound models should use flax ``nn.remat`` on block
    # boundaries inside the module definition, which the Trainer runs
    # unchanged.
    compute_dtype: Any = None
    # Optional shared compiled-step cache (from_model_function wires it to
    # the ModelFunction): repeated fits of the same model — HPO maps,
    # repeated estimator.fit — reuse ONE jitted step instead of paying the
    # ~15 s tunnel compile each time. Safe because the step closes over no
    # fit-specific values: params/opt_state arrive via TrainState and the
    # learning rate is an opt_state hyperparam (make_optimizer injects it).
    step_cache: Any = None
    step_cache_key: Any = None

    # -- constructors --------------------------------------------------------

    @classmethod
    def from_flax(cls, module, variables: Dict[str, Any],
                  loss="categorical_crossentropy", optimizer="adam",
                  learning_rate: Optional[float] = None, mesh=None,
                  from_logits: bool = False, **kwargs) -> Tuple["Trainer", TrainState]:
        """Flax module + variables → (trainer, initial state).

        Mutable collections (``batch_stats``) train properly: they update
        inside the same XLA program as the gradient step.
        """
        variables = dict(variables)
        params = variables.pop("params")
        model_state = variables  # batch_stats etc (may be empty)
        mutable_keys = sorted(model_state)

        def apply_fn(vs, x, train, rngs):
            if train and mutable_keys:
                out, updates = module.apply(vs, x, train=True,
                                            mutable=mutable_keys, rngs=rngs)
                return out, updates
            return module.apply(vs, x, train=train, rngs=rngs)

        trainer = cls(apply_fn=apply_fn,
                      loss=make_loss(loss, from_logits=from_logits),
                      optimizer=make_optimizer(optimizer, learning_rate),
                      mesh=mesh, has_model_state=bool(mutable_keys),
                      accuracy_from_logits=from_logits, **kwargs)
        state = trainer.init_state(params, model_state)
        return trainer, state

    @classmethod
    def from_model_function(cls, mf, loss="categorical_crossentropy",
                            optimizer="adam",
                            learning_rate: Optional[float] = None, mesh=None,
                            from_logits: bool = False,
                            **kwargs) -> Tuple["Trainer", TrainState]:
        """ModelFunction (e.g. an ingested Keras DAG) → (trainer, state).

        The model runs in inference form during training (normalization
        uses stored moving stats — fine-tune semantics). Weights the
        ingestion marked non-trainable (``mf.trainable_mask``, e.g. Keras
        BatchNorm moving stats) are frozen so their gradients through the
        inference-mode forward are never applied.
        """
        if isinstance(mf.input_spec, dict):
            raise ValueError(
                f"Model {mf.name!r} has multiple named inputs; the Trainer "
                "trains single-input models — serve multi-IO models via "
                "TPUTransformer inputMapping/outputMapping instead")

        def apply_fn(vs, x, train, rngs):
            out = mf.apply_fn(vs["params"], x)
            if isinstance(out, dict):
                raise ValueError(
                    f"Model {mf.name!r} returns multiple named outputs; "
                    "the Trainer's loss needs a single output head")
            return out

        tx = make_optimizer(optimizer, learning_rate)
        mask = getattr(mf, "trainable_mask", None)
        if mask is not None and not all(jax.tree.leaves(mask)):
            labels = jax.tree.map(lambda t: "train" if t else "freeze", mask)
            tx = optax.multi_transform(
                {"train": tx, "freeze": optax.set_to_zero()}, labels)
        cache = cache_key = None
        if isinstance(loss, str) and isinstance(optimizer, str):
            # lr is NOT part of the key: it's an injected opt_state
            # hyperparam, so one compiled step serves every lr. EVERY
            # other Trainer option (compute_accuracy, compute_dtype, ...)
            # changes the compiled program, so all kwargs key the cache —
            # any unhashable option value disables caching rather than
            # risking a stale step.
            try:
                cache_key = (loss, optimizer, from_logits, mesh,
                             tuple(sorted(
                                 (k, str(v)) for k, v in kwargs.items())))
                hash(cache_key)
            except TypeError:
                cache_key = None
            if cache_key is not None:
                cache = mf.__dict__.setdefault("_train_step_cache", {})
        trainer = cls(apply_fn=apply_fn, loss=make_loss(loss, from_logits=from_logits),
                      optimizer=tx, mesh=mesh, has_model_state=False,
                      accuracy_from_logits=from_logits,
                      step_cache=cache, step_cache_key=cache_key, **kwargs)
        state = trainer.init_state(mf.variables, {})
        return trainer, state

    # -- state ---------------------------------------------------------------

    def init_state(self, params, model_state=None, seed: int = 0) -> TrainState:
        # Own fresh copies: the train step donates state buffers (in-place
        # HBM update), which deletes them — caller-supplied arrays must
        # survive (e.g. two trainers initialized from the same variables).
        params = jax.tree.map(jnp.array, params)
        model_state = jax.tree.map(jnp.array, model_state or {})
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=self.optimizer.init(params),
            model_state=model_state,
            rng=jax.random.PRNGKey(seed))

    # -- the step ------------------------------------------------------------

    def make_train_step(self, donate: bool = True) -> Callable:
        """Compiled ``(state, x, y) -> (state, metrics)``.

        With a shared ``step_cache`` (from_model_function), the jitted
        step is built once per (loss, optimizer, mesh, dtype, donate) and
        reused by every subsequent fit of the same ModelFunction.

        One XLA program: forward, loss, backward, (implicit all-reduce),
        optimizer update, model-state update. With a mesh, x/y shard over
        ``data`` and state is replicated; XLA inserts the collectives.
        """
        if self.step_cache is not None:
            cached = self.step_cache.get((self.step_cache_key, donate))
            if cached is not None:
                return cached
        loss_fn = self.loss
        apply_fn = self.apply_fn
        optimizer = self.optimizer
        has_state = self.has_model_state
        want_acc = self.compute_accuracy
        acc_from_logits = self.accuracy_from_logits
        compute_dtype = (jnp.dtype(self.compute_dtype)
                         if self.compute_dtype is not None else None)

        def to_compute(tree):
            return jax.tree.map(
                lambda a: a.astype(compute_dtype)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)

        def to_master(tree, like):
            return jax.tree.map(
                lambda a, m: a.astype(m.dtype), tree, like)

        def step_fn(state: TrainState, x, y):
            rng, step_rng = jax.random.split(state.rng)
            rngs = {"dropout": step_rng}

            def compute_loss(params):
                # model_state (e.g. BatchNorm running stats) deliberately
                # stays f32 under mixed precision: the moving-average
                # update old*m + batch*(1-m) underflows bf16's 8-bit
                # mantissa for small increments and the stats would stall
                # (keras mixed_precision keeps BN state f32 for the same
                # reason)
                model_state = state.model_state
                if compute_dtype is not None:
                    params = to_compute(params)
                    xc = to_compute(x)
                else:
                    xc = x
                vs = {"params": params, **model_state}
                res = apply_fn(vs, xc, True, rngs)
                if has_state:
                    out, new_model_state = res
                else:
                    out, new_model_state = res, state.model_state
                # loss in f32 regardless: reductions over many bf16 terms
                # lose precision
                return loss_fn(out.astype(jnp.float32), y), (out, new_model_state)

            grad_fn = jax.value_and_grad(compute_loss, has_aux=True)
            (loss, (out, new_model_state)), grads = grad_fn(state.params)
            if compute_dtype is not None:
                # value_and_grad already returns f32 grads (the cast is in
                # the graph); this is a defensive no-op. Model-state leaves
                # a model computes in low precision get restored to master
                # dtype.
                grads = to_master(grads, state.params)
                new_model_state = to_master(new_model_state,
                                            state.model_state)
            updates, new_opt_state = optimizer.update(grads, state.opt_state,
                                                      state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_state = TrainState(step=state.step + 1, params=new_params,
                                   opt_state=new_opt_state,
                                   model_state=new_model_state, rng=rng)
            metrics = {"loss": loss}
            if want_acc and out.ndim >= 2:
                metrics["accuracy"] = accuracy_metric(
                    out, y, from_logits=acc_from_logits)
            return new_state, metrics

        kwargs: Dict[str, Any] = {"donate_argnums": (0,)} if donate else {}
        if self.mesh is None:
            jitted = jax.jit(step_fn, **kwargs)
        else:
            data_sh = batch_sharding(self.mesh)
            # state sharding None = keep as placed (replicated by
            # fit/device_put); batch sharded over data → XLA all-reduces
            # grads across the axis.
            jitted = jax.jit(step_fn, in_shardings=(None, data_sh, data_sh),
                             **kwargs)
        if self.step_cache is not None:
            self.step_cache[(self.step_cache_key, donate)] = jitted
        return jitted

    def make_eval_step(self) -> Callable:
        apply_fn = self.apply_fn

        def eval_fn(state: TrainState, x):
            vs = {"params": state.params, **state.model_state}
            return apply_fn(vs, x, False, None)

        if self.mesh is None:
            return jax.jit(eval_fn)
        data_sh = batch_sharding(self.mesh)
        return jax.jit(eval_fn, in_shardings=(None, data_sh),
                       out_shardings=data_sh)

    def make_eval_metrics_step(self) -> Callable:
        """Compiled ``(state, x, y) -> {loss, accuracy}`` (no grads).

        Deliberately jitted WITHOUT batch in_shardings even under a mesh:
        validation sets are small and arbitrarily sized, and a
        data-sharded eval step would reject any batch not divisible by
        the data axis. GSPMD propagates shardings from the (replicated)
        state; exact metrics beat parallel evaluation here.
        """
        if self.step_cache is not None:
            cached = self.step_cache.get((self.step_cache_key, "eval"))
            if cached is not None:
                return cached
        own = self.__dict__.get("_eval_step")
        if own is not None:
            return own
        apply_fn = self.apply_fn
        loss_fn = self.loss
        want_acc = self.compute_accuracy
        acc_from_logits = self.accuracy_from_logits

        def eval_fn(state: TrainState, x, y):
            vs = {"params": state.params, **state.model_state}
            out = apply_fn(vs, x, False, None)
            metrics = {"loss": loss_fn(out, y)}
            if want_acc:
                metrics["accuracy"] = accuracy_metric(
                    out, y, from_logits=acc_from_logits)
            return metrics

        jitted = jax.jit(eval_fn)
        if self.step_cache is not None:
            self.step_cache[(self.step_cache_key, "eval")] = jitted
        else:
            # no shared cache (custom loss/optimizer objects): memoize on
            # this Trainer so per-epoch evaluate() doesn't recompile
            self.__dict__["_eval_step"] = jitted
        return jitted

    def evaluate(self, state: TrainState,
                 batches: Iterable[Tuple[np.ndarray, np.ndarray]]
                 ) -> Dict[str, float]:
        """Mean loss/accuracy over a batch stream (keras ``evaluate``).

        Multi-host (VERDICT r4 #7): training state is replicated, so every
        host holds a full copy — pull it host-local and evaluate the
        (host-identical) validation batches as a purely LOCAL computation.
        Every process reports metrics EXACTLY equal to a single-process
        evaluation; no collectives, no divisibility constraints on the
        validation batch size.
        """
        if jax.process_count() > 1:
            try:
                state = jax.tree.map(
                    lambda a: np.asarray(jax.device_get(a)), state)
            except RuntimeError as e:
                raise NotImplementedError(
                    "multi-host evaluate requires fully-replicated train "
                    f"state (every host must hold a full copy): {e}") from e
        eval_step = self.make_eval_metrics_step()
        totals: Dict[str, float] = {}
        n = 0
        for x, y in batches:
            xd = jnp.asarray(np.asarray(x))
            if xd.dtype == jnp.uint8:  # same contract as stage_batch
                xd = xd.astype(jnp.float32)
            m = jax.device_get(eval_step(state, xd,
                                         jnp.asarray(np.asarray(y))))
            k = len(x)
            n += k
            for key, value in m.items():
                totals[key] = totals.get(key, 0.0) + float(value) * k
        if n == 0:
            return {}
        return {f"val_{k}": v / n for k, v in totals.items()}

    # -- the loop ------------------------------------------------------------

    def fit(self, state: TrainState,
            batches: Iterable[Tuple[np.ndarray, np.ndarray]],
            epochs: int = 1,
            metrics_logger: Optional[MetricsLogger] = None,
            checkpoint: Optional[CheckpointManager] = None,
            checkpoint_every: int = 0,
            resume: bool = True,
            on_step: Optional[Callable[[int], None]] = None,
            on_epoch: Optional[Callable[[int, TrainState], None]] = None,
            sync_every: int = 8,
            prefetch: int = 2) -> TrainState:
        """Run the pipelined train loop; resume from the latest checkpoint.

        ``batches``: a reiterable of ``(x, y)`` numpy pairs (all the same
        shape — pad or drop the remainder upstream; static shapes keep one
        compiled program). ``on_step(step)`` is the fault-injection hook
        (SURVEY.md §5.3): raising from it aborts the loop exactly as a
        worker loss would, and TPURunner restarts from the checkpoint.
        ``on_epoch(epoch_index, state)`` fires after each epoch (the
        estimator's validation-evaluation hook).

        Async input pipeline (ISSUE 3, docs/PERF.md): host pull + decode +
        staging for batch ``k+1`` runs on a background thread
        (``core.pipeline.DevicePrefetcher``, ``prefetch`` staged batches
        deep; 0 = inline serial staging) while the device trains batch
        ``k``, and the loop never blocks on the device per step — the
        step counter is tracked on the HOST (the device chain is
        deterministic, so they agree) and the device is only awaited at
        the designated sync points: every ``sync_every`` steps, at
        checkpoint writes, before each ``on_step`` call (so the hook's
        contract — "the step has completed" — survives), and at epoch
        boundaries. Per-step metrics defer on device and materialize at
        sync points (``MetricsLogger.flush``). Batch values, order, RNG
        chain and donation semantics are untouched, so a pipelined fit is
        bit-identical to the serial loop, and exact resume still replays
        to the precise next batch (skipped positions are never staged).
        ``sync_every`` also bounds in-flight device work (each unsynced
        step holds its staged batch alive): raise it to hide slow hosts
        deeper, lower it to cap device memory and tighten failure
        detection latency.
        """
        if checkpoint is not None and resume:
            latest = checkpoint.latest_step()
            if latest is not None:
                state = checkpoint.restore(state)
                state = jax.tree.map(jnp.asarray, state)
                health.record(health.FIT_RESUMED, step=int(state.step))
        train_step = self.make_train_step()
        multihost = self.mesh is not None and jax.process_count() > 1
        if jax.process_count() > 1:
            # Multi-process: force inline staging. The batch source may run
            # per-batch collectives (the streaming estimator's lockstep
            # allgather) and stage_batch assembles global arrays — enqueued
            # from a staging thread they would interleave with the main
            # thread's train-step collectives in a scheduler-dependent
            # order that can DIVERGE across processes and hang the gang.
            # One thread per process keeps every host's collective order
            # identical to the serial loop's; deferred step sync (the
            # host-side win) still applies.
            prefetch = 0
        if self.mesh is not None:
            state = jax.device_put(state, replicated(self.mesh))

        def stage_batch(arr):
            """Host batch → device array sharded over ``data``.

            uint8 batches (decoded images) transfer raw and cast to f32
            ON DEVICE — 4x less host→device traffic than casting on the
            host (the cast is exact for 0-255 integers). Multi-host
            (SURVEY.md §5.8, HorovodRunner parity): every process passes
            its LOCAL rows; the global array is assembled from the
            process-local shards — the per-host input feeding the
            reference achieved with one Spark partition per worker.
            """
            arr = np.asarray(arr)
            if multihost:
                sharding = batch_sharding(self.mesh, arr.ndim)
                out = jax.make_array_from_process_local_data(sharding, arr)
            else:
                out = jnp.asarray(arr)
            if out.dtype == jnp.uint8:
                out = out.astype(jnp.float32)
            return out

        def stage_pair(pair):
            """Staging-thread stage: host (x, y) → (n_examples, xd, yd)."""
            x, y = pair
            with profiling.annotate(profiling.STAGE_BATCH):
                return len(x), stage_batch(x), stage_batch(y)

        # Exact resume: the loop replays the (deterministic) batch stream and
        # skips the first `state.step` positions — mid-epoch restarts land on
        # the precise next batch.
        done = int(state.step)
        host_step = done
        global_idx = 0
        sync_every = max(1, int(sync_every))
        last_sync_t: Optional[float] = None
        last_sync_step = done

        def sync(st: TrainState) -> None:
            """Designated sync point — the ONLY place the step loop blocks
            on the device (enforced by the AST lint in
            tests/test_taxonomy_lint.py). Drains deferred metrics (one
            batched fetch), then barriers on the device step counter — a
            scalar fetch, the reliable barrier under the remote tunnel
            (core/profiling.py; cross-dispatch block_until_ready is not).
            The sync window also feeds the telemetry steps/sec histogram:
            steps COMPLETED (barriered) per wall second, the honest
            throughput number the deferred pipeline obscures per step.
            """
            nonlocal last_sync_t, last_sync_step
            if metrics_logger is not None:
                metrics_logger.flush()
            with profiling.annotate(profiling.DEVICE_SYNC):
                device_step = int(st.step)
            if device_step != host_step:
                raise RuntimeError(
                    f"pipelined fit desynchronized: device step "
                    f"{device_step} != host-tracked step {host_step} — "
                    "the batch stream or state chain was tampered with "
                    "mid-fit")
            now = time.perf_counter()
            if last_sync_t is not None and host_step > last_sync_step:
                dt = now - last_sync_t
                if dt > 0:
                    telemetry.observe(telemetry.M_STEPS_PER_SEC,
                                      (host_step - last_sync_step) / dt)
            last_sync_t, last_sync_step = now, host_step

        def save_checkpoint(st: TrainState) -> None:
            with telemetry.span(telemetry.SPAN_CHECKPOINT_SAVE,
                                step=host_step):
                checkpoint.save(host_step, jax.device_get(st))

        def epoch_source():
            # runs on the staging thread: resume-skipped positions are
            # counted but never staged (no wasted device_put on replay)
            nonlocal global_idx
            for pair in batches:
                if global_idx < done:
                    global_idx += 1
                    continue
                global_idx += 1
                yield pair

        # Telemetry (docs/OBSERVABILITY.md): the fit span is the parent
        # of every epoch/step span on this thread AND — via the
        # prefetcher's context handoff — of the staging thread's
        # stage_batch/decode spans, so one run trace covers both sides
        # of the pipeline. Step timing below is HOST dispatch interval
        # (perf_counter only — telemetry must never sync the device; the
        # step-loop AST lint enforces it).
        fit_span = telemetry.span(telemetry.SPAN_FIT, epochs=epochs,
                                  resume_step=done, prefetch=prefetch,
                                  sync_every=sync_every)
        last_dispatch = None
        try:
            fit_span.__enter__()
            for _epoch in range(epochs):
                with telemetry.span(telemetry.SPAN_EPOCH, epoch=_epoch), \
                        pipeline.DevicePrefetcher(
                        epoch_source(), stage_fn=stage_pair,
                        depth=prefetch, name="trainer.fit",
                        report_health=True) as staged:
                    for n_examples, xd, yd in staged:
                        # dispatch only — execution is awaited at sync
                        # points (DEVICE_SYNC carries the blocking time)
                        with profiling.annotate("sparkdl.train_step",
                                                step=host_step + 1):
                            state, metrics = train_step(state, xd, yd)
                        host_step += 1
                        now = time.perf_counter()
                        if last_dispatch is not None:
                            telemetry.observe(telemetry.M_STEP_TIME_S,
                                              now - last_dispatch)
                        last_dispatch = now
                        if metrics_logger is not None:
                            metrics_logger.log_step(host_step, metrics,
                                                    examples=n_examples,
                                                    defer=True)
                        due_ckpt = (checkpoint is not None and
                                    checkpoint_every and
                                    host_step % checkpoint_every == 0)
                        if (due_ckpt or on_step is not None
                                or host_step % sync_every == 0):
                            sync(state)
                        if due_ckpt:
                            save_checkpoint(state)
                        if on_step is not None:
                            on_step(host_step)
                        # Injection point AFTER the checkpoint write: a
                        # preemption here models losing the gang between
                        # steps — TPURunner classifies it retryable,
                        # restarts, and this loop's resume path replays
                        # from the step just saved (SURVEY.md §5.3).
                        resilience.inject("preemption", step=host_step)
                # epoch boundary is a designated sync point: on_epoch
                # observes a fully-materialized state and complete metrics
                sync(state)
                if on_epoch is not None:
                    on_epoch(_epoch, state)
        except BaseException:
            # The gang is dying with async checkpoint writes possibly in
            # flight. Flush them before unwinding so (a) the restarted
            # attempt's latest_step() sees every step this attempt
            # completed (no redone work) and (b) an abandoned async write
            # can't race the restart's save of the same step. Deferred
            # metrics flush best-effort (their steps may be the ones that
            # failed); the staging thread is already closed by the
            # prefetcher's context manager.
            if metrics_logger is not None:
                try:
                    metrics_logger.flush()
                except Exception:  # noqa: BLE001 - already unwinding
                    pass
            if checkpoint is not None:
                try:
                    checkpoint.wait_until_finished()
                except Exception:  # noqa: BLE001 - already unwinding
                    pass
            fit_span.__exit__(*sys.exc_info())
            raise
        try:
            if checkpoint is not None:
                checkpoint.save(host_step, jax.device_get(state),
                                synchronous=True)
            health.record(health.FIT_COMPLETED, steps=host_step)
            fit_span.set_attribute("steps", host_step)
        except BaseException:
            # the final synchronous save can fail too (disk full, bad
            # path) — the span must still close, or it leaks on the
            # thread-local stack and adopts every later span
            fit_span.__exit__(*sys.exc_info())
            raise
        fit_span.__exit__(None, None, None)
        return state

    def variables_of(self, state: TrainState) -> Dict[str, Any]:
        """Variables dict for inference from a trained state."""
        return {"params": state.params, **state.model_state}
