"""TPUTransformer — arbitrary model over numeric array/scalar columns.

Parity: the reference's ``TFTransformer`` (``transformers/tf_tensor.py``,
SURVEY.md §2.1) which mapped Spark rows → numpy blocks → ``sess.run`` →
output column. Here: Arrow FixedSizeList / numeric column → contiguous
numpy block (zero-copy where Arrow allows) → jitted ModelFunction with
padded static batch shapes → list<float32> output column.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pyarrow as pa

from sparkdl_tpu.core import executor as device_executor
from sparkdl_tpu.engine.dataframe import (
    _schema_with,
    _set_column,
    column_to_numpy,
    fixed_size_list_array,
)
from sparkdl_tpu.ml.base import Transformer
from sparkdl_tpu.ml.persistence import ModelFunctionPersistence
from sparkdl_tpu.param.base import Param, keyword_only
from sparkdl_tpu.param.converters import SparkDLTypeConverters
from sparkdl_tpu.param.shared_params import (
    HasBatchSize,
    HasInputCol,
    HasMesh,
    HasModelFunction,
    HasOutputCol,
    HasPriority,
)


def column_to_block(column: pa.Array, element_shape) -> np.ndarray:
    """Arrow column → (N, *element_shape) contiguous numpy block.

    Conversion is the engine's ``column_to_numpy`` (FixedSizeList/List/
    numeric); this adds the model-input contract: row length must match the
    input spec's element size — rows are reshaped, never resized.
    """
    values = column_to_numpy(column)
    n = len(column)
    want = int(np.prod(element_shape)) if element_shape else 1
    if values.ndim == 1 and want != 1:
        raise ValueError(
            f"scalar input column for model expecting {element_shape}")
    if values.size != n * want:
        raise ValueError(
            f"input rows have {values.size // max(n, 1)} elements, model "
            f"expects {want}")
    return np.ascontiguousarray(values).reshape((n,) + tuple(element_shape))


class TPUTransformer(Transformer, HasInputCol, HasOutputCol,
                     HasModelFunction, HasBatchSize, HasMesh, HasPriority,
                     ModelFunctionPersistence):
    """Apply a ModelFunction to numeric columns, emitting list<float32>.

    Single-IO: ``inputCol``/``outputCol``. Multi-IO (the reference
    ``TFTransformer``'s tensor↔column maps, SURVEY.md §2.1): a model whose
    ``input_spec`` is a ``{input-name: TensorSpec}`` dict takes
    ``inputMapping={column: input-name}`` and emits one column per entry of
    ``outputMapping={output-name: column}`` from its dict output.
    """

    inputMapping = Param(
        "TPUTransformer", "inputMapping",
        "{column-name: model-input-name} for multi-input models",
        typeConverter=SparkDLTypeConverters.asColumnToInputMap)
    outputMapping = Param(
        "TPUTransformer", "outputMapping",
        "{model-output-name: column-name} for multi-output models",
        typeConverter=SparkDLTypeConverters.asOutputToColumnMap)

    _persist_name = "tpu_transformer"
    _persist_skip = ("mesh", "modelFunction")

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 inputMapping: Optional[dict] = None,
                 outputMapping: Optional[dict] = None,
                 modelFunction=None,
                 batchSize: int = 64,
                 mesh=None, priority: Optional[str] = None) -> None:
        super().__init__()
        self._setDefault(batchSize=64)
        kwargs = self._input_kwargs
        self.setParams(**kwargs)

    @keyword_only
    def setParams(self, *, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  inputMapping: Optional[dict] = None,
                  outputMapping: Optional[dict] = None,
                  modelFunction=None,
                  batchSize: int = 64,
                  mesh=None,
                  priority: Optional[str] = None) -> "TPUTransformer":
        return self._set(**self._input_kwargs)

    def setInputMapping(self, value: dict) -> "TPUTransformer":
        return self._set(inputMapping=value)

    def getInputMapping(self) -> Optional[dict]:
        return (self.getOrDefault(self.inputMapping)
                if self.isDefined(self.inputMapping) else None)

    def setOutputMapping(self, value: dict) -> "TPUTransformer":
        return self._set(outputMapping=value)

    def getOutputMapping(self) -> Optional[dict]:
        return (self.getOrDefault(self.outputMapping)
                if self.isDefined(self.outputMapping) else None)


    def _transform(self, dataset):
        model = self.getModelFunction()
        if model is None:
            raise ValueError("modelFunction must be set")
        # Multi-host data-parallel inference (SURVEY.md §2.4 row 1): each
        # process transforms only its round-robin partition share; no-op
        # single-process, idempotent across chained transformers. Assembly
        # is opt-in via DataFrame.gatherProcesses (docs/DISTRIBUTED.md).
        dataset = dataset.processShard()
        if isinstance(model.input_spec, dict) or self.getInputMapping():
            return self._transform_multi(dataset, model)
        input_col = self.getInputCol()
        output_col = self.getOutputCol()
        batch_size = self.getBatchSize()
        from sparkdl_tpu.core.mesh import host_local_mesh

        mesh = host_local_mesh(self.resolveMesh())
        element_shape = model.input_spec.element_shape
        priority = self.getPriority()  # None: EngineConfig default lane
        if input_col not in dataset.columns:
            raise KeyError(f"No such column: {input_col!r}")

        def apply_partition(batch: pa.RecordBatch) -> pa.Array:
            if batch.num_rows == 0:
                return pa.array([], type=pa.list_(pa.float32()))
            col = batch.column(batch.schema.get_field_index(input_col))
            block = column_to_block(col, element_shape)
            block = block.astype(model.input_spec.dtype, copy=False)
            # device entry via the execution-service choke point
            # (core/executor.py): concurrent partition chunks coalesce
            out = device_executor.execute(model, block,
                                          batch_size=batch_size, mesh=mesh,
                                          priority=priority)
            out = np.asarray(out, dtype=np.float32).reshape(batch.num_rows, -1)
            return fixed_size_list_array(out).cast(pa.list_(pa.float32()))

        return dataset.withColumnBatch(output_col, apply_partition,
                                       outputType=pa.list_(pa.float32()))

    def _transform_multi(self, dataset, model):
        """Column↔named-IO mapping path for dict-spec models."""
        in_map = self.getInputMapping()
        out_map = self.getOutputMapping()
        if not isinstance(model.input_spec, dict):
            raise ValueError(
                "inputMapping requires a model with a dict input_spec")
        if not in_map:
            raise ValueError(
                "multi-input model requires inputMapping={column: input}")
        if not out_map:
            raise ValueError(
                "multi-input model requires outputMapping={output: column}")
        missing = set(model.input_spec) - set(in_map.values())
        if missing:
            raise ValueError(f"inputMapping covers no column for model "
                             f"inputs {sorted(missing)}")
        unknown = set(in_map.values()) - set(model.input_spec)
        if unknown:
            raise ValueError(
                f"inputMapping references unknown model inputs "
                f"{sorted(unknown)}; model has {sorted(model.input_spec)}")
        for col in in_map:
            if col not in dataset.columns:
                raise KeyError(f"No such column: {col!r}")
        batch_size = self.getBatchSize()
        from sparkdl_tpu.core.mesh import host_local_mesh

        mesh = host_local_mesh(self.resolveMesh())
        priority = self.getPriority()  # None: EngineConfig default lane
        out_cols = list(out_map.items())  # [(output-name, column)]

        def apply_partition(batch: pa.RecordBatch) -> pa.RecordBatch:
            n = batch.num_rows
            if n == 0:
                out = batch
                for _name, col in out_cols:
                    out = _set_column(
                        out, col, pa.array([], type=pa.list_(pa.float32())))
                return out
            blocks = {}
            for col, input_name in in_map.items():
                spec = model.input_spec[input_name]
                arr = batch.column(batch.schema.get_field_index(col))
                blocks[input_name] = column_to_block(arr, spec.element_shape)
            outs = device_executor.execute(model, blocks,
                                           batch_size=batch_size, mesh=mesh,
                                           priority=priority)
            if not isinstance(outs, dict):
                raise ValueError(
                    "outputMapping requires the model to return a "
                    f"{{output-name: array}} dict, got {type(outs).__name__}")
            result = batch
            for name, col in out_cols:
                if name not in outs:
                    raise KeyError(
                        f"model returned no output named {name!r}; has "
                        f"{sorted(outs)}")
                flat = np.asarray(outs[name], dtype=np.float32).reshape(n, -1)
                result = _set_column(
                    result, col,
                    fixed_size_list_array(flat).cast(pa.list_(pa.float32())))
            return result

        # declared schema must mirror _set_column (replace-in-place when an
        # output column name already exists, append if new) or a colliding
        # outputMapping would declare a duplicate field the batches lack
        schema = dataset.schema
        for _name, col in out_cols:
            schema = _schema_with(schema, col, pa.list_(pa.float32()))
        return dataset.mapPartitions(apply_partition, schema=schema)
