"""TPUTransformer — arbitrary model over numeric array/scalar columns.

Parity: the reference's ``TFTransformer`` (``transformers/tf_tensor.py``,
SURVEY.md §2.1) which mapped Spark rows → numpy blocks → ``sess.run`` →
output column. Here: Arrow FixedSizeList / numeric column → contiguous
numpy block (zero-copy where Arrow allows) → jitted ModelFunction with
padded static batch shapes → list<float32> output column.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pyarrow as pa

from sparkdl_tpu.engine.dataframe import column_to_numpy, fixed_size_list_array
from sparkdl_tpu.ml.base import Transformer
from sparkdl_tpu.ml.persistence import ModelFunctionPersistence
from sparkdl_tpu.param.base import keyword_only
from sparkdl_tpu.param.shared_params import (
    HasBatchSize,
    HasInputCol,
    HasMesh,
    HasModelFunction,
    HasOutputCol,
)


def column_to_block(column: pa.Array, element_shape) -> np.ndarray:
    """Arrow column → (N, *element_shape) contiguous numpy block.

    Conversion is the engine's ``column_to_numpy`` (FixedSizeList/List/
    numeric); this adds the model-input contract: row length must match the
    input spec's element size — rows are reshaped, never resized.
    """
    values = column_to_numpy(column)
    n = len(column)
    want = int(np.prod(element_shape)) if element_shape else 1
    if values.ndim == 1 and want != 1:
        raise ValueError(
            f"scalar input column for model expecting {element_shape}")
    if values.size != n * want:
        raise ValueError(
            f"input rows have {values.size // max(n, 1)} elements, model "
            f"expects {want}")
    return np.ascontiguousarray(values).reshape((n,) + tuple(element_shape))


class TPUTransformer(Transformer, HasInputCol, HasOutputCol,
                     HasModelFunction, HasBatchSize, HasMesh,
                     ModelFunctionPersistence):
    """Apply a ModelFunction to a numeric column, emitting list<float32>."""

    _persist_name = "tpu_transformer"

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelFunction=None,
                 batchSize: int = 64,
                 mesh=None) -> None:
        super().__init__()
        self._setDefault(batchSize=64)
        kwargs = self._input_kwargs
        self.setParams(**kwargs)

    @keyword_only
    def setParams(self, *, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelFunction=None,
                  batchSize: int = 64,
                  mesh=None) -> "TPUTransformer":
        return self._set(**self._input_kwargs)


    def _transform(self, dataset):
        model = self.getModelFunction()
        if model is None:
            raise ValueError("modelFunction must be set")
        input_col = self.getInputCol()
        output_col = self.getOutputCol()
        batch_size = self.getBatchSize()
        mesh = self.resolveMesh()
        element_shape = model.input_spec.element_shape
        if input_col not in dataset.columns:
            raise KeyError(f"No such column: {input_col!r}")

        def apply_partition(batch: pa.RecordBatch) -> pa.Array:
            if batch.num_rows == 0:
                return pa.array([], type=pa.list_(pa.float32()))
            col = batch.column(batch.schema.get_field_index(input_col))
            block = column_to_block(col, element_shape)
            block = block.astype(model.input_spec.dtype, copy=False)
            out = model.apply_batch(block, batch_size=batch_size, mesh=mesh)
            out = np.asarray(out, dtype=np.float32).reshape(batch.num_rows, -1)
            return fixed_size_list_array(out).cast(pa.list_(pa.float32()))

        return dataset.withColumnBatch(output_col, apply_partition,
                                       outputType=pa.list_(pa.float32()))
