"""KerasTransformer — 1-D array column → Keras model → output arrays.

Parity: the reference's ``transformers/keras_tensor.py`` (SURVEY.md §2.1):
loads a Keras model, converts it to a graph, executes via ``TFTransformer``.
Here: generic layer-DAG ingestion (models.keras_ingest) → TPUTransformer.
"""

from __future__ import annotations

from typing import Optional

from sparkdl_tpu.ml.base import Transformer
from sparkdl_tpu.ml.persistence import ModelFunctionPersistence
from sparkdl_tpu.ml.tensor_transformer import TPUTransformer
from sparkdl_tpu.param.base import keyword_only
from sparkdl_tpu.param.shared_params import (
    HasMesh,
    HasBatchSize,
    HasInputCol,
    HasKerasModel,
    HasOutputCol,
)


class KerasTransformer(Transformer, HasInputCol, HasOutputCol,
                       HasKerasModel, HasBatchSize, HasMesh,
                       ModelFunctionPersistence):
    """Apply a Keras model to a numeric column (1-D rows)."""

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelFile: Optional[str] = None,
                 model=None,
                 batchSize: int = 64,
                 mesh=None) -> None:
        super().__init__()
        self._setDefault(batchSize=64)
        self._mf_cache = None
        kwargs = self._input_kwargs
        self.setParams(**kwargs)

    @keyword_only
    def setParams(self, *, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelFile: Optional[str] = None,
                  model=None,
                  batchSize: int = 64,
                  mesh=None) -> "KerasTransformer":
        if {"model", "modelFile"} & self._input_kwargs.keys():
            self._mf_cache = None
        return self._set(**self._input_kwargs)

    def copy(self, extra=None):
        that = super().copy(extra)
        that._mf_cache = None
        return that

    def setModel(self, value):
        self._mf_cache = None
        return super().setModel(value)

    def setModelFile(self, value):
        self._mf_cache = None
        return super().setModelFile(value)

    # persistence: ingested Keras DAG → StableHLO (ModelFunctionPersistence)
    _persist_skip = ("mesh", "modelFile", "model", "modelFunction")
    _persist_name = "keras_tensor"

    def _persist_model_function(self):
        if self._mf_cache is None:
            self._mf_cache = self.loadKerasModelAsFunction()
        return self._mf_cache

    def _restore_model_function(self, mf) -> None:
        self._mf_cache = mf

    def _transform(self, dataset):
        if self._mf_cache is None:
            self._mf_cache = self.loadKerasModelAsFunction()
        inner = TPUTransformer(
            inputCol=self.getInputCol(), outputCol=self.getOutputCol(),
            modelFunction=self._mf_cache, batchSize=self.getBatchSize(),
            mesh=self.getMesh())
        return inner.transform(dataset)
