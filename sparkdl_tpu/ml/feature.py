"""Feature stages: StringIndexer / IndexToString / VectorAssembler /
OneHotEncoder.

Parity: the Spark ML feature stages real reference-era pipelines put
around ``Pipeline([DeepImageFeaturizer, LogisticRegression])`` (upstream
README assumed Spark ML): string labels in, assembled feature vectors,
readable predictions out. Semantics per stage:

- ``StringIndexer.fit`` orders labels by ``stringOrderType``
  (``frequencyDesc`` default, ties and alphabet orders broken
  alphabetically like Spark) and the model maps values to float indices;
  ``handleInvalid`` = ``error``/``skip``/``keep`` applies to unseen
  labels AND nulls (Spark's invalid-data contract).
- ``IndexToString`` inverts with an explicit ``labels`` list or the one
  a ``StringIndexerModel`` learned.
- ``VectorAssembler`` concatenates numeric scalar and vector columns
  into one vector column in input order; ``handleInvalid`` =
  ``error``/``skip``/``keep`` (keep pads null scalars as NaN, Spark's
  rule; a null vector cell cannot be kept — its width is unknown).
- ``OneHotEncoder`` maps a category-index column to an indicator vector
  with Spark's ``dropLast=True`` default (the last category encodes as
  all-zeros).
"""

from __future__ import annotations

from collections import Counter
from typing import List, Optional

from sparkdl_tpu.engine.dataframe import list_column_to_numpy
from sparkdl_tpu.ml.base import Estimator, Model, Transformer
from sparkdl_tpu.ml.persistence import ParamsOnlyPersistence
from sparkdl_tpu.param.base import Param, Params, keyword_only
from sparkdl_tpu.param.converters import SparkDLTypeConverters, TypeConverters

_ORDER_TYPES = ("frequencyDesc", "frequencyAsc", "alphabetDesc",
                "alphabetAsc")
_INVALID_POLICIES = ("error", "skip", "keep")


class _IndexerParams(Params):
    inputCol = Param("_IndexerParams", "inputCol", "input column",
                     typeConverter=SparkDLTypeConverters.toColumnName)
    outputCol = Param("_IndexerParams", "outputCol", "output column",
                      typeConverter=SparkDLTypeConverters.toColumnName)

    def setInputCol(self, value):
        return self._set(inputCol=value)

    def getInputCol(self):
        return self.getOrDefault(self.inputCol)

    def setOutputCol(self, value):
        return self._set(outputCol=value)

    def getOutputCol(self):
        return self.getOrDefault(self.outputCol)


class StringIndexer(Estimator, _IndexerParams, ParamsOnlyPersistence):
    """Learn a string→index mapping over a column (Spark semantics)."""

    stringOrderType = Param(
        "StringIndexer", "stringOrderType", f"one of {_ORDER_TYPES}",
        typeConverter=SparkDLTypeConverters.supportedNameConverter(
            list(_ORDER_TYPES)))
    handleInvalid = Param(
        "StringIndexer", "handleInvalid", f"one of {_INVALID_POLICIES}",
        typeConverter=SparkDLTypeConverters.supportedNameConverter(
            list(_INVALID_POLICIES)))

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 stringOrderType: str = "frequencyDesc",
                 handleInvalid: str = "error") -> None:
        super().__init__()
        self._setDefault(stringOrderType="frequencyDesc",
                         handleInvalid="error")
        self._set(**self._input_kwargs)

    def setStringOrderType(self, value):
        return self._set(stringOrderType=value)

    def getStringOrderType(self):
        return self.getOrDefault(self.stringOrderType)

    def setHandleInvalid(self, value):
        return self._set(handleInvalid=value)

    def getHandleInvalid(self):
        return self.getOrDefault(self.handleInvalid)

    def _fit(self, dataset) -> "StringIndexerModel":
        col = self.getInputCol()
        counts: Counter = Counter()
        saw_null = False
        for batch in dataset.select(col).streamPartitions():
            # sparkdl: allow(columnar-hot-path): string label column —
            # indexing needs Python strings; not a tensor hop
            for v in batch.column(0).to_pylist():
                if v is None:
                    saw_null = True
                else:
                    counts[str(v)] += 1
        if saw_null and self.getHandleInvalid() == "error":
            # Spark semantics: NULL is invalid data, subject to the policy
            raise ValueError(
                f"{col!r} contains NULL values (handleInvalid='error'; "
                "use 'skip' or 'keep')")
        if not counts:
            raise ValueError(f"no non-null values in {col!r} to index")
        order = self.getStringOrderType()
        if order == "frequencyDesc":
            # Spark tie-break: alphabetical among equal frequencies
            labels = sorted(counts, key=lambda v: (-counts[v], v))
        elif order == "frequencyAsc":
            labels = sorted(counts, key=lambda v: (counts[v], v))
        elif order == "alphabetDesc":
            labels = sorted(counts, reverse=True)
        else:
            labels = sorted(counts)
        model = StringIndexerModel(
            inputCol=col, outputCol=self.getOutputCol(),
            handleInvalid=self.getHandleInvalid(), labels=labels)
        model._set_parent(self)
        return model


class StringIndexerModel(Model, _IndexerParams, ParamsOnlyPersistence):
    """Fitted mapping: ``labels[i] -> float(i)``."""

    handleInvalid = Param(
        "StringIndexerModel", "handleInvalid",
        f"one of {_INVALID_POLICIES}",
        typeConverter=SparkDLTypeConverters.supportedNameConverter(
            list(_INVALID_POLICIES)))
    labels = Param("StringIndexerModel", "labels",
                   "ordered label list (index = position)",
                   typeConverter=TypeConverters.toListString)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 handleInvalid: str = "error",
                 labels: Optional[List[str]] = None) -> None:
        super().__init__()
        self._setDefault(handleInvalid="error")
        self._set(**self._input_kwargs)

    def getLabels(self) -> List[str]:
        return list(self.getOrDefault(self.labels))

    def getHandleInvalid(self):
        return self.getOrDefault(self.handleInvalid)

    def _transform(self, dataset):
        col = self.getInputCol()
        out = self.getOutputCol()
        labels = self.getLabels()
        index = {v: float(i) for i, v in enumerate(labels)}
        policy = self.getHandleInvalid()

        # Spark semantics: NULL counts as invalid data like an unseen
        # label — error raises, skip drops the row, keep maps to numLabels
        if policy == "skip":
            dataset = dataset.filter(
                lambda v: v is not None and str(v) in index,
                inputCols=[col])

        def lookup(v):
            if v is not None and str(v) in index:
                return index[str(v)]
            if policy == "keep":
                return float(len(labels))
            raise ValueError(
                f"Invalid label {v!r} in {col!r} (handleInvalid='error'; "
                "use 'skip' or 'keep')")

        import pyarrow as pa

        return dataset.withColumn(out, lookup, inputCols=[col],
                                  outputType=pa.float64())


class VectorAssembler(Transformer, Params, ParamsOnlyPersistence):
    """Concatenate numeric/vector columns into one vector column."""

    inputCols = Param("VectorAssembler", "inputCols",
                      "columns to concatenate, in order",
                      typeConverter=TypeConverters.toListString)
    outputCol = Param("VectorAssembler", "outputCol", "output column",
                      typeConverter=SparkDLTypeConverters.toColumnName)
    handleInvalid = Param(
        "VectorAssembler", "handleInvalid", f"one of {_INVALID_POLICIES}",
        typeConverter=SparkDLTypeConverters.supportedNameConverter(
            list(_INVALID_POLICIES)))

    @keyword_only
    def __init__(self, *, inputCols: Optional[List[str]] = None,
                 outputCol: Optional[str] = None,
                 handleInvalid: str = "error") -> None:
        super().__init__()
        self._setDefault(handleInvalid="error")
        self._set(**self._input_kwargs)

    def setInputCols(self, value):
        return self._set(inputCols=value)

    def getInputCols(self):
        return list(self.getOrDefault(self.inputCols))

    def setOutputCol(self, value):
        return self._set(outputCol=value)

    def getOutputCol(self):
        return self.getOrDefault(self.outputCol)

    def getHandleInvalid(self):
        return self.getOrDefault(self.handleInvalid)

    def _transform(self, dataset):
        import pyarrow as pa

        cols = self.getInputCols()
        if not cols:
            raise ValueError("inputCols must name at least one column")
        for c in cols:
            if c not in dataset.columns:
                raise KeyError(f"No such column: {c!r}")
        policy = self.getHandleInvalid()
        # Schema-derived column kinds: a null VECTOR cell has unknown
        # width, so even 'keep' must raise for it (a single NaN would
        # make the assembled column ragged and crash/misalign the
        # downstream learner far from the cause — Spark raises too).
        vector_cols = {
            c for c in cols
            if pa.types.is_list(dataset.schema.field(c).type)
            or pa.types.is_fixed_size_list(dataset.schema.field(c).type)
            or pa.types.is_large_list(dataset.schema.field(c).type)}

        if policy == "skip":
            # element-level too: a [1.0, None] vector cell is invalid data
            # even though the cell itself is non-null
            def row_valid(*vals) -> bool:
                for v in vals:
                    if v is None:
                        return False
                    if isinstance(v, (list, tuple)) and any(
                            x is None for x in v):
                        return False
                return True

            dataset = dataset.filter(row_valid, inputCols=cols)

        def assemble(*vals):
            out: List[float] = []
            for c, v in zip(cols, vals):
                if v is None:
                    if policy == "keep" and c not in vector_cols:
                        out.append(float("nan"))  # Spark: null scalar→NaN
                        continue
                    raise ValueError(
                        f"NULL in {c!r} "
                        + ("(vector column: width unknown, cannot keep)"
                           if c in vector_cols else
                           "(handleInvalid='error'; use 'skip' or 'keep')"))
                if isinstance(v, (list, tuple)):
                    for x in v:
                        if x is None:
                            # element width IS known here: keep → NaN
                            if policy == "keep":
                                out.append(float("nan"))
                                continue
                            raise ValueError(
                                f"NULL element inside vector column "
                                f"{c!r} (handleInvalid='error'; use "
                                "'skip' or 'keep')")
                        out.append(float(x))
                else:
                    out.append(float(v))
            return out

        # float64 like Spark's double vectors: float32 would silently
        # round int64 ids above 2^24 and truncate float64 inputs
        return dataset.withColumn(self.getOutputCol(), assemble,
                                  inputCols=cols,
                                  outputType=pa.list_(pa.float64()))


class OneHotEncoder(Transformer, _IndexerParams, ParamsOnlyPersistence):
    """Category-index column → indicator vector (Spark semantics:
    ``dropLast=True`` encodes the last category as all-zeros;
    ``handleInvalid='keep'`` widens the vector by one extra category for
    invalid values — nulls and out-of-range indices — while the default
    ``'error'`` raises at the encoder, naming the column)."""

    numCategories = Param("OneHotEncoder", "numCategories",
                          "category count (vector width before dropLast)",
                          typeConverter=TypeConverters.toInt)
    dropLast = Param("OneHotEncoder", "dropLast",
                     "encode the last category as all-zeros (Spark "
                     "default True)",
                     typeConverter=TypeConverters.toBoolean)
    handleInvalid = Param(
        "OneHotEncoder", "handleInvalid",
        "'error' (raise on null/out-of-range) or 'keep' (extra category)",
        typeConverter=SparkDLTypeConverters.supportedNameConverter(
            ["error", "keep"]))

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 numCategories: Optional[int] = None,
                 dropLast: bool = True,
                 handleInvalid: str = "error") -> None:
        super().__init__()
        self._setDefault(dropLast=True, handleInvalid="error")
        self._set(**self._input_kwargs)

    def getNumCategories(self):
        return (self.getOrDefault(self.numCategories)
                if self.isDefined(self.numCategories) else None)

    def getDropLast(self):
        return self.getOrDefault(self.dropLast)

    def getHandleInvalid(self):
        return self.getOrDefault(self.handleInvalid)

    def _transform(self, dataset):
        import pyarrow as pa

        n = self.getNumCategories()
        if n is None or n < 2:
            raise ValueError(f"numCategories must be >= 2, got {n}")
        col = self.getInputCol()
        keep = self.getHandleInvalid() == "keep"
        # Spark widths: keep adds an extra "invalid" category; dropLast
        # drops one. keep+dropLast: invalid encodes as all-zeros.
        width = n + (1 if keep else 0) - (1 if self.getDropLast() else 0)

        import math

        def encode(v):
            invalid = v is None or (isinstance(v, float)
                                    and not math.isfinite(v))
            i = -1
            if not invalid:
                i = int(v)
                if float(v) != i:
                    # a fractional index is a wiring mistake (probability
                    # column?), never valid data — always raise
                    raise ValueError(
                        f"category index {v!r} in {col!r} is not integral")
                invalid = not 0 <= i < n
            if invalid:
                if not keep:
                    raise ValueError(
                        f"invalid category {v!r} in {col!r} "
                        "(handleInvalid='error'; use 'keep')")
                i = n  # the extra category (all-zeros when dropped)
            vec = [0.0] * width
            if i < width:
                vec[i] = 1.0
            return vec

        return dataset.withColumn(self.getOutputCol(), encode,
                                  inputCols=[col],
                                  outputType=pa.list_(pa.float32()))


class StandardScaler(Estimator, _IndexerParams, ParamsOnlyPersistence):
    """Standardize a vector column (Spark semantics: ``withStd=True``
    divides by the UNBIASED per-dimension std, ``withMean=False`` by
    default — centering densifies sparse data, so Spark makes it
    opt-in)."""

    withMean = Param("StandardScaler", "withMean",
                     "center by the mean before scaling (Spark default "
                     "False)", typeConverter=TypeConverters.toBoolean)
    withStd = Param("StandardScaler", "withStd",
                    "scale to unit std (Spark default True)",
                    typeConverter=TypeConverters.toBoolean)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 withMean: bool = False, withStd: bool = True) -> None:
        super().__init__()
        self._setDefault(withMean=False, withStd=True)
        self._set(**self._input_kwargs)

    def getWithMean(self):
        return self.getOrDefault(self.withMean)

    def getWithStd(self):
        return self.getOrDefault(self.withStd)

    def _fit(self, dataset) -> "StandardScalerModel":
        import numpy as np

        col = self.getInputCol()
        # streaming Welford merge per dimension (bounded memory, no
        # catastrophic cancellation — same recipe as RegressionEvaluator)
        n = 0
        mean = None
        m2 = None
        for batch in dataset.select(col).streamPartitions():
            # columnar hoist: uniform-width vector columns become one
            # (n, K) float64 view without the per-row Python hop
            x = list_column_to_numpy(batch.column(0))
            if x is None:
                # sparkdl: allow(columnar-hot-path): ragged/null-element
                # fallback — uniform vector batches take the hoist above
                rows = [r for r in batch.column(0).to_pylist()
                        if r is not None]
                if not rows:
                    continue
                x = np.asarray(rows, np.float64)
            if not len(x):
                continue
            nb = len(x)
            batch_mean = x.mean(axis=0)
            batch_m2 = ((x - batch_mean) ** 2).sum(axis=0)
            if mean is None:
                mean, m2, n = batch_mean, batch_m2, nb
                continue
            if batch_mean.shape != mean.shape:
                # numpy would silently broadcast mismatched widths into
                # garbage statistics
                raise ValueError(
                    f"{col!r} holds vectors of inconsistent widths: "
                    f"{mean.shape[0]} vs {batch_mean.shape[0]}")
            delta = batch_mean - mean
            total = n + nb
            m2 = m2 + batch_m2 + delta ** 2 * n * nb / total
            mean = mean + delta * nb / total
            n = total
        if n == 0:
            raise ValueError(f"no non-null rows in {col!r} to fit on")
        std = np.sqrt(m2 / max(n - 1, 1))
        std = np.where(std > 0, std, 1.0)
        model = StandardScalerModel(
            inputCol=col, outputCol=self.getOutputCol(),
            withMean=self.getWithMean(), withStd=self.getWithStd(),
            mean=mean.tolist(), std=std.tolist())
        model._set_parent(self)
        return model


class StandardScalerModel(Model, _IndexerParams, ParamsOnlyPersistence):
    """Fitted scaler: per-dimension (x - mean?) / std?."""

    withMean = Param("StandardScalerModel", "withMean", "center first",
                     typeConverter=TypeConverters.toBoolean)
    withStd = Param("StandardScalerModel", "withStd", "scale to unit std",
                    typeConverter=TypeConverters.toBoolean)
    mean = Param("StandardScalerModel", "mean", "per-dimension mean",
                 typeConverter=TypeConverters.toListFloat)
    std = Param("StandardScalerModel", "std", "per-dimension unbiased std",
                typeConverter=TypeConverters.toListFloat)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 withMean: bool = False, withStd: bool = True,
                 mean: Optional[List[float]] = None,
                 std: Optional[List[float]] = None) -> None:
        super().__init__()
        self._setDefault(withMean=False, withStd=True)
        self._set(**self._input_kwargs)

    def getMean(self):
        import numpy as np

        return np.asarray(self.getOrDefault(self.mean), np.float64)

    def getStd(self):
        import numpy as np

        return np.asarray(self.getOrDefault(self.std), np.float64)

    def _transform(self, dataset):
        import numpy as np
        import pyarrow as pa

        mean = self.getMean()
        std = self.getStd()
        center = self.getOrDefault(self.withMean)
        scale = self.getOrDefault(self.withStd)

        def scale_row(v):
            if v is None:
                return None
            x = np.asarray(v, np.float64)
            if x.shape != mean.shape:
                raise ValueError(
                    f"row width {x.shape} != fitted width {mean.shape}")
            if center:
                x = x - mean
            if scale:
                x = x / std
            return x.tolist()

        return dataset.withColumn(self.getOutputCol(), scale_row,
                                  inputCols=[self.getInputCol()],
                                  outputType=pa.list_(pa.float64()))


class MinMaxScaler(Estimator, _IndexerParams, ParamsOnlyPersistence):
    """Rescale a vector column to [min, max] per dimension (Spark
    semantics: constant dimensions map to the midpoint)."""

    min = Param("MinMaxScaler", "min", "lower bound (default 0.0)",
                typeConverter=TypeConverters.toFloat)
    max = Param("MinMaxScaler", "max", "upper bound (default 1.0)",
                typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 min: float = 0.0, max: float = 1.0) -> None:
        super().__init__()
        self._setDefault(min=0.0, max=1.0)
        self._set(**self._input_kwargs)

    def _fit(self, dataset) -> "MinMaxScalerModel":
        import numpy as np

        lo_b = self.getOrDefault(self.min)
        hi_b = self.getOrDefault(self.max)
        if lo_b >= hi_b:
            raise ValueError(f"min ({lo_b}) must be < max ({hi_b})")
        col = self.getInputCol()
        lo = hi = None
        for batch in dataset.select(col).streamPartitions():
            # columnar hoist; null ELEMENTS surface as NaN and fail the
            # finite check below with the same error as the row path
            x = list_column_to_numpy(batch.column(0), element_nulls="nan")
            if x is None:
                # sparkdl: allow(columnar-hot-path): ragged fallback —
                # uniform vector batches take the hoist above
                rows = [r for r in batch.column(0).to_pylist()
                        if r is not None]
                if not rows:
                    continue
                x = np.asarray(rows, np.float64)
            if not len(x):
                continue
            if not np.isfinite(x).all():
                # NaN would poison min/max and the transform would then
                # silently midpoint the whole dimension — demand finite
                # inputs (run Imputer first)
                raise ValueError(
                    f"{col!r} holds NaN/Inf/null elements; impute before "
                    "MinMaxScaler")
            bl, bh = x.min(axis=0), x.max(axis=0)
            if lo is None:
                lo, hi = bl, bh
                continue
            if bl.shape != lo.shape:
                raise ValueError(
                    f"{col!r} holds vectors of inconsistent widths: "
                    f"{lo.shape[0]} vs {bl.shape[0]}")
            lo = np.minimum(lo, bl)
            hi = np.maximum(hi, bh)
        if lo is None:
            raise ValueError(f"no non-null rows in {col!r} to fit on")
        model = MinMaxScalerModel(
            inputCol=col, outputCol=self.getOutputCol(),
            min=lo_b, max=hi_b, originalMin=lo.tolist(),
            originalMax=hi.tolist())
        model._set_parent(self)
        return model


class MinMaxScalerModel(Model, _IndexerParams, ParamsOnlyPersistence):
    """Fitted range scaler."""

    min = Param("MinMaxScalerModel", "min", "lower bound",
                typeConverter=TypeConverters.toFloat)
    max = Param("MinMaxScalerModel", "max", "upper bound",
                typeConverter=TypeConverters.toFloat)
    originalMin = Param("MinMaxScalerModel", "originalMin",
                        "fitted per-dimension minimum",
                        typeConverter=TypeConverters.toListFloat)
    originalMax = Param("MinMaxScalerModel", "originalMax",
                        "fitted per-dimension maximum",
                        typeConverter=TypeConverters.toListFloat)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 min: float = 0.0, max: float = 1.0,
                 originalMin: Optional[List[float]] = None,
                 originalMax: Optional[List[float]] = None) -> None:
        super().__init__()
        self._setDefault(min=0.0, max=1.0)
        self._set(**self._input_kwargs)

    def _transform(self, dataset):
        import numpy as np
        import pyarrow as pa

        lo = np.asarray(self.getOrDefault(self.originalMin), np.float64)
        hi = np.asarray(self.getOrDefault(self.originalMax), np.float64)
        out_lo = self.getOrDefault(self.min)
        out_hi = self.getOrDefault(self.max)
        span = hi - lo
        mid = (out_lo + out_hi) / 2.0
        # hoisted per-dimension affine: one multiply-add per row. Spark's
        # rule for constant dimensions (span 0): map to the midpoint.
        scale = np.where(span > 0, (out_hi - out_lo)
                         / np.where(span > 0, span, 1.0), 0.0)
        offset = np.where(span > 0, out_lo - lo * scale, mid)

        def scale_row(v):
            if v is None:
                return None
            x = np.asarray(v, np.float64)
            if x.shape != lo.shape:
                raise ValueError(
                    f"row width {x.shape} != fitted width {lo.shape}")
            return (x * scale + offset).tolist()

        return dataset.withColumn(self.getOutputCol(), scale_row,
                                  inputCols=[self.getInputCol()],
                                  outputType=pa.list_(pa.float64()))


class Imputer(Estimator, _IndexerParams, ParamsOnlyPersistence):
    """Fill nulls (and NaNs) in a vector column with the per-dimension
    mean or median (Spark's Imputer, single-column form)."""

    strategy = Param(
        "Imputer", "strategy", "'mean' or 'median'",
        typeConverter=SparkDLTypeConverters.supportedNameConverter(
            ["mean", "median"]))

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 strategy: str = "mean") -> None:
        super().__init__()
        self._setDefault(strategy="mean")
        self._set(**self._input_kwargs)

    def getStrategy(self):
        return self.getOrDefault(self.strategy)

    def _fit(self, dataset) -> "ImputerModel":
        import numpy as np

        col = self.getInputCol()
        # Missing = null/NaN ONLY; +/-inf is a regular value (Spark
        # semantics — an inf observation makes the mean inf, it is not
        # silently dropped).
        if self.getStrategy() == "mean":
            # streaming per-dimension sum/count (bounded memory, like
            # the scalers)
            total = count = None
            for batch in dataset.select(col).streamPartitions():
                # columnar hoist: null ELEMENTS map to NaN — exactly the
                # row path's None→NaN convention below
                x = list_column_to_numpy(batch.column(0),
                                         element_nulls="nan")
                if x is None:
                    # sparkdl: allow(columnar-hot-path): ragged fallback —
                    # uniform vector batches take the hoist above
                    rows = [r for r in batch.column(0).to_pylist()
                            if r is not None]
                    if not rows:
                        continue
                    x = np.asarray([[np.nan if e is None else e for e in r]
                                    for r in rows], np.float64)
                if not len(x):
                    continue
                observed = ~np.isnan(x)
                bsum = np.where(observed, x, 0.0).sum(axis=0)
                bcnt = observed.sum(axis=0)
                if total is None:
                    total, count = bsum, bcnt
                    continue
                if bsum.shape != total.shape:
                    raise ValueError(
                        f"{col!r} holds vectors of inconsistent widths: "
                        f"{total.shape[0]} vs {bsum.shape[0]}")
                total = total + bsum
                count = count + bcnt
            if total is None:
                raise ValueError(f"no non-null rows in {col!r} to fit on")
            if (count == 0).any():
                raise ValueError(
                    f"{col!r} has dimensions with NO observed values; "
                    "cannot impute")
            fill = total / count
        else:
            # median needs the observed value set per dimension; Spark's
            # percentile_approx(0.5) returns an ACTUAL element — the
            # lower-middle for even counts — not numpy's midpoint average
            rows = [r[col] for r in dataset.select(col).collect()
                    if r[col] is not None]
            if not rows:
                raise ValueError(f"no non-null rows in {col!r} to fit on")
            x = np.asarray([[np.nan if e is None else e for e in r]
                            for r in rows], np.float64)
            fill = np.empty(x.shape[1])
            for j in range(x.shape[1]):
                observed = np.sort(x[~np.isnan(x[:, j]), j])
                if len(observed) == 0:
                    raise ValueError(
                        f"{col!r} has dimensions with NO observed "
                        "values; cannot impute")
                fill[j] = observed[(len(observed) - 1) // 2]
        model = ImputerModel(inputCol=col, outputCol=self.getOutputCol(),
                             surrogates=fill.tolist())
        model._set_parent(self)
        return model


class ImputerModel(Model, _IndexerParams, ParamsOnlyPersistence):
    """Fitted imputer: null rows and NaN elements fill with surrogates."""

    surrogates = Param("ImputerModel", "surrogates",
                       "per-dimension fill values",
                       typeConverter=TypeConverters.toListFloat)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 surrogates: Optional[List[float]] = None) -> None:
        super().__init__()
        self._set(**self._input_kwargs)

    def getSurrogates(self):
        import numpy as np

        return np.asarray(self.getOrDefault(self.surrogates), np.float64)

    def _transform(self, dataset):
        import numpy as np
        import pyarrow as pa

        fill = self.getSurrogates()

        def impute(v):
            if v is None:
                return fill.tolist()
            x = np.asarray([np.nan if e is None else e for e in v],
                           np.float64)
            if x.shape != fill.shape:
                raise ValueError(
                    f"row width {x.shape} != fitted width {fill.shape}")
            return np.where(np.isnan(x), fill, x).tolist()

        return dataset.withColumn(self.getOutputCol(), impute,
                                  inputCols=[self.getInputCol()],
                                  outputType=pa.list_(pa.float64()))


class Normalizer(Transformer, _IndexerParams, ParamsOnlyPersistence):
    """Scale each vector row to unit p-norm (Spark's Normalizer;
    default p=2). Zero rows pass through unchanged (Spark behavior)."""

    p = Param("Normalizer", "p", "norm order (p >= 1; default 2.0)",
              typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None, p: float = 2.0) -> None:
        super().__init__()
        self._setDefault(p=2.0)
        self._set(**self._input_kwargs)

    def _transform(self, dataset):
        import numpy as np
        import pyarrow as pa

        p = self.getOrDefault(self.p)
        if p < 1.0:
            raise ValueError(f"p must be >= 1, got {p}")

        def normalize(v):
            if v is None:
                return None
            x = np.asarray(v, np.float64)
            norm = float(np.linalg.norm(x, ord=p))
            if norm == 0:  # exact-zero rows pass through (Spark)
                return x.tolist()
            # a NaN norm divides through — NaN elements propagate to the
            # whole row like Spark, never a silently un-normalized row
            return (x / norm).tolist()

        return dataset.withColumn(self.getOutputCol(), normalize,
                                  inputCols=[self.getInputCol()],
                                  outputType=pa.list_(pa.float64()))


class Binarizer(Transformer, _IndexerParams, ParamsOnlyPersistence):
    """Threshold a numeric or vector column to 0/1 (Spark's Binarizer:
    strictly greater than ``threshold`` → 1.0)."""

    threshold = Param("Binarizer", "threshold",
                      "values > threshold become 1.0 (default 0.0)",
                      typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 threshold: float = 0.0) -> None:
        super().__init__()
        self._setDefault(threshold=0.0)
        self._set(**self._input_kwargs)

    def _transform(self, dataset):
        import numpy as np
        import pyarrow as pa

        t = self.getOrDefault(self.threshold)

        def binarize(v):
            if v is None:
                return None
            if isinstance(v, (list, tuple)):
                return (np.asarray(v, np.float64) > t) \
                    .astype(np.float64).tolist()
            return 1.0 if float(v) > t else 0.0

        # Declare the output type from the INPUT column's declared type:
        # leaving it to inference would type the lazy column pa.null(),
        # which defeats downstream schema-driven logic (VectorAssembler's
        # vector-column detection and its null-vector-cell guard).
        in_type = dataset.schema.field(self.getInputCol()).type
        if (pa.types.is_list(in_type) or pa.types.is_large_list(in_type)
                or pa.types.is_fixed_size_list(in_type)):
            out_type = pa.list_(pa.float64())
        elif pa.types.is_null(in_type):
            out_type = None  # unknown upstream type: defer to inference
        else:
            out_type = pa.float64()
        return dataset.withColumn(self.getOutputCol(), binarize,
                                  inputCols=[self.getInputCol()],
                                  outputType=out_type)


class SQLTransformer(Transformer, Params, ParamsOnlyPersistence):
    """A SQL statement as a Pipeline stage (Spark's SQLTransformer):
    ``statement`` runs against the input frame bound as ``__THIS__`` —
    registered UDFs, WHERE filters, aliases and literals all work, so a
    served model (``registerImageUDF``) composes into a Pipeline as one
    stage: ``SQLTransformer(statement="SELECT my_udf(image) AS f, label
    FROM __THIS__ WHERE label IS NOT NULL")``."""

    statement = Param("SQLTransformer", "statement",
                      "SQL with __THIS__ as the input table",
                      typeConverter=TypeConverters.toString)

    @keyword_only
    def __init__(self, *, statement: Optional[str] = None) -> None:
        super().__init__()
        self._set(**self._input_kwargs)

    def getStatement(self) -> str:
        return self.getOrDefault(self.statement)

    def _transform(self, dataset):
        import uuid

        from sparkdl_tpu.engine import dataframe as _df

        statement = self.getStatement()
        if "__THIS__" not in statement:
            raise ValueError(
                f"statement must reference __THIS__: {statement!r}")
        view = f"sdl_sqlt_{uuid.uuid4().hex[:12]}"
        _df._temp_views[view] = dataset
        try:
            return _df.sql(statement.replace("__THIS__", view))
        finally:
            _df._temp_views.pop(view, None)


class IndexToString(Transformer, _IndexerParams, ParamsOnlyPersistence):
    """Inverse mapping: float index column → label string column."""

    labels = Param("IndexToString", "labels", "ordered label list",
                   typeConverter=TypeConverters.toListString)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 labels: Optional[List[str]] = None) -> None:
        super().__init__()
        self._set(**self._input_kwargs)

    def getLabels(self) -> List[str]:
        return list(self.getOrDefault(self.labels))

    def _transform(self, dataset):
        labels = self.getLabels()

        def lookup(v):
            if v is None:
                return None
            i = int(v)
            if not 0 <= i < len(labels):
                raise ValueError(
                    f"index {i} out of range for {len(labels)} labels")
            return labels[i]

        import pyarrow as pa

        return dataset.withColumn(self.getOutputCol(), lookup,
                                  inputCols=[self.getInputCol()],
                                  outputType=pa.string())
