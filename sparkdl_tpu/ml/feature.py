"""Feature stages: StringIndexer / IndexToString.

Parity: Spark ML's label-indexing pair. The reference's flagship
pipeline (``Pipeline([DeepImageFeaturizer, LogisticRegression])``,
upstream README) assumed Spark ML around it — real datasets carry string
labels, and Spark users put ``StringIndexer`` in front of the classifier
and ``IndexToString`` behind it. Same semantics here:

- ``StringIndexer.fit`` orders labels by ``stringOrderType``
  (``frequencyDesc`` default, ties and alphabet orders broken
  alphabetically like Spark) and the model maps values to float indices.
- ``handleInvalid``: ``error`` (raise on unseen values), ``skip`` (drop
  those rows), ``keep`` (index them as ``len(labels)``).
- ``IndexToString`` inverts with an explicit ``labels`` list or the
  one a ``StringIndexerModel`` learned.
"""

from __future__ import annotations

from collections import Counter
from typing import List, Optional

from sparkdl_tpu.ml.base import Estimator, Model, Transformer
from sparkdl_tpu.ml.persistence import ParamsOnlyPersistence
from sparkdl_tpu.param.base import Param, Params, keyword_only
from sparkdl_tpu.param.converters import SparkDLTypeConverters, TypeConverters

_ORDER_TYPES = ("frequencyDesc", "frequencyAsc", "alphabetDesc",
                "alphabetAsc")
_INVALID_POLICIES = ("error", "skip", "keep")


class _IndexerParams(Params):
    inputCol = Param("_IndexerParams", "inputCol", "input column",
                     typeConverter=SparkDLTypeConverters.toColumnName)
    outputCol = Param("_IndexerParams", "outputCol", "output column",
                      typeConverter=SparkDLTypeConverters.toColumnName)

    def setInputCol(self, value):
        return self._set(inputCol=value)

    def getInputCol(self):
        return self.getOrDefault(self.inputCol)

    def setOutputCol(self, value):
        return self._set(outputCol=value)

    def getOutputCol(self):
        return self.getOrDefault(self.outputCol)


class StringIndexer(Estimator, _IndexerParams, ParamsOnlyPersistence):
    """Learn a string→index mapping over a column (Spark semantics)."""

    stringOrderType = Param(
        "StringIndexer", "stringOrderType", f"one of {_ORDER_TYPES}",
        typeConverter=SparkDLTypeConverters.supportedNameConverter(
            list(_ORDER_TYPES)))
    handleInvalid = Param(
        "StringIndexer", "handleInvalid", f"one of {_INVALID_POLICIES}",
        typeConverter=SparkDLTypeConverters.supportedNameConverter(
            list(_INVALID_POLICIES)))

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 stringOrderType: str = "frequencyDesc",
                 handleInvalid: str = "error") -> None:
        super().__init__()
        self._setDefault(stringOrderType="frequencyDesc",
                         handleInvalid="error")
        self._set(**self._input_kwargs)

    def setStringOrderType(self, value):
        return self._set(stringOrderType=value)

    def getStringOrderType(self):
        return self.getOrDefault(self.stringOrderType)

    def setHandleInvalid(self, value):
        return self._set(handleInvalid=value)

    def getHandleInvalid(self):
        return self.getOrDefault(self.handleInvalid)

    def _fit(self, dataset) -> "StringIndexerModel":
        col = self.getInputCol()
        counts: Counter = Counter()
        saw_null = False
        for batch in dataset.select(col).streamPartitions():
            for v in batch.column(0).to_pylist():
                if v is None:
                    saw_null = True
                else:
                    counts[str(v)] += 1
        if saw_null and self.getHandleInvalid() == "error":
            # Spark semantics: NULL is invalid data, subject to the policy
            raise ValueError(
                f"{col!r} contains NULL values (handleInvalid='error'; "
                "use 'skip' or 'keep')")
        if not counts:
            raise ValueError(f"no non-null values in {col!r} to index")
        order = self.getStringOrderType()
        if order == "frequencyDesc":
            # Spark tie-break: alphabetical among equal frequencies
            labels = sorted(counts, key=lambda v: (-counts[v], v))
        elif order == "frequencyAsc":
            labels = sorted(counts, key=lambda v: (counts[v], v))
        elif order == "alphabetDesc":
            labels = sorted(counts, reverse=True)
        else:
            labels = sorted(counts)
        model = StringIndexerModel(
            inputCol=col, outputCol=self.getOutputCol(),
            handleInvalid=self.getHandleInvalid(), labels=labels)
        model._set_parent(self)
        return model


class StringIndexerModel(Model, _IndexerParams, ParamsOnlyPersistence):
    """Fitted mapping: ``labels[i] -> float(i)``."""

    handleInvalid = Param(
        "StringIndexerModel", "handleInvalid",
        f"one of {_INVALID_POLICIES}",
        typeConverter=SparkDLTypeConverters.supportedNameConverter(
            list(_INVALID_POLICIES)))
    labels = Param("StringIndexerModel", "labels",
                   "ordered label list (index = position)",
                   typeConverter=TypeConverters.toListString)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 handleInvalid: str = "error",
                 labels: Optional[List[str]] = None) -> None:
        super().__init__()
        self._setDefault(handleInvalid="error")
        self._set(**self._input_kwargs)

    def getLabels(self) -> List[str]:
        return list(self.getOrDefault(self.labels))

    def getHandleInvalid(self):
        return self.getOrDefault(self.handleInvalid)

    def _transform(self, dataset):
        col = self.getInputCol()
        out = self.getOutputCol()
        labels = self.getLabels()
        index = {v: float(i) for i, v in enumerate(labels)}
        policy = self.getHandleInvalid()

        # Spark semantics: NULL counts as invalid data like an unseen
        # label — error raises, skip drops the row, keep maps to numLabels
        if policy == "skip":
            dataset = dataset.filter(
                lambda v: v is not None and str(v) in index,
                inputCols=[col])

        def lookup(v):
            if v is not None and str(v) in index:
                return index[str(v)]
            if policy == "keep":
                return float(len(labels))
            raise ValueError(
                f"Invalid label {v!r} in {col!r} (handleInvalid='error'; "
                "use 'skip' or 'keep')")

        import pyarrow as pa

        return dataset.withColumn(out, lookup, inputCols=[col],
                                  outputType=pa.float64())


class IndexToString(Transformer, _IndexerParams, ParamsOnlyPersistence):
    """Inverse mapping: float index column → label string column."""

    labels = Param("IndexToString", "labels", "ordered label list",
                   typeConverter=TypeConverters.toListString)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 labels: Optional[List[str]] = None) -> None:
        super().__init__()
        self._set(**self._input_kwargs)

    def getLabels(self) -> List[str]:
        return list(self.getOrDefault(self.labels))

    def _transform(self, dataset):
        labels = self.getLabels()

        def lookup(v):
            if v is None:
                return None
            i = int(v)
            if not 0 <= i < len(labels):
                raise ValueError(
                    f"index {i} out of range for {len(labels)} labels")
            return labels[i]

        import pyarrow as pa

        return dataset.withColumn(self.getOutputCol(), lookup,
                                  inputCols=[self.getInputCol()],
                                  outputType=pa.string())
