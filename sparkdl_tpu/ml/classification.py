"""Downstream classical learners: LogisticRegression (+ model).

Parity: the reference's flagship workflow is
``Pipeline([DeepImageFeaturizer, LogisticRegression])`` — the featurizer
emits a vector column and **Spark ML's** LogisticRegression consumes it
(upstream README example; SURVEY.md §0). The rebuild has no Spark ML to
lean on, so the consumer ships in-framework with Spark's param surface
(``featuresCol/labelCol/predictionCol/probabilityCol, maxIter, regParam,
tol, fitIntercept``) and TPU-native training: one jitted
``lax.while_loop`` of L-BFGS (optax) over the full feature matrix —
multinomial softmax with L2 regularization, converged on gradient norm.

Scale note: features for classical learners are small (thousands of rows
x 2048 dims); full-batch on-device optimization IS the idiomatic TPU
form — per-row streaming would be dispatch-bound.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sparkdl_tpu.engine.dataframe import list_column_to_numpy
from sparkdl_tpu.ml.base import Estimator, Model
from sparkdl_tpu.ml.persistence import ParamsOnlyPersistence
from sparkdl_tpu.param.base import Param, keyword_only
from sparkdl_tpu.param.converters import (
    SparkDLTypeConverters,
    TypeConverters,
)
from sparkdl_tpu.param.shared_params import HasLabelCol


class _HasClassifierCols(HasLabelCol):
    featuresCol = Param("_HasClassifierCols", "featuresCol",
                        "input column of fixed-length float vectors",
                        typeConverter=SparkDLTypeConverters.toColumnName)
    predictionCol = Param("_HasClassifierCols", "predictionCol",
                          "output column: predicted class index (float, "
                          "Spark ML convention)",
                          typeConverter=SparkDLTypeConverters.toColumnName)
    probabilityCol = Param("_HasClassifierCols", "probabilityCol",
                           "output column: class probability vector",
                           typeConverter=SparkDLTypeConverters.toColumnName)

    def setFeaturesCol(self, value): return self._set(featuresCol=value)

    def getFeaturesCol(self): return self.getOrDefault(self.featuresCol)

    def setPredictionCol(self, value): return self._set(predictionCol=value)

    def getPredictionCol(self): return self.getOrDefault(self.predictionCol)

    def setProbabilityCol(self, value): return self._set(probabilityCol=value)

    def getProbabilityCol(self): return self.getOrDefault(self.probabilityCol)


class LogisticRegression(Estimator, _HasClassifierCols,
                         ParamsOnlyPersistence):
    """Multinomial (softmax) logistic regression on a vector column.

    **Spark ML parity envelope** (the exact contract vs
    ``pyspark.ml.classification.LogisticRegression``, VERDICT r4 #6):

    ================== =====================================================
    matches Spark      ``featuresCol/labelCol/predictionCol/probabilityCol``,
                       ``maxIter``, ``regParam`` (L2), ``tol``,
                       ``fitIntercept``, ``standardization`` — features are
                       scaled by their (unbiased) std before the solve and
                       coefficients unscaled after, so regularized fits
                       match Spark's default-standardized coefficients;
                       the intercept is never penalized.
                       ``weightCol`` (loss = Σwᵢ·ceᵢ / Σw + penalty, r5)
                       and ``thresholds`` (predict
                       ``argmax(pᵢ/tᵢ)``, Spark's rule, r5).
    differs            multinomial softmax is the ONLY family (Spark's
                       binary path uses pivoted logistic; probabilities
                       agree, coefficients differ by the usual centering);
                       coefficients are NOT centered post-fit.
    absent (raises on  ``elasticNetParam`` (L1 needs a prox/OWL-QN solver,
    no silent default) not a deliberate omission of a flag),
                       ``lowerBoundsOnCoefficients`` et al.
    ================== =====================================================
    """

    maxIter = Param("LogisticRegression", "maxIter",
                    "maximum L-BFGS iterations",
                    typeConverter=TypeConverters.toInt)
    regParam = Param("LogisticRegression", "regParam",
                     "L2 regularization strength (0 disables)",
                     typeConverter=TypeConverters.toFloat)
    tol = Param("LogisticRegression", "tol",
                "convergence tolerance on the gradient norm",
                typeConverter=TypeConverters.toFloat)
    fitIntercept = Param("LogisticRegression", "fitIntercept",
                         "whether to fit an intercept term",
                         typeConverter=TypeConverters.toBoolean)
    standardization = Param(
        "LogisticRegression", "standardization",
        "scale features to unit std before fitting (Spark's default True; "
        "changes the regularized optimum, reported coefficients are always "
        "on the original scale)",
        typeConverter=TypeConverters.toBoolean)
    weightCol = Param(
        "LogisticRegression", "weightCol",
        "optional column of non-negative row weights; the loss becomes "
        "the weighted mean cross-entropy (Spark semantics: weight 2 == "
        "duplicating the row)",
        typeConverter=SparkDLTypeConverters.toColumnName)
    thresholds = Param(
        "LogisticRegression", "thresholds",
        "per-class thresholds; prediction = argmax_i(p_i / t_i) (Spark's "
        "rule); length must equal the class count, values > 0",
        typeConverter=TypeConverters.identity)

    @keyword_only
    def __init__(self, *, featuresCol: str = "features",
                 labelCol: str = "label",
                 predictionCol: str = "prediction",
                 probabilityCol: str = "probability",
                 maxIter: int = 100, regParam: float = 0.0,
                 tol: float = 1e-6, fitIntercept: bool = True,
                 standardization: bool = True,
                 weightCol: Optional[str] = None,
                 thresholds: Optional[list] = None) -> None:
        super().__init__()
        self._setDefault(featuresCol="features", labelCol="label",
                         predictionCol="prediction",
                         probabilityCol="probability", maxIter=100,
                         regParam=0.0, tol=1e-6, fitIntercept=True,
                         standardization=True)
        self.setParams(**self._input_kwargs)

    @keyword_only
    def setParams(self, *, featuresCol: str = "features",
                  labelCol: str = "label",
                  predictionCol: str = "prediction",
                  probabilityCol: str = "probability",
                  maxIter: int = 100, regParam: float = 0.0,
                  tol: float = 1e-6,
                  fitIntercept: bool = True,
                  standardization: bool = True,
                  weightCol: Optional[str] = None,
                  thresholds: Optional[list] = None) -> "LogisticRegression":
        self._set(**self._input_kwargs)
        return self

    def setMaxIter(self, value): return self._set(maxIter=value)

    def getMaxIter(self): return self.getOrDefault(self.maxIter)

    def setRegParam(self, value): return self._set(regParam=value)

    def getRegParam(self): return self.getOrDefault(self.regParam)

    def setTol(self, value): return self._set(tol=value)

    def getTol(self): return self.getOrDefault(self.tol)

    def setFitIntercept(self, value): return self._set(fitIntercept=value)

    def getFitIntercept(self): return self.getOrDefault(self.fitIntercept)

    def setStandardization(self, value):
        return self._set(standardization=value)

    def getStandardization(self):
        return self.getOrDefault(self.standardization)

    def setWeightCol(self, value):
        return self._set(weightCol=value)

    def getWeightCol(self):
        return (self.getOrDefault(self.weightCol)
                if self.isDefined(self.weightCol) else None)

    def setThresholds(self, value):
        return self._set(thresholds=value)

    def getThresholds(self):
        return (self.getOrDefault(self.thresholds)
                if self.isDefined(self.thresholds) else None)

    def _collect_xy(self, dataset):
        weight_col = self.getWeightCol()
        cols = [self.getFeaturesCol(), self.getLabelCol()]
        if weight_col is not None:
            cols.append(weight_col)
        rows = dataset.select(*cols).collect()
        feats, labels, weights = [], [], []
        for r in rows:
            f = r[self.getFeaturesCol()]
            if f is None:
                continue
            feats.append(np.asarray(f, np.float32))
            labels.append(r[self.getLabelCol()])
            if weight_col is not None:
                w = r[weight_col]
                weights.append(1.0 if w is None else float(w))
        if not feats:
            raise ValueError("no non-null feature rows to fit on")
        x = np.stack(feats)
        y = np.asarray(labels)
        if y.dtype.kind not in "iuf":
            raise ValueError(
                f"labelCol {self.getLabelCol()!r} must hold numeric class "
                f"indices, got dtype {y.dtype}")
        y = y.astype(np.int32)
        if y.min() < 0:
            raise ValueError("labels must be non-negative class indices")
        w = None
        if weight_col is not None:
            from sparkdl_tpu.ml.linear_utils import validate_weights

            w = validate_weights(np.asarray(weights, np.float32),
                                 weight_col)
        return x, y, int(y.max()) + 1, w

    def _fit(self, dataset) -> "LogisticRegressionModel":
        x, y, n_classes, sample_w = self._collect_xy(dataset)
        if n_classes < 2:
            n_classes = 2
        thresholds = self.getThresholds()
        if thresholds is not None:
            t = np.asarray(thresholds, np.float64)
            if len(t) != n_classes or (t <= 0).any():
                raise ValueError(
                    f"thresholds must hold {n_classes} positive values, "
                    f"got {thresholds}")
        # Spark semantics: fit in unit-std feature space (intercept
        # unpenalized and unaffected — scaling is shift-free), report
        # coefficients on the original scale.
        std = None
        if self.getStandardization() and len(x) > 1:
            from sparkdl_tpu.ml.linear_utils import weighted_feature_std

            std = weighted_feature_std(x, sample_w).astype(np.float32)
            x = x / std
        w, b, iters = _fit_softmax(
            x, y, n_classes, max_iter=self.getMaxIter(),
            reg=self.getRegParam(), tol=self.getTol(),
            fit_intercept=self.getFitIntercept(), sample_weight=sample_w)
        if std is not None:
            w = np.asarray(w) / std[:, None]
        model = LogisticRegressionModel(
            featuresCol=self.getFeaturesCol(), labelCol=self.getLabelCol(),
            predictionCol=self.getPredictionCol(),
            probabilityCol=self.getProbabilityCol(),
            thresholds=thresholds)
        model._set_weights(np.asarray(w), np.asarray(b))
        model.numIterations = int(iters)
        model._set_parent(self)
        return model


def _fit_softmax(x: np.ndarray, y: np.ndarray, n_classes: int,
                 max_iter: int, reg: float, tol: float,
                 fit_intercept: bool,
                 sample_weight: Optional[np.ndarray] = None):
    """Jitted L-BFGS on (weighted) mean softmax-CE + (reg/2)·||W||²; whole
    opt loop is ONE XLA program (lax.while_loop over optax.lbfgs
    updates). ``sample_weight`` gives Σwᵢ·ceᵢ/Σw — weight 2 equals
    duplicating the row (Spark's weightCol)."""
    xd = jnp.asarray(x)
    yd = jnp.asarray(y)
    wd = None if sample_weight is None else jnp.asarray(sample_weight)
    d = x.shape[1]

    def loss_fn(params):
        logits = xd @ params["w"]
        if fit_intercept:
            logits = logits + params["b"]
        ce_rows = optax.softmax_cross_entropy_with_integer_labels(
            logits, yd)
        ce = (ce_rows.mean() if wd is None
              else jnp.sum(ce_rows * wd) / jnp.sum(wd))
        return ce + 0.5 * reg * jnp.sum(params["w"] ** 2)

    opt = optax.lbfgs()
    params0 = {"w": jnp.zeros((d, n_classes), jnp.float32),
               "b": jnp.zeros((n_classes,), jnp.float32)}

    @jax.jit
    def run(params):
        value_and_grad = optax.value_and_grad_from_state(loss_fn)
        state0 = opt.init(params)

        def cond(carry):
            params, state, g, i = carry
            gnorm = optax.global_norm(g)
            return (i < max_iter) & (gnorm > tol)

        def body(carry):
            params, state, _, i = carry
            value, grad = value_and_grad(params, state=state)
            updates, state = opt.update(
                grad, state, params, value=value, grad=grad,
                value_fn=loss_fn)
            params = optax.apply_updates(params, updates)
            return params, state, grad, i + 1

        g0 = jax.grad(loss_fn)(params)
        params, state, g, iters = jax.lax.while_loop(
            cond, body, (params, state0, g0, jnp.zeros((), jnp.int32)))
        return params, iters

    params, iters = run(params0)
    return (jax.device_get(params["w"]), jax.device_get(params["b"]),
            jax.device_get(iters))


class LogisticRegressionModel(Model, _HasClassifierCols):
    """Fitted model: adds prediction (+ probability) columns.

    With ``thresholds`` set, prediction is ``argmax_i(p_i / t_i)``
    (Spark's multiclass thresholding rule); otherwise plain argmax.
    """

    thresholds = Param("LogisticRegressionModel", "thresholds",
                       "per-class thresholds applied at prediction time",
                       typeConverter=TypeConverters.identity)

    @keyword_only
    def __init__(self, *, featuresCol: str = "features",
                 labelCol: str = "label",
                 predictionCol: str = "prediction",
                 probabilityCol: str = "probability",
                 thresholds: Optional[list] = None) -> None:
        super().__init__()
        self._setDefault(featuresCol="features", labelCol="label",
                         predictionCol="prediction",
                         probabilityCol="probability")
        self._set(**self._input_kwargs)
        self.numIterations: Optional[int] = None

    def getThresholds(self):
        return (self.getOrDefault(self.thresholds)
                if self.isDefined(self.thresholds) else None)

    def _set_weights(self, w: np.ndarray, b: np.ndarray) -> None:
        self._w = np.asarray(w, np.float32)
        self._b = np.asarray(b, np.float32)

    @property
    def coefficients(self) -> np.ndarray:
        return self._w

    @property
    def intercept(self) -> np.ndarray:
        return self._b

    @property
    def numClasses(self) -> int:
        return int(self._w.shape[1])

    def _transform(self, dataset):
        import pyarrow as pa

        w, b = self._w, self._b
        feat_col = self.getFeaturesCol()
        prob_col = self.getProbabilityCol()

        def predict_batch(batch: "pa.RecordBatch") -> "pa.Array":
            col = batch.column(batch.schema.get_field_index(feat_col))
            # columnar hoist: uniform vector column → one (n, K) view
            n_rows = len(col)
            xmat = list_column_to_numpy(col)
            if xmat is not None:
                valid = np.flatnonzero(col.is_valid()).tolist()
                x = np.asarray(xmat, np.float32)
            else:
                # sparkdl: allow(columnar-hot-path): ragged fallback —
                # uniform vector batches take the hoist above
                rows = col.to_pylist()
                valid = [i for i, r in enumerate(rows) if r is not None]
                x = (np.asarray([rows[i] for i in valid], np.float32)
                     if valid else None)
            out = []
            probs_by_row: Dict[int, np.ndarray] = {}
            if valid:
                logits = x @ w + b
                logits -= logits.max(axis=1, keepdims=True)
                e = np.exp(logits)
                probs = e / e.sum(axis=1, keepdims=True)
                probs_by_row = dict(zip(valid, probs))
            for i in range(n_rows):
                out.append(probs_by_row[i].tolist() if i in probs_by_row
                           else None)
            return pa.array(out, type=pa.list_(pa.float32()))

        thresholds = self.getThresholds()
        t = (np.asarray(thresholds, np.float64)
             if thresholds is not None else None)

        def decide(p):
            if p is None:
                return None
            probs = np.asarray(p, np.float64)
            if t is not None:
                probs = probs / t  # Spark's rule: argmax(p_i / t_i)
            return float(int(np.argmax(probs)))

        with_probs = dataset.withColumnBatch(
            prob_col, predict_batch,
            outputType=pa.list_(pa.float32()))
        return with_probs.withColumn(
            self.getPredictionCol(), decide, inputCols=[prob_col])

    # -- persistence ---------------------------------------------------------

    def save(self, path: str) -> None:
        import os

        from sparkdl_tpu.ml import persistence as P

        os.makedirs(path, exist_ok=True)
        params = P.jsonable_params(self)
        np.savez(os.path.join(path, "weights.npz"), w=self._w, b=self._b)
        P.write_metadata(path, self, params, {"weights": "weights.npz"})

    @classmethod
    def _load_from(cls, path: str, meta):
        import os

        inst = cls(**meta["params"])
        data = np.load(os.path.join(path, meta["artifacts"]["weights"]))
        inst._set_weights(data["w"], data["b"])
        return inst
