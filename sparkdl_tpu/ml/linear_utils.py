"""Shared helpers for the classical linear learners
(classification.LogisticRegression / regression.LinearRegression):
weighted standardization statistics and weight validation — one
implementation so the two learners cannot drift (review r5)."""

from __future__ import annotations

from typing import Optional

import numpy as np


def weighted_feature_std(x: np.ndarray,
                         w: Optional[np.ndarray]) -> np.ndarray:
    """Per-dimension unbiased std for standardization, weighted when
    ``w`` is given (Spark's weighted summarizer: with integer weights
    this equals the duplicated sample's ddof=1 std, keeping
    weight-k == k-duplicated-rows exact under regularization).
    Zero-variance dimensions return 1.0 so scaling is a no-op there.
    """
    if w is None:
        std = x.std(axis=0, ddof=1)
    else:
        wsum = float(w.sum())
        mu = (w[:, None] * x).sum(axis=0) / wsum
        var = ((w[:, None] * (x - mu) ** 2).sum(axis=0)
               / max(wsum - 1.0, 1e-12))
        std = np.sqrt(var)
    return np.where(std > 0, std, 1.0)


def validate_weights(w: np.ndarray, weight_col: str) -> np.ndarray:
    w = np.asarray(w)
    if (w < 0).any():
        raise ValueError(f"{weight_col!r} holds negative weights")
    return w
