"""KerasImageFileTransformer — image URIs → Keras model → predictions.

Parity: the reference's ``transformers/keras_image.py`` (SURVEY.md §2.1):
mixes in ``CanLoadImage`` (URI → decode → user preprocessor → image
struct), converts the Keras model and runs it through the image
transformer. Here the Keras model is ingested once by the generic layer-DAG
walker (models.keras_ingest) into a jitted XLA program.

Data plane: ``loadImagesInternal`` builds its decoded column through the
zero-copy columnar builder (``imageIO.imageArraysToStructColumn``, gated
by ``EngineConfig.columnar_images``), and the inner TPUImageTransformer
ships raw uint8 with resize/normalize fused into the compiled program
under ``EngineConfig.fused_preprocess`` — see docs/PERF.md "Columnar
data plane". No code here changes for that: this transformer rides the
shared ingest spine.
"""

from __future__ import annotations

from typing import Callable, Optional

from sparkdl_tpu.ml.base import Transformer
from sparkdl_tpu.ml.image_transformer import TPUImageTransformer
from sparkdl_tpu.ml.persistence import ModelFunctionPersistence
from sparkdl_tpu.param.base import keyword_only
from sparkdl_tpu.param.shared_params import (
    HasMesh,
    CanLoadImage,
    HasBatchSize,
    HasInputCol,
    HasKerasModel,
    HasOutputCol,
    HasOutputMode,
)

_LOADED_IMAGE_COL = "__sdl_loaded_image"


class KerasImageFileTransformer(Transformer, HasInputCol, HasOutputCol,
                                HasKerasModel, CanLoadImage, HasOutputMode,
                                HasBatchSize, HasMesh,
                                ModelFunctionPersistence):
    """Apply a Keras model (from file or object) to an image-URI column."""

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelFile: Optional[str] = None,
                 model=None,
                 imageLoader: Optional[Callable] = None,
                 outputMode: str = "vector",
                 batchSize: int = 64,
                 mesh=None) -> None:
        super().__init__()
        self._setDefault(outputMode="vector", batchSize=64)
        self._mf_cache = None
        kwargs = self._input_kwargs
        self.setParams(**kwargs)

    @keyword_only
    def setParams(self, *, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelFile: Optional[str] = None,
                  model=None,
                  imageLoader: Optional[Callable] = None,
                  outputMode: str = "vector",
                  batchSize: int = 64,
                  mesh=None) -> "KerasImageFileTransformer":
        kwargs = dict(self._input_kwargs)
        loader = kwargs.pop("imageLoader", None)
        if {"model", "modelFile"} & kwargs.keys():
            self._mf_cache = None
        self._set(**kwargs)
        if loader is not None:
            self.setImageLoader(loader)
        return self

    def _model_function(self):
        if self._mf_cache is None:
            self._mf_cache = self.loadKerasModelAsFunction()
        return self._mf_cache

    def copy(self, extra=None):
        that = super().copy(extra)
        that._mf_cache = None
        return that

    def setModel(self, value):
        self._mf_cache = None
        return super().setModel(value)

    def setModelFile(self, value):
        self._mf_cache = None
        return super().setModelFile(value)

    # persistence: ingested Keras DAG → StableHLO (ModelFunctionPersistence)
    # model (live Keras object) and imageLoader are artifact-/guard-handled
    _persist_skip = ("mesh", "modelFile", "model", "imageLoader",
                     "modelFunction")
    _persist_check_loader = True
    _persist_name = "keras_image_file"

    def _persist_model_function(self):
        return self._model_function()

    def _restore_model_function(self, mf) -> None:
        self._mf_cache = mf

    def _transform(self, dataset):
        mf = self._model_function()
        shape = mf.input_spec.shape
        target_size = ((shape[1], shape[2])
                       if len(shape) == 4 and None not in shape[1:3] else None)
        loaded = self.loadImagesInternal(
            dataset, self.getInputCol(), _LOADED_IMAGE_COL,
            target_size=target_size)
        inner = TPUImageTransformer(
            inputCol=_LOADED_IMAGE_COL, outputCol=self.getOutputCol(),
            modelFunction=mf, outputMode=self.getOutputMode(),
            batchSize=self.getBatchSize(), mesh=self.getMesh())
        return inner.transform(loaded).drop(_LOADED_IMAGE_COL)
