"""Evaluators — the metric side of model selection.

Parity: Spark ML's ``MulticlassClassificationEvaluator`` /
``RegressionEvaluator`` / ``BinaryClassificationEvaluator`` are what the
reference's documented HPO workflow
(``CrossValidator(estimator=KerasImageFileEstimator, ...)``, upstream
README) plugged in as ``evaluator``. Same param surface
(``predictionCol/labelCol/metricName``, ``evaluate(df) -> float``,
``isLargerBetter``), computed with numpy over the engine frame.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from sparkdl_tpu.ml.persistence import ParamsOnlyPersistence
from sparkdl_tpu.param.base import Param, Params, keyword_only
from sparkdl_tpu.param.converters import SparkDLTypeConverters
from sparkdl_tpu.param.shared_params import HasLabelCol


class Evaluator(Params):
    """``evaluate(dataset) -> float`` + ``isLargerBetter()``."""

    def evaluate(self, dataset) -> float:
        raise NotImplementedError

    def isLargerBetter(self) -> bool:
        return True


class _HasPredictionCol(Params):
    predictionCol = Param(
        "_HasPredictionCol", "predictionCol", "prediction column name",
        typeConverter=SparkDLTypeConverters.toColumnName)

    def setPredictionCol(self, value):
        return self._set(predictionCol=value)

    def getPredictionCol(self):
        return self.getOrDefault(self.predictionCol)


def _iter_pair_batches(dataset, prediction_col: str, label_col: str):
    """Yield (pred, label) float64 arrays per partition, nulls dropped.

    Streams via ``streamPartitions`` (VERDICT r4 weak #3): evaluation
    memory stays bounded by one partition, so a CV loop over a dataset
    that motivated streaming ``fit`` never materializes a fold.
    """
    frame = dataset.select(prediction_col, label_col)
    for batch in frame.streamPartitions():
        if batch.num_rows == 0:
            continue
        pcol = batch.column(batch.schema.get_field_index(prediction_col))
        lcol = batch.column(batch.schema.get_field_index(label_col))
        # columnar hoist: validity masks + one vectorized conversion per
        # column — NULL rows drop (Spark convention), genuine NaN VALUES
        # survive into the metric exactly as the per-row path kept them
        keep = (np.asarray(pcol.is_valid()) & np.asarray(lcol.is_valid()))
        if not keep.any():
            continue
        yield (np.asarray(pcol.to_numpy(zero_copy_only=False)[keep],
                          np.float64),
               np.asarray(lcol.to_numpy(zero_copy_only=False)[keep],
                          np.float64))


def _no_rows() -> ValueError:
    return ValueError("no non-null (prediction, label) rows to evaluate")


class MulticlassClassificationEvaluator(Evaluator, _HasPredictionCol,
                                        HasLabelCol, ParamsOnlyPersistence):
    """accuracy / f1 / weightedPrecision / weightedRecall over class-index
    prediction+label columns (Spark's default metric is f1)."""

    _METRICS = ("f1", "accuracy", "weightedPrecision", "weightedRecall")

    metricName = Param("MulticlassClassificationEvaluator", "metricName",
                       f"one of {_METRICS}",
                       typeConverter=SparkDLTypeConverters.supportedNameConverter(list(_METRICS)))

    @keyword_only
    def __init__(self, *, predictionCol: str = "prediction",
                 labelCol: str = "label",
                 metricName: str = "f1") -> None:
        super().__init__()
        self._setDefault(predictionCol="prediction", labelCol="label",
                         metricName="f1")
        self._set(**self._input_kwargs)

    def setMetricName(self, value):
        return self._set(metricName=value)

    def getMetricName(self):
        return self.getOrDefault(self.metricName)

    def evaluate(self, dataset) -> float:
        """Streaming accumulation: per-class tp/fp/fn counts build up
        partition by partition; metrics close over the counts at the end
        (identical values to a whole-dataset computation)."""
        from collections import defaultdict

        tp: dict = defaultdict(float)
        fp: dict = defaultdict(float)
        fn: dict = defaultdict(float)
        n = 0
        correct = 0.0
        for pred, lab in _iter_pair_batches(dataset, self.getPredictionCol(),
                                            self.getLabelCol()):
            n += len(pred)
            hit = pred == lab
            correct += float(hit.sum())
            for c in np.unique(np.concatenate([pred, lab])):
                tp[c] += float(((pred == c) & hit).sum())
                fp[c] += float(((pred == c) & ~hit).sum())
                fn[c] += float(((lab == c) & ~hit).sum())
        if n == 0:
            raise _no_rows()
        metric = self.getMetricName()
        if metric == "accuracy":
            return correct / n
        weights, precisions, recalls, f1s = [], [], [], []
        for c in sorted(set(tp) | set(fp) | set(fn)):
            support = tp[c] + fn[c]
            p = tp[c] / (tp[c] + fp[c]) if tp[c] + fp[c] > 0 else 0.0
            r = tp[c] / support if support > 0 else 0.0
            f1 = 2 * p * r / (p + r) if p + r > 0 else 0.0
            weights.append(support)
            precisions.append(p)
            recalls.append(r)
            f1s.append(f1)
        w = np.asarray(weights) / max(1.0, float(sum(weights)))
        table = {"weightedPrecision": precisions, "weightedRecall": recalls,
                 "f1": f1s}
        return float(np.dot(w, table[metric]))


class RegressionEvaluator(Evaluator, _HasPredictionCol, HasLabelCol,
                          ParamsOnlyPersistence):
    """rmse / mse / mae / r2 over numeric prediction+label columns."""

    _METRICS = ("rmse", "mse", "mae", "r2")

    metricName = Param("RegressionEvaluator", "metricName",
                       f"one of {_METRICS}",
                       typeConverter=SparkDLTypeConverters.supportedNameConverter(list(_METRICS)))

    @keyword_only
    def __init__(self, *, predictionCol: str = "prediction",
                 labelCol: str = "label",
                 metricName: str = "rmse") -> None:
        super().__init__()
        self._setDefault(predictionCol="prediction", labelCol="label",
                         metricName="rmse")
        self._set(**self._input_kwargs)

    def setMetricName(self, value):
        return self._set(metricName=value)

    def getMetricName(self):
        return self.getOrDefault(self.metricName)

    def isLargerBetter(self) -> bool:
        return self.getMetricName() == "r2"

    def evaluate(self, dataset) -> float:
        """Streaming accumulation — memory bounded by one partition.

        SStot uses Chan's parallel Welford merge (running mean + M2), not
        Σlab² − n·mean²: the raw-moment form cancels catastrophically for
        labels with large mean (e.g. timestamps), silently zeroing r2.
        """
        n = 0
        ss_err = abs_err = 0.0
        lab_mean = lab_m2 = 0.0  # Welford running mean / sum of squares
        for pred, lab in _iter_pair_batches(dataset, self.getPredictionCol(),
                                            self.getLabelCol()):
            err = pred - lab
            ss_err += float(np.sum(err ** 2))
            abs_err += float(np.sum(np.abs(err)))
            nb = len(lab)
            batch_mean = float(lab.mean())
            batch_m2 = float(np.sum((lab - batch_mean) ** 2))
            delta = batch_mean - lab_mean
            total = n + nb
            lab_m2 += batch_m2 + delta ** 2 * n * nb / total
            lab_mean += delta * nb / total
            n = total
        if n == 0:
            raise _no_rows()
        metric = self.getMetricName()
        if metric == "mse":
            return ss_err / n
        if metric == "rmse":
            return float(np.sqrt(ss_err / n))
        if metric == "mae":
            return abs_err / n
        return 1.0 - ss_err / lab_m2 if lab_m2 > 0 else 0.0


class BinaryClassificationEvaluator(Evaluator, HasLabelCol,
                                    ParamsOnlyPersistence):
    """areaUnderROC / areaUnderPR over a score + binary-label column.

    Parity: Spark ML's ``BinaryClassificationEvaluator`` — the third
    evaluator of the family the reference's CV workflows used (Spark's
    param surface: ``rawPredictionCol``/``labelCol``/``metricName``,
    default metric areaUnderROC). The score column may hold either a
    scalar (decision value / P(class 1)) or a probability/raw vector,
    in which case the LAST element — the positive class, Spark's
    convention for 2-vectors — is used.

    Curve semantics (documented contract, asserted by hand-computed
    tests): points are taken at every distinct score threshold with ties
    grouped; areaUnderROC is the trapezoid integral of TPR over FPR from
    (0,0); areaUnderPR prepends Spark's (recall=0, firstPrecision)
    anchor — the first curve point's precision, matching
    ``BinaryClassificationMetrics`` — and integrates precision over
    recall by trapezoid.

    Unlike the multiclass/regression evaluators (streaming sufficient
    statistics), exact AUC needs the full score vector for the global
    sort, so this one holds all (score, label) pairs — two scalars per
    row, not the dataset.
    """

    _METRICS = ("areaUnderROC", "areaUnderPR")

    rawPredictionCol = Param(
        "BinaryClassificationEvaluator", "rawPredictionCol",
        "score column: scalar or probability/raw vector (last element "
        "= positive class)",
        typeConverter=SparkDLTypeConverters.toColumnName)
    metricName = Param("BinaryClassificationEvaluator", "metricName",
                       f"one of {_METRICS}",
                       typeConverter=SparkDLTypeConverters.supportedNameConverter(list(_METRICS)))

    @keyword_only
    def __init__(self, *, rawPredictionCol: str = "rawPrediction",
                 labelCol: str = "label",
                 metricName: str = "areaUnderROC") -> None:
        super().__init__()
        self._setDefault(rawPredictionCol="rawPrediction", labelCol="label",
                         metricName="areaUnderROC")
        self._set(**self._input_kwargs)

    def setRawPredictionCol(self, value):
        return self._set(rawPredictionCol=value)

    def getRawPredictionCol(self):
        return self.getOrDefault(self.rawPredictionCol)

    def setMetricName(self, value):
        return self._set(metricName=value)

    def getMetricName(self):
        return self.getOrDefault(self.metricName)

    def _collect_scores(self, dataset):
        rows = dataset.select(self.getRawPredictionCol(),
                              self.getLabelCol()).collect()
        scores, labels = [], []
        for r in rows:
            s, lab = r[self.getRawPredictionCol()], r[self.getLabelCol()]
            if s is None or lab is None:
                continue
            if isinstance(s, (list, tuple, np.ndarray)):
                s = s[-1]
            scores.append(float(s))
            labels.append(float(lab))
        if not scores:
            raise ValueError("no non-null (score, label) rows to evaluate")
        lab = np.asarray(labels)
        if not np.isin(lab, (0.0, 1.0)).all():
            raise ValueError(
                f"{self.getLabelCol()!r} must hold binary 0/1 labels")
        sc = np.asarray(scores)
        if not np.isfinite(sc).all():
            # a diverged model's NaN scores would rank arbitrarily and
            # yield a finite-but-meaningless AUC — fail loudly instead
            raise ValueError(
                f"{self.getRawPredictionCol()!r} contains non-finite scores")
        return sc, lab

    def _curve_points(self, score: np.ndarray, label: np.ndarray):
        """Cumulative (tp, fp) at each distinct descending threshold."""
        order = np.argsort(-score, kind="mergesort")
        s, lab = score[order], label[order]
        last_of_group = np.r_[np.nonzero(np.diff(s))[0], len(s) - 1]
        tp = np.cumsum(lab)[last_of_group]
        fp = np.cumsum(1.0 - lab)[last_of_group]
        return tp, fp

    def evaluate(self, dataset) -> float:
        score, label = self._collect_scores(dataset)
        tp, fp = self._curve_points(score, label)
        pos, neg = tp[-1], fp[-1]
        if pos == 0 or neg == 0:
            raise ValueError(
                "both classes must be present to compute a binary metric")
        if self.getMetricName() == "areaUnderROC":
            tpr = np.r_[0.0, tp / pos]
            fpr = np.r_[0.0, fp / neg]
            return float(_trapezoid(tpr, fpr))
        recall = np.r_[0.0, tp / pos]
        # Spark parity (ADVICE r5): the PR curve is anchored at
        # (recall=0, precision=first point's precision) — Spark's
        # BinaryClassificationMetrics prepends (0.0, firstPrecision), NOT
        # an optimistic (0, 1.0), which would inflate AUPR whenever the
        # top-scoring threshold group contains a negative.
        prec_curve = tp / (tp + fp)
        precision = np.r_[prec_curve[0], prec_curve]
        return float(_trapezoid(precision, recall))


# numpy renamed trapz -> trapezoid in 2.0; pyproject leaves numpy unpinned
_trapezoid = getattr(np, "trapezoid", None) or np.trapz
