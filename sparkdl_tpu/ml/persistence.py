"""ML-object persistence: ``stage.save(dir)`` / ``load(dir)``.

Parity: the reference round-tripped fitted models through Spark ML's
MLWritable/MLReadable (metadata JSON + model artifacts; Keras HDF5 inside
the estimator, SURVEY.md §3.3/§5.4). TPU-native artifact formats:

- **ModelFunction-backed stages** (fitted estimator models, generic
  transformers, Keras transformers): the model is serialized via
  ``ModelFunction.toJaxExport`` — StableHLO with the (trained) weights
  baked in, runnable at load time WITHOUT the original Python model class
  (the reference's frozen-graph analog). Batch dim exports symbolically so
  the reloaded stage serves any batch size.
- **Named-model stages** (DeepImageFeaturizer/Predictor): weights msgpack +
  the model name; the architecture is rebuilt from the in-repo zoo.
- **PipelineModel**: one subdirectory per stage, recursively.

Layout: ``<dir>/metadata.json`` ({class, params, artifacts}) plus artifact
files. Runtime-only params are NOT persisted: ``mesh`` (a device resource;
the process default mesh applies after load) — and a custom ``imageLoader``
callable raises at save time, as Spark did for non-serializable params.

``sparkdl_tpu.ml.load(dir)`` dispatches on the saved class name.
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any, Dict, Optional

_METADATA = "metadata.json"
_MODEL_EXPORT = "model_fn.stablehlo"
_WEIGHTS = "weights.msgpack"

# Only classes registered here can be loaded — a guard against metadata
# injection pointing at arbitrary importables.
_LOADABLE = {
    "sparkdl_tpu.ml.named_image.DeepImageFeaturizer",
    "sparkdl_tpu.ml.named_image.DeepImagePredictor",
    "sparkdl_tpu.ml.image_transformer.TPUImageTransformer",
    "sparkdl_tpu.ml.tensor_transformer.TPUTransformer",
    "sparkdl_tpu.ml.keras_image.KerasImageFileTransformer",
    "sparkdl_tpu.ml.keras_tensor.KerasTransformer",
    "sparkdl_tpu.ml.classification.LogisticRegression",
    "sparkdl_tpu.ml.classification.LogisticRegressionModel",
    "sparkdl_tpu.ml.estimator.KerasImageFileEstimator",
    "sparkdl_tpu.ml.estimator.KerasImageFileModel",
    "sparkdl_tpu.ml.base.Pipeline",
    "sparkdl_tpu.ml.base.PipelineModel",
    "sparkdl_tpu.ml.feature.StringIndexer",
    "sparkdl_tpu.ml.feature.StringIndexerModel",
    "sparkdl_tpu.ml.feature.IndexToString",
    "sparkdl_tpu.ml.feature.VectorAssembler",
    "sparkdl_tpu.ml.feature.OneHotEncoder",
    "sparkdl_tpu.ml.feature.StandardScaler",
    "sparkdl_tpu.ml.feature.StandardScalerModel",
    "sparkdl_tpu.ml.feature.MinMaxScaler",
    "sparkdl_tpu.ml.feature.MinMaxScalerModel",
    "sparkdl_tpu.ml.feature.Imputer",
    "sparkdl_tpu.ml.feature.Normalizer",
    "sparkdl_tpu.ml.feature.Binarizer",
    "sparkdl_tpu.ml.feature.SQLTransformer",
    "sparkdl_tpu.ml.feature.ImputerModel",
    "sparkdl_tpu.ml.regression.LinearRegression",
    "sparkdl_tpu.ml.regression.LinearRegressionModel",
    "sparkdl_tpu.ml.evaluation.MulticlassClassificationEvaluator",
    "sparkdl_tpu.ml.evaluation.RegressionEvaluator",
    "sparkdl_tpu.ml.evaluation.BinaryClassificationEvaluator",
    "sparkdl_tpu.ml.tuning.CrossValidator",
    "sparkdl_tpu.ml.tuning.CrossValidatorModel",
    "sparkdl_tpu.ml.tuning.TrainValidationSplit",
    "sparkdl_tpu.ml.tuning.TrainValidationSplitModel",
}


def class_path(obj) -> str:
    cls = type(obj)
    return f"{cls.__module__}.{cls.__qualname__}"


def write_metadata(path: str, instance, params: Dict[str, Any],
                   artifacts: Optional[Dict[str, str]] = None) -> None:
    os.makedirs(path, exist_ok=True)
    meta = {
        "class": class_path(instance),
        "params": params,
        "artifacts": artifacts or {},
        "format_version": 1,
    }
    with open(os.path.join(path, _METADATA), "w") as f:
        json.dump(meta, f, indent=1)


def read_metadata(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, _METADATA)) as f:
        return json.load(f)


def jsonable_params(instance, skip=("mesh",)) -> Dict[str, Any]:
    """Explicitly-set + defaulted params that JSON-serialize, by name.

    A non-JSON value on an *explicitly set* param raises: dropping it
    silently would reload the stage with different behavior. Unset defaults
    that fail (a future complex-valued default) are skipped — the class
    restores them on construction.
    """
    out: Dict[str, Any] = {}
    for param in instance.params:
        if param.name in skip:
            continue
        if not instance.isDefined(param):
            continue
        value = instance.getOrDefault(param)
        try:
            json.dumps(value)
        except TypeError:
            if instance.isSet(param):
                raise ValueError(
                    f"Param {param.name!r}={value!r} is not JSON-"
                    "serializable and would be silently lost on save; "
                    "clear it or add it to the stage's _persist_skip "
                    "(with a matching artifact) to persist this stage")
            continue
        out[param.name] = value
    return out


def dtype_name(dtype) -> Optional[str]:
    if dtype is None:
        return None
    import numpy as np

    return np.dtype(dtype).name


def save_model_function(mf, path: str) -> str:
    """ModelFunction → StableHLO artifact (weights baked in).

    The batch dim exports symbolically so the reloaded stage serves any
    batch size; a program that rejects symbolic shapes cannot round-trip
    (a fixed-batch artifact would fail at transform time on every other
    bucket shape), so that raises HERE, at save, where it is debuggable.
    """
    target = os.path.join(path, _MODEL_EXPORT)
    try:
        mf.toJaxExport(target)  # symbolic batch dim
    except Exception as e:
        raise ValueError(
            f"Model {mf.name!r} does not export with a symbolic batch "
            "dimension and therefore cannot be saved as a serve-any-batch "
            f"artifact: {e}") from e
    return _MODEL_EXPORT


def load_model_function(path: str, artifact: str, name: str = "loaded"):
    from sparkdl_tpu.core.model_function import ModelFunction

    return ModelFunction.fromJaxExport(os.path.join(path, artifact), name=name)


def save_weights_msgpack(variables, path: str) -> str:
    import flax.serialization as fser

    with open(os.path.join(path, _WEIGHTS), "wb") as f:
        f.write(fser.to_bytes(variables))
    return _WEIGHTS


def save_keras_artifact(instance, path: str) -> Optional[str]:
    """Persist an unfitted stage's Keras model payload into ``path``.

    The saved directory is self-contained (VERDICT r3 #6): an in-memory
    ``model`` serializes via Keras's own format; a ``modelFile`` path is
    copied in (keeping its suffix so ``load_keras_file`` dispatches the
    same way). Returns the artifact filename, or None when the stage
    carries no model params.
    """
    import shutil

    model = instance.getModel() if hasattr(instance, "getModel") else None
    if model is not None:
        name = "keras_model.keras"
        model.save(os.path.join(path, name))
        return name
    model_file = (instance.getModelFile()
                  if hasattr(instance, "getModelFile") else None)
    if model_file is not None:
        ext = os.path.splitext(model_file)[1] or ".keras"
        name = "keras_model" + ext
        shutil.copyfile(model_file, os.path.join(path, name))
        return name
    return None


def check_no_custom_loader(instance) -> None:
    getter = getattr(instance, "getImageLoader", None)
    if getter is not None and getter() is not None:
        raise ValueError(
            "Cannot save a stage with a custom imageLoader callable; "
            "clear it (setImageLoader(None)) and re-apply after load")


class ModelFunctionPersistence:
    """save/_load_from for stages whose payload is one ModelFunction.

    Subclasses set ``_persist_skip`` (params excluded from metadata; mesh
    and runtime-only values), ``_persist_check_loader`` (True for stages
    carrying a CanLoadImage callable), and implement
    ``_persist_model_function()`` / ``_restore_model_function(mf)``.
    """

    # mesh is runtime-only; modelFunction is the artifact itself
    _persist_skip = ("mesh", "modelFunction")
    _persist_check_loader = False
    _persist_name = "model"

    def _persist_model_function(self):
        return self.getModelFunction()

    def _restore_model_function(self, mf) -> None:
        self._set(modelFunction=mf)

    def save(self, path: str) -> None:
        if self._persist_check_loader:
            check_no_custom_loader(self)
        os.makedirs(path, exist_ok=True)
        params = jsonable_params(self, skip=self._persist_skip)
        artifacts = {"model": save_model_function(
            self._persist_model_function(), path)}
        write_metadata(path, self, params, artifacts)

    @classmethod
    def _load_from(cls, path: str, meta):
        mf = load_model_function(path, meta["artifacts"]["model"],
                                 name=cls._persist_name)
        inst = cls(**meta["params"])
        inst._restore_model_function(mf)
        return inst


class ParamsOnlyPersistence:
    """save/_load_from for stages whose whole state is their params
    (evaluators, simple unfitted estimators): metadata JSON, no artifacts."""

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        write_metadata(path, self, jsonable_params(self), {})

    @classmethod
    def _load_from(cls, path: str, meta):
        return cls(**meta["params"])


def save_stage_dirs(instance, stages, path: str) -> None:
    """Shared layout for Pipeline/PipelineModel: one subdir per stage."""
    os.makedirs(path, exist_ok=True)
    stage_dirs = []
    for i, stage in enumerate(stages):
        if not hasattr(stage, "save"):
            raise ValueError(
                f"Pipeline stage {i} ({type(stage).__name__}) does not "
                "support save()")
        sub = f"stage_{i:03d}_{type(stage).__name__}"
        stage.save(os.path.join(path, sub))
        stage_dirs.append(sub)
    write_metadata(path, instance, {"stage_dirs": stage_dirs}, {})


def load_stage_dirs(path: str, meta):
    return [load(os.path.join(path, sub))
            for sub in meta["params"]["stage_dirs"]]


def load(path: str):
    """Load any saved stage (``sparkdl_tpu.ml.load`` public entry point)."""
    meta = read_metadata(path)
    cls_path = meta["class"]
    if cls_path not in _LOADABLE:
        raise ValueError(f"Refusing to load unknown class {cls_path!r}")
    module_name, _, cls_name = cls_path.rpartition(".")
    cls = getattr(importlib.import_module(module_name), cls_name)
    return cls._load_from(path, meta)
