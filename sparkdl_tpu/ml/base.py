"""Pipeline abstractions: Transformer / Estimator / Model / Pipeline.

Parity: Spark ML's ``pyspark.ml.base`` + ``pyspark.ml.pipeline`` semantics,
which the reference's whole L4 surface subclasses (SURVEY.md §1). The
semantics reproduced faithfully (SURVEY.md §7 "hard parts" #4):

- ``fit(df)`` / ``fit(df, paramMap)`` / ``fit(df, [paramMap, ...])`` — a
  list of maps trains one model per map (task-parallel HPO, §2.4).
- ``fitMultiple(df, paramMaps)`` returns a thread-safe iterator of
  ``(index, model)`` — indices may complete out of order.
- ``transform(df, paramMap)`` applies overrides to a *copy*; the receiver
  is never mutated.
- ``Pipeline(stages=[...])`` fits estimator stages on the running
  intermediate frame and returns a ``PipelineModel`` of transformers.

Everything operates on the engine's Arrow DataFrame (sparkdl_tpu.engine).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple, Union

from sparkdl_tpu.param.base import Param, Params, keyword_only

ParamMap = Dict[Param, Any]


class Transformer(Params):
    """A fit-free stage: ``transform(df) -> df`` with a new column."""

    def _transform(self, dataset):
        raise NotImplementedError

    def transform(self, dataset, params: Optional[ParamMap] = None):
        if params is None:
            return self._transform(dataset)
        if isinstance(params, dict):
            return self.copy(params)._transform(dataset)
        raise TypeError(f"params must be a param map dict, got {type(params)}")


class Estimator(Params):
    """A trainable stage: ``fit(df) -> Model``."""

    def _fit(self, dataset) -> "Model":
        raise NotImplementedError

    def fit(self, dataset, params: Optional[Union[ParamMap, Sequence[ParamMap]]] = None):
        if params is None:
            return self._fit(dataset)
        if isinstance(params, dict):
            return self.copy(params)._fit(dataset)
        if isinstance(params, (list, tuple)):
            models: List[Optional[Model]] = [None] * len(params)
            for index, model in self.fitMultiple(dataset, params):
                models[index] = model
            return models
        raise TypeError(
            f"params must be a param map or a list/tuple of them, got {type(params)}")

    def fitMultiple(self, dataset, paramMaps: Sequence[ParamMap]
                    ) -> Iterator[Tuple[int, "Model"]]:
        """Iterator of ``(index, model)``; safe to drain from threads.

        Parity: ``pyspark.ml.Estimator.fitMultiple`` (the reference's HPO
        mechanism, SURVEY.md §3.3). The default fits lazily on ``next()``;
        subclasses override to share work (e.g. decode images once).
        """
        estimator = self.copy()

        class _FitMultipleIterator:
            def __init__(self) -> None:
                self._lock = threading.Lock()
                self._next = 0

            def __iter__(self):
                return self

            def __next__(self) -> Tuple[int, Model]:
                with self._lock:
                    index = self._next
                    if index >= len(paramMaps):
                        raise StopIteration
                    self._next += 1
                return index, estimator.fit(dataset, paramMaps[index])

        return _FitMultipleIterator()


class Model(Transformer):
    """A Transformer produced by an Estimator; tracks its parent."""

    parent: Optional[Estimator] = None

    def _set_parent(self, parent: Estimator) -> "Model":
        self.parent = parent
        return self


class Pipeline(Estimator):
    """Ordered stages; estimator stages are fit on the running frame.

    Parity: ``pyspark.ml.Pipeline`` — the container the reference's
    README-level examples put ``DeepImageFeaturizer`` into (ahead of a
    LogisticRegression).
    """

    stages = Param("Pipeline", "stages", "pipeline stages (Transformer/Estimator)")

    @keyword_only
    def __init__(self, *, stages: Optional[List[Params]] = None) -> None:
        super().__init__()
        self._set(stages=stages or [])

    def setStages(self, value: List[Params]) -> "Pipeline":
        return self._set(stages=value)

    def getStages(self) -> List[Params]:
        return self.getOrDefault(self.stages)

    def _fit(self, dataset) -> "PipelineModel":
        stages = self.getStages()
        for stage in stages:
            if not isinstance(stage, (Transformer, Estimator)):
                raise TypeError(
                    f"Pipeline stage must be Estimator or Transformer, got {stage!r}")
        # Frames after the last estimator need no materialization: later
        # transformers only run at PipelineModel.transform time.
        last_estimator = -1
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                last_estimator = i
        fitted: List[Transformer] = []
        frame = dataset
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(frame)
                fitted.append(model)
                if i < last_estimator:
                    frame = model.transform(frame)
            else:
                fitted.append(stage)
                if i < last_estimator:
                    frame = stage.transform(frame)
        return PipelineModel(fitted)._set_parent(self)

    def copy(self, extra: Optional[ParamMap] = None) -> "Pipeline":
        # extra fans out to every stage; each stage's copy keeps only the
        # params it owns (pyspark Pipeline.copy semantics — this is how one
        # param map addresses individual stages during HPO).
        that = super().copy(extra)
        that._set(stages=[
            s.copy(extra) if isinstance(s, Params) else s
            for s in that.getStages()])
        return that

    # -- persistence: unfitted pipeline (VERDICT r3 #6) ----------------------

    def save(self, path: str) -> None:
        """Persist the UNFITTED pipeline — one subdirectory per stage
        (transformers and unfitted estimators alike), so a training
        pipeline can be saved, reloaded, and then fit (Spark MLWritable
        covered unfitted Pipelines too, SURVEY.md §2.1)."""
        from sparkdl_tpu.ml import persistence as P

        P.save_stage_dirs(self, self.getStages(), path)

    @classmethod
    def _load_from(cls, path: str, meta):
        from sparkdl_tpu.ml import persistence as P

        return cls(stages=P.load_stage_dirs(path, meta))


class PipelineModel(Model):
    """The fitted pipeline: a chain of transformers."""

    def __init__(self, stages: List[Transformer]) -> None:
        super().__init__()
        self.stages = stages

    def _transform(self, dataset):
        frame = dataset
        for stage in self.stages:
            frame = stage.transform(frame)
        return frame

    def copy(self, extra: Optional[ParamMap] = None) -> "PipelineModel":
        that = PipelineModel([s.copy(extra) for s in self.stages])
        that.parent = self.parent
        return that

    # -- persistence: one subdirectory per stage -----------------------------

    def save(self, path: str) -> None:
        from sparkdl_tpu.ml import persistence as P

        P.save_stage_dirs(self, self.stages, path)

    @classmethod
    def _load_from(cls, path: str, meta):
        from sparkdl_tpu.ml import persistence as P

        return cls(P.load_stage_dirs(path, meta))
