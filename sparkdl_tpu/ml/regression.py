"""LinearRegression — the regression-side downstream learner.

Parity: Spark ML's ``LinearRegression`` is the other classical consumer
of a featurizer's vector column (and the estimator the shipped
``RegressionEvaluator`` exists to score). Spark's parity envelope,
mirroring ``classification.LogisticRegression``:

================== =====================================================
matches Spark      ``featuresCol/labelCol/predictionCol``, ``regParam``
                   (L2), ``fitIntercept``, ``standardization`` (fit in
                   unit-std space, coefficients reported on the original
                   scale, intercept unpenalized), ``weightCol``
                   (weight 2 == duplicating the row).
differs            solved in CLOSED FORM, exactly — a float64
                   augmented least-squares on the host (``maxIter/tol``
                   therefore do not exist). Deliberately NOT a device
                   solve: jax computes f32 unless the global x64 flag is
                   set, and normal equations square the condition
                   number, so an f32 "exact" solve on correlated
                   2048-dim deep features would be exact in name only.
                   The one-shot d×d solve is host-cheap; lstsq also
                   returns the MIN-NORM solution for rank-deficient
                   problems (n < d transfer-learning fits) instead of
                   silently emitting NaN coefficients.
absent             ``elasticNetParam`` (L1 needs an iterative prox
                   solver), ``solver``, ``aggregationDepth``.
================== =====================================================

Objective (Spark's): minimize ``1/(2·Σwᵢ) Σ wᵢ(yᵢ - xᵢ·β - b)² +
(regParam/2)·||β||²`` — solved as the augmented least-squares
``[√W·X̃; √(λ·Σw)·I] β ≈ [√W·ỹ; 0]`` on (weighted-)centered data when
fitting an intercept.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from sparkdl_tpu.engine.dataframe import list_column_to_numpy
from sparkdl_tpu.ml.base import Estimator, Model
from sparkdl_tpu.ml.linear_utils import validate_weights, weighted_feature_std
from sparkdl_tpu.ml.persistence import ParamsOnlyPersistence
from sparkdl_tpu.param.base import Param, Params, keyword_only
from sparkdl_tpu.param.converters import SparkDLTypeConverters, TypeConverters
from sparkdl_tpu.param.shared_params import HasLabelCol


class _HasRegressionCols(HasLabelCol):
    featuresCol = Param("_HasRegressionCols", "featuresCol",
                        "input column of fixed-length float vectors",
                        typeConverter=SparkDLTypeConverters.toColumnName)
    predictionCol = Param("_HasRegressionCols", "predictionCol",
                          "output column: predicted value",
                          typeConverter=SparkDLTypeConverters.toColumnName)

    def setFeaturesCol(self, value): return self._set(featuresCol=value)

    def getFeaturesCol(self): return self.getOrDefault(self.featuresCol)

    def setPredictionCol(self, value): return self._set(predictionCol=value)

    def getPredictionCol(self): return self.getOrDefault(self.predictionCol)


class LinearRegression(Estimator, _HasRegressionCols, ParamsOnlyPersistence):
    """Weighted ridge regression on a vector column (closed form)."""

    regParam = Param("LinearRegression", "regParam",
                     "L2 regularization strength (0 disables)",
                     typeConverter=TypeConverters.toFloat)
    fitIntercept = Param("LinearRegression", "fitIntercept",
                         "whether to fit an intercept term",
                         typeConverter=TypeConverters.toBoolean)
    standardization = Param(
        "LinearRegression", "standardization",
        "scale features to unit std before solving (Spark default True; "
        "coefficients are always reported on the original scale)",
        typeConverter=TypeConverters.toBoolean)
    weightCol = Param(
        "LinearRegression", "weightCol",
        "optional column of non-negative row weights",
        typeConverter=SparkDLTypeConverters.toColumnName)

    @keyword_only
    def __init__(self, *, featuresCol: str = "features",
                 labelCol: str = "label",
                 predictionCol: str = "prediction",
                 regParam: float = 0.0,
                 fitIntercept: bool = True,
                 standardization: bool = True,
                 weightCol: Optional[str] = None) -> None:
        super().__init__()
        self._setDefault(featuresCol="features", labelCol="label",
                         predictionCol="prediction", regParam=0.0,
                         fitIntercept=True, standardization=True)
        self._set(**self._input_kwargs)

    def setRegParam(self, value): return self._set(regParam=value)

    def getRegParam(self): return self.getOrDefault(self.regParam)

    def setFitIntercept(self, value): return self._set(fitIntercept=value)

    def getFitIntercept(self): return self.getOrDefault(self.fitIntercept)

    def getStandardization(self):
        return self.getOrDefault(self.standardization)

    def getWeightCol(self):
        return (self.getOrDefault(self.weightCol)
                if self.isDefined(self.weightCol) else None)

    def _collect_xyw(self, dataset):
        weight_col = self.getWeightCol()
        cols = [self.getFeaturesCol(), self.getLabelCol()]
        if weight_col is not None:
            cols.append(weight_col)
        rows = dataset.select(*cols).collect()
        feats, labels, weights = [], [], []
        for r in rows:
            f = r[self.getFeaturesCol()]
            lab = r[self.getLabelCol()]
            if f is None or lab is None:
                continue
            feats.append(np.asarray(f, np.float64))
            labels.append(float(lab))
            if weight_col is not None:
                w = r[weight_col]
                weights.append(1.0 if w is None else float(w))
        if not feats:
            raise ValueError("no non-null (features, label) rows to fit on")
        x = np.stack(feats)
        y = np.asarray(labels, np.float64)
        w = None
        if weight_col is not None:
            w = validate_weights(np.asarray(weights, np.float64),
                                 weight_col)
        return x, y, w

    def _fit(self, dataset) -> "LinearRegressionModel":
        x, y, w = self._collect_xyw(dataset)
        std = None
        if self.getStandardization() and len(x) > 1:
            std = weighted_feature_std(x, w)
            x = x / std
        beta, intercept = _solve_ridge(
            x, y, w, reg=self.getRegParam(),
            fit_intercept=self.getFitIntercept())
        beta = np.asarray(beta, np.float64)
        if std is not None:
            beta = beta / std
        model = LinearRegressionModel(
            featuresCol=self.getFeaturesCol(), labelCol=self.getLabelCol(),
            predictionCol=self.getPredictionCol())
        model._set_weights(beta, float(intercept))
        model._set_parent(self)
        return model


def _solve_ridge(x: np.ndarray, y: np.ndarray, w: Optional[np.ndarray],
                 reg: float, fit_intercept: bool):
    """Float64 augmented least-squares (see the module docstring for why
    this is a host numpy solve, not a device one): lstsq on
    ``[√W·X̃; √(λ·Σw)·I]`` avoids squaring the condition number and
    returns the min-norm solution when the problem is rank-deficient
    (n < d) instead of NaN."""
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    wv = np.ones_like(y) if w is None else np.asarray(w, np.float64)
    wsum = float(wv.sum())
    if wsum <= 0:
        raise ValueError("sum of sample weights must be positive")
    if fit_intercept:
        x_mean = (wv[:, None] * x).sum(0) / wsum
        y_mean = float((wv * y).sum() / wsum)
        xc = x - x_mean
        yc = y - y_mean
    else:
        xc, yc = x, y
    sw = np.sqrt(wv)[:, None]
    a = xc * sw
    b = yc * np.sqrt(wv)
    if reg > 0:
        d = x.shape[1]
        a = np.vstack([a, np.sqrt(reg * wsum) * np.eye(d)])
        b = np.concatenate([b, np.zeros(d)])
    beta = np.linalg.lstsq(a, b, rcond=None)[0]
    if fit_intercept:
        return beta, y_mean - float(x_mean @ beta)
    return beta, 0.0


class LinearRegressionModel(Model, _HasRegressionCols):
    """Fitted model: adds a prediction column."""

    @keyword_only
    def __init__(self, *, featuresCol: str = "features",
                 labelCol: str = "label",
                 predictionCol: str = "prediction") -> None:
        super().__init__()
        self._setDefault(featuresCol="features", labelCol="label",
                         predictionCol="prediction")
        self._set(**self._input_kwargs)

    def _set_weights(self, beta: np.ndarray, intercept: float) -> None:
        self._beta = np.asarray(beta, np.float64)
        self._intercept = float(intercept)

    @property
    def coefficients(self) -> np.ndarray:
        return self._beta

    @property
    def intercept(self) -> float:
        return self._intercept

    def _transform(self, dataset):
        import pyarrow as pa

        beta, b = self._beta, self._intercept
        feat_col = self.getFeaturesCol()

        def predict_batch(batch: "pa.RecordBatch") -> "pa.Array":
            col = batch.column(batch.schema.get_field_index(feat_col))
            # columnar hoist: uniform vector column → one (n, K) view
            n_rows = len(col)
            x = list_column_to_numpy(col)
            if x is not None:
                valid = np.flatnonzero(col.is_valid()).tolist()
            else:
                # sparkdl: allow(columnar-hot-path): ragged fallback —
                # uniform vector batches take the hoist above
                rows = col.to_pylist()
                valid = [i for i, r in enumerate(rows) if r is not None]
                x = np.asarray([rows[i] for i in valid], np.float64)
            out = [None] * n_rows
            if valid:
                # one matmul per Arrow batch, not a dot per row
                preds = np.asarray(x, np.float64) @ beta + b
                for j, i in enumerate(valid):
                    out[i] = float(preds[j])
            return pa.array(out, type=pa.float64())

        return dataset.withColumnBatch(self.getPredictionCol(),
                                       predict_batch,
                                       outputType=pa.float64())

    # -- persistence ---------------------------------------------------------

    def save(self, path: str) -> None:
        import os

        from sparkdl_tpu.ml import persistence as P

        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, "weights.npz"), beta=self._beta,
                 intercept=np.asarray(self._intercept))
        P.write_metadata(path, self, P.jsonable_params(self),
                         {"weights": "weights.npz"})

    @classmethod
    def _load_from(cls, path: str, meta):
        import os

        inst = cls(**meta["params"])
        data = np.load(os.path.join(path, meta["artifacts"]["weights"]))
        inst._set_weights(data["beta"], float(data["intercept"]))
        return inst
