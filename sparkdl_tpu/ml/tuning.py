"""Model selection: ParamGridBuilder, CrossValidator, TrainValidationSplit.

Parity: the reference's documented HPO workflow wrapped
``KerasImageFileEstimator`` in **Spark ML's** CrossValidator (upstream
README: "used with CrossValidator for hyperparameter search"). The
rebuild ships the same three classes with Spark's semantics:

- ``ParamGridBuilder().addGrid(p, values).build()`` → the cartesian list
  of param maps.
- ``CrossValidator``: k seeded folds (``DataFrame.randomSplit``); per
  fold, ALL maps fit through the estimator's ``fitMultiple`` (which
  shares one decode pass — and, via the ModelFunction step cache, one
  compiled train step); metrics average across folds; the best map
  refits on the full dataset.
- ``TrainValidationSplit``: the single-split variant.

Both produce a model wrapper exposing ``bestModel`` + the per-map
metrics, transforming with the best model.
"""

from __future__ import annotations

import concurrent.futures as _futures
import itertools
import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from sparkdl_tpu.ml.base import Estimator, Model
from sparkdl_tpu.ml.evaluation import Evaluator
from sparkdl_tpu.param.base import Param, Params, keyword_only
from sparkdl_tpu.param.converters import TypeConverters

ParamMap = Dict[Param, Any]


class ParamGridBuilder:
    """Cartesian param-map grid (Spark's builder API)."""

    def __init__(self) -> None:
        self._grid: Dict[Param, Sequence[Any]] = {}

    def addGrid(self, param: Param, values: Sequence[Any]
                ) -> "ParamGridBuilder":
        if not isinstance(param, Param):
            raise TypeError(f"addGrid needs a Param, got {type(param)}")
        if not values:
            raise ValueError(f"empty value list for {param.name}")
        self._grid[param] = list(values)
        return self

    def baseOn(self, *args) -> "ParamGridBuilder":
        """Fixed (param, value) pairs applied to every map."""
        pairs = args[0].items() if len(args) == 1 and isinstance(
            args[0], dict) else args
        for param, value in pairs:
            self.addGrid(param, [value])
        return self

    def build(self) -> List[ParamMap]:
        params = list(self._grid)
        if not params:
            return [{}]
        combos = itertools.product(*(self._grid[p] for p in params))
        return [dict(zip(params, combo)) for combo in combos]


class _ValidatorParams(Params):
    seed = Param("_ValidatorParams", "seed", "fold/split seed",
                 typeConverter=TypeConverters.toInt)
    parallelism = Param(
        "_ValidatorParams", "parallelism",
        "number of threads draining fitMultiple concurrently (Spark's "
        "CrossValidator.parallelism; default 1 = serial). The estimator's "
        "fitMultiple iterator is thread-safe by contract, so concurrent "
        "maps overlap host-side decode/eval with device train steps",
        typeConverter=TypeConverters.toInt)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(seed=0, parallelism=1)
        self.estimator: Optional[Estimator] = None
        self.evaluator: Optional[Evaluator] = None
        self.estimatorParamMaps: List[ParamMap] = []

    def setSeed(self, value):
        return self._set(seed=value)

    def getSeed(self):
        return self.getOrDefault(self.seed)

    def setParallelism(self, value):
        return self._set(parallelism=value)

    def getParallelism(self):
        return self.getOrDefault(self.parallelism)

    def _check_configured(self) -> None:
        if self.estimator is None or self.evaluator is None:
            raise ValueError(
                f"{type(self).__name__} needs estimator= and evaluator=")
        if not self.estimatorParamMaps:
            raise ValueError(
                f"{type(self).__name__} needs a non-empty "
                "estimatorParamMaps (ParamGridBuilder().build())")

    def _fit_and_score(self, train, val) -> List[float]:
        """Fit every map on ``train`` (shared-work fitMultiple) and score
        its model on ``val``; ``parallelism`` worker threads drain the
        thread-safe iterator concurrently (scores land by index, so the
        result is identical to serial draining)."""
        import jax

        maps = self.estimatorParamMaps
        scores: List[Optional[float]] = [None] * len(maps)
        models = self.estimator.fitMultiple(train, maps)
        multihost = jax.process_count() > 1

        def drain() -> None:
            while True:
                try:
                    index, model = next(models)
                except StopIteration:
                    return
                out = model.transform(val)
                if multihost and out._process_shard is not None:
                    # transform auto-shards per process; every host must
                    # score the FULL validation output or _best_index can
                    # diverge across hosts (and with it the refit).
                    # Models that don't shard (e.g. LogisticRegression's
                    # host-side transform) already return the full frame.
                    out = out.gatherProcesses()
                scores[index] = float(self.evaluator.evaluate(out))

        n_threads = min(max(1, self.getParallelism()), len(maps))
        if multihost:
            # collectives (gather, multi-host fit steps) must issue in
            # the same order on every process; concurrent draining would
            # interleave them nondeterministically
            n_threads = 1
        if n_threads == 1:
            drain()
        else:
            with _futures.ThreadPoolExecutor(
                    n_threads, thread_name_prefix="sparkdl-tune") as pool:
                for f in [pool.submit(drain) for _ in range(n_threads)]:
                    f.result()
        return scores  # type: ignore[return-value]

    def _best_index(self, metrics: Sequence[float]) -> int:
        arr = np.asarray(metrics)
        return int(np.argmax(arr) if self.evaluator.isLargerBetter()
                   else np.argmin(arr))

    def _refit(self, dataset, best: int) -> Model:
        """Refit the winning map THROUGH fitMultiple so the final model
        trains under the same regime as the fold fits (ADVICE r4: a bare
        estimator.fit defaults streaming=True while fitMultiple's cache
        path defaults collected — selection and refit would silently use
        different shuffle semantics)."""
        model: Optional[Model] = None
        for _, fitted in self.estimator.fitMultiple(
                dataset, [self.estimatorParamMaps[best]]):
            model = fitted
        return model

    # -- persistence (Spark MLWritable parity for the tuning layer) ----------

    def _serializable_maps(self) -> List[Dict[str, Any]]:
        """Param maps as {param_name: value} dicts, resolvable against the
        estimator on load. Maps addressing params the estimator does not
        own (e.g. nested Pipeline-stage params) cannot round-trip by name
        and raise here, at save, where it is debuggable."""
        out = []
        for m in self.estimatorParamMaps:
            entry = {}
            for param, value in m.items():
                if not self.estimator.hasParam(param.name):
                    raise ValueError(
                        f"Cannot persist a param map addressing "
                        f"{param.name!r}: the estimator "
                        f"({type(self.estimator).__name__}) does not own "
                        "it (nested-stage param maps do not round-trip)")
                # Name alone is not identity (ADVICE r5): a foreign param
                # whose name collides with one of the estimator's would
                # serialize fine and silently REBIND to the estimator's
                # param on load — the grid would tune a different knob
                # than the one the user built. Require the map's param to
                # BE the estimator's param (Param equality is (parent uid,
                # name), i.e. instance identity for bound params).
                if param not in self.estimator.params:
                    raise ValueError(
                        f"Cannot persist a param map addressing "
                        f"{param!r}: its name collides with "
                        f"{self.estimator.getParam(param.name)!r} but it "
                        f"belongs to a different component — resolving by "
                        "name on load would silently rebind it")
                try:
                    json.dumps(value)
                except TypeError:
                    raise ValueError(
                        f"Param map value {param.name}={value!r} is not "
                        "JSON-serializable; the grid cannot be persisted")
                entry[param.name] = value
            out.append(entry)
        return out

    def _save_validator(self, path: str) -> None:
        from sparkdl_tpu.ml import persistence as P

        self._check_configured()
        if not hasattr(self.estimator, "save"):
            raise ValueError(
                f"estimator {type(self.estimator).__name__} does not "
                "support save()")
        if not hasattr(self.evaluator, "save"):
            raise ValueError(
                f"evaluator {type(self.evaluator).__name__} does not "
                "support save()")
        os.makedirs(path, exist_ok=True)
        params = P.jsonable_params(self)
        params["estimatorParamMaps"] = self._serializable_maps()
        self.estimator.save(os.path.join(path, "estimator"))
        self.evaluator.save(os.path.join(path, "evaluator"))
        P.write_metadata(path, self, params,
                         {"estimator": "estimator", "evaluator": "evaluator"})

    @classmethod
    def _load_validator(cls, path: str, meta):
        from sparkdl_tpu.ml import persistence as P

        params = dict(meta["params"])
        raw_maps = params.pop("estimatorParamMaps", [])
        estimator = P.load(os.path.join(path, meta["artifacts"]["estimator"]))
        evaluator = P.load(os.path.join(path, meta["artifacts"]["evaluator"]))
        maps = [{estimator.getParam(name): value
                 for name, value in m.items()} for m in raw_maps]
        return cls(estimator=estimator, evaluator=evaluator,
                   estimatorParamMaps=maps, **params)


class CrossValidator(Estimator, _ValidatorParams):
    """k-fold model selection over a param grid (Spark semantics)."""

    numFolds = Param("CrossValidator", "numFolds", "number of folds (>= 2)",
                     typeConverter=TypeConverters.toInt)

    @keyword_only
    def __init__(self, *, estimator: Optional[Estimator] = None,
                 estimatorParamMaps: Optional[List[ParamMap]] = None,
                 evaluator: Optional[Evaluator] = None,
                 numFolds: int = 3, seed: int = 0,
                 parallelism: int = 1) -> None:
        super().__init__()
        self._setDefault(numFolds=3)
        kwargs = self._input_kwargs
        self.estimator = kwargs.get("estimator")
        self.evaluator = kwargs.get("evaluator")
        self.estimatorParamMaps = list(kwargs.get("estimatorParamMaps") or [])
        self._set(numFolds=kwargs.get("numFolds", 3),
                  seed=kwargs.get("seed", 0),
                  parallelism=kwargs.get("parallelism", 1))

    def setNumFolds(self, value):
        return self._set(numFolds=value)

    def getNumFolds(self):
        return self.getOrDefault(self.numFolds)

    def _fit(self, dataset) -> "CrossValidatorModel":
        import pyarrow as pa

        from sparkdl_tpu.engine.dataframe import DataFrame

        self._check_configured()
        k = self.getNumFolds()
        if k < 2:
            raise ValueError(f"numFolds must be >= 2, got {k}")
        folds = dataset.randomSplit([1.0] * k, seed=self.getSeed())
        # Each fold materializes ONCE; per-fold train sets are zero-copy
        # Arrow concatenations of the other k-1 tables (VERDICT r4 weak #2:
        # the previous chained union re-materialized both sides per step,
        # copying the dataset O(k^2) times).
        tables = [f.toArrow() for f in folds]
        n_maps = len(self.estimatorParamMaps)
        totals = np.zeros(n_maps)
        for i in range(k):
            train = DataFrame.fromArrow(
                pa.concat_tables(t for j, t in enumerate(tables) if j != i),
                numPartitions=max(1, dataset.numPartitions))
            totals += np.asarray(self._fit_and_score(train, folds[i]))
        avg = (totals / k).tolist()
        best = self._best_index(avg)
        best_model = self._refit(dataset, best)
        model = CrossValidatorModel(best_model, avg, best)
        model._set_parent(self)
        return model

    def copy(self, extra=None):
        that = super().copy(extra)
        that.estimator = self.estimator
        that.evaluator = self.evaluator
        that.estimatorParamMaps = list(self.estimatorParamMaps)
        return that

    def save(self, path: str) -> None:
        """Persist the UNFITTED validator: estimator + evaluator as stage
        subdirs, the grid as named param values (Spark MLWritable
        parity for the tuning layer)."""
        self._save_validator(path)

    @classmethod
    def _load_from(cls, path: str, meta):
        return cls._load_validator(path, meta)


class TrainValidationSplit(Estimator, _ValidatorParams):
    """Single train/validation split model selection (Spark semantics)."""

    trainRatio = Param("TrainValidationSplit", "trainRatio",
                       "fraction of rows used for training (0, 1)",
                       typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, *, estimator: Optional[Estimator] = None,
                 estimatorParamMaps: Optional[List[ParamMap]] = None,
                 evaluator: Optional[Evaluator] = None,
                 trainRatio: float = 0.75, seed: int = 0,
                 parallelism: int = 1) -> None:
        super().__init__()
        self._setDefault(trainRatio=0.75)
        kwargs = self._input_kwargs
        self.estimator = kwargs.get("estimator")
        self.evaluator = kwargs.get("evaluator")
        self.estimatorParamMaps = list(kwargs.get("estimatorParamMaps") or [])
        self._set(trainRatio=kwargs.get("trainRatio", 0.75),
                  seed=kwargs.get("seed", 0),
                  parallelism=kwargs.get("parallelism", 1))

    def setTrainRatio(self, value):
        return self._set(trainRatio=value)

    def getTrainRatio(self):
        return self.getOrDefault(self.trainRatio)

    def _fit(self, dataset) -> "TrainValidationSplitModel":
        self._check_configured()
        ratio = self.getTrainRatio()
        if not 0.0 < ratio < 1.0:
            raise ValueError(f"trainRatio must be in (0, 1), got {ratio}")
        train, val = dataset.randomSplit([ratio, 1.0 - ratio],
                                         seed=self.getSeed())
        metrics = self._fit_and_score(train, val)
        best = self._best_index(metrics)
        best_model = self._refit(dataset, best)
        model = TrainValidationSplitModel(best_model, list(metrics), best)
        model._set_parent(self)
        return model

    def copy(self, extra=None):
        that = super().copy(extra)
        that.estimator = self.estimator
        that.evaluator = self.evaluator
        that.estimatorParamMaps = list(self.estimatorParamMaps)
        return that

    def save(self, path: str) -> None:
        """Persist the UNFITTED validator (see CrossValidator.save)."""
        self._save_validator(path)

    @classmethod
    def _load_from(cls, path: str, meta):
        return cls._load_validator(path, meta)


class _SelectionModel(Model):
    def __init__(self, best_model: Model, metrics: List[float],
                 best_index: int) -> None:
        super().__init__()
        self.bestModel = best_model
        self.bestIndex = best_index

    def _transform(self, dataset):
        return self.bestModel.transform(dataset)

    # -- persistence: metrics in metadata, bestModel as a stage subdir -------

    _metrics_key = "metrics"

    def save(self, path: str) -> None:
        from sparkdl_tpu.ml import persistence as P

        if not hasattr(self.bestModel, "save"):
            raise ValueError(
                f"bestModel {type(self.bestModel).__name__} does not "
                "support save()")
        os.makedirs(path, exist_ok=True)
        self.bestModel.save(os.path.join(path, "bestModel"))
        P.write_metadata(
            path, self,
            {self._metrics_key: [float(v) for v in self._metrics()],
             "bestIndex": int(self.bestIndex)},
            {"bestModel": "bestModel"})

    def _metrics(self) -> List[float]:
        raise NotImplementedError

    @classmethod
    def _load_from(cls, path: str, meta):
        from sparkdl_tpu.ml import persistence as P

        best = P.load(os.path.join(path, meta["artifacts"]["bestModel"]))
        return cls(best, list(meta["params"][cls._metrics_key]),
                   int(meta["params"]["bestIndex"]))


class CrossValidatorModel(_SelectionModel):
    """``bestModel`` + per-map ``avgMetrics`` (fold averages)."""

    def __init__(self, best_model: Model, avg_metrics: List[float],
                 best_index: int) -> None:
        super().__init__(best_model, avg_metrics, best_index)
        self.avgMetrics = avg_metrics

    def _metrics(self) -> List[float]:
        return self.avgMetrics


class TrainValidationSplitModel(_SelectionModel):
    """``bestModel`` + per-map ``validationMetrics``."""

    def __init__(self, best_model: Model, metrics: List[float],
                 best_index: int) -> None:
        super().__init__(best_model, metrics, best_index)
        self.validationMetrics = metrics

    def _metrics(self) -> List[float]:
        return self.validationMetrics
