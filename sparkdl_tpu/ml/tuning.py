"""Model selection: ParamGridBuilder, CrossValidator, TrainValidationSplit.

Parity: the reference's documented HPO workflow wrapped
``KerasImageFileEstimator`` in **Spark ML's** CrossValidator (upstream
README: "used with CrossValidator for hyperparameter search"). The
rebuild ships the same three classes with Spark's semantics:

- ``ParamGridBuilder().addGrid(p, values).build()`` → the cartesian list
  of param maps.
- ``CrossValidator``: k seeded folds (``DataFrame.randomSplit``); per
  fold, ALL maps fit through the estimator's ``fitMultiple`` (which
  shares one decode pass — and, via the ModelFunction step cache, one
  compiled train step); metrics average across folds; the best map
  refits on the full dataset.
- ``TrainValidationSplit``: the single-split variant.

Both produce a model wrapper exposing ``bestModel`` + the per-map
metrics, transforming with the best model.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from sparkdl_tpu.ml.base import Estimator, Model
from sparkdl_tpu.ml.evaluation import Evaluator
from sparkdl_tpu.param.base import Param, Params, keyword_only
from sparkdl_tpu.param.converters import TypeConverters

ParamMap = Dict[Param, Any]


class ParamGridBuilder:
    """Cartesian param-map grid (Spark's builder API)."""

    def __init__(self) -> None:
        self._grid: Dict[Param, Sequence[Any]] = {}

    def addGrid(self, param: Param, values: Sequence[Any]
                ) -> "ParamGridBuilder":
        if not isinstance(param, Param):
            raise TypeError(f"addGrid needs a Param, got {type(param)}")
        if not values:
            raise ValueError(f"empty value list for {param.name}")
        self._grid[param] = list(values)
        return self

    def baseOn(self, *args) -> "ParamGridBuilder":
        """Fixed (param, value) pairs applied to every map."""
        pairs = args[0].items() if len(args) == 1 and isinstance(
            args[0], dict) else args
        for param, value in pairs:
            self.addGrid(param, [value])
        return self

    def build(self) -> List[ParamMap]:
        params = list(self._grid)
        if not params:
            return [{}]
        combos = itertools.product(*(self._grid[p] for p in params))
        return [dict(zip(params, combo)) for combo in combos]


class _ValidatorParams(Params):
    seed = Param("_ValidatorParams", "seed", "fold/split seed",
                 typeConverter=TypeConverters.toInt)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(seed=0)
        self.estimator: Optional[Estimator] = None
        self.evaluator: Optional[Evaluator] = None
        self.estimatorParamMaps: List[ParamMap] = []

    def setSeed(self, value):
        return self._set(seed=value)

    def getSeed(self):
        return self.getOrDefault(self.seed)

    def _check_configured(self) -> None:
        if self.estimator is None or self.evaluator is None:
            raise ValueError(
                f"{type(self).__name__} needs estimator= and evaluator=")
        if not self.estimatorParamMaps:
            raise ValueError(
                f"{type(self).__name__} needs a non-empty "
                "estimatorParamMaps (ParamGridBuilder().build())")

    def _fit_and_score(self, train, val) -> List[float]:
        """Fit every map on ``train`` (shared-work fitMultiple) and score
        its model on ``val``."""
        maps = self.estimatorParamMaps
        scores: List[Optional[float]] = [None] * len(maps)
        for index, model in self.estimator.fitMultiple(train, maps):
            scores[index] = float(
                self.evaluator.evaluate(model.transform(val)))
        return scores  # type: ignore[return-value]

    def _best_index(self, metrics: Sequence[float]) -> int:
        arr = np.asarray(metrics)
        return int(np.argmax(arr) if self.evaluator.isLargerBetter()
                   else np.argmin(arr))


class CrossValidator(Estimator, _ValidatorParams):
    """k-fold model selection over a param grid (Spark semantics)."""

    numFolds = Param("CrossValidator", "numFolds", "number of folds (>= 2)",
                     typeConverter=TypeConverters.toInt)

    @keyword_only
    def __init__(self, *, estimator: Optional[Estimator] = None,
                 estimatorParamMaps: Optional[List[ParamMap]] = None,
                 evaluator: Optional[Evaluator] = None,
                 numFolds: int = 3, seed: int = 0) -> None:
        super().__init__()
        self._setDefault(numFolds=3)
        kwargs = self._input_kwargs
        self.estimator = kwargs.get("estimator")
        self.evaluator = kwargs.get("evaluator")
        self.estimatorParamMaps = list(kwargs.get("estimatorParamMaps") or [])
        self._set(numFolds=kwargs.get("numFolds", 3),
                  seed=kwargs.get("seed", 0))

    def setNumFolds(self, value):
        return self._set(numFolds=value)

    def getNumFolds(self):
        return self.getOrDefault(self.numFolds)

    def _fit(self, dataset) -> "CrossValidatorModel":
        self._check_configured()
        k = self.getNumFolds()
        if k < 2:
            raise ValueError(f"numFolds must be >= 2, got {k}")
        folds = dataset.randomSplit([1.0] * k, seed=self.getSeed())
        n_maps = len(self.estimatorParamMaps)
        totals = np.zeros(n_maps)
        for i in range(k):
            train = None
            for j, fold in enumerate(folds):
                if j == i:
                    continue
                train = fold if train is None else train.union(fold)
            totals += np.asarray(self._fit_and_score(train, folds[i]))
        avg = (totals / k).tolist()
        best = self._best_index(avg)
        best_model = self.estimator.fit(dataset,
                                        self.estimatorParamMaps[best])
        model = CrossValidatorModel(best_model, avg, best)
        model._set_parent(self)
        return model

    def copy(self, extra=None):
        that = super().copy(extra)
        that.estimator = self.estimator
        that.evaluator = self.evaluator
        that.estimatorParamMaps = list(self.estimatorParamMaps)
        return that


class TrainValidationSplit(Estimator, _ValidatorParams):
    """Single train/validation split model selection (Spark semantics)."""

    trainRatio = Param("TrainValidationSplit", "trainRatio",
                       "fraction of rows used for training (0, 1)",
                       typeConverter=TypeConverters.toFloat)

    @keyword_only
    def __init__(self, *, estimator: Optional[Estimator] = None,
                 estimatorParamMaps: Optional[List[ParamMap]] = None,
                 evaluator: Optional[Evaluator] = None,
                 trainRatio: float = 0.75, seed: int = 0) -> None:
        super().__init__()
        self._setDefault(trainRatio=0.75)
        kwargs = self._input_kwargs
        self.estimator = kwargs.get("estimator")
        self.evaluator = kwargs.get("evaluator")
        self.estimatorParamMaps = list(kwargs.get("estimatorParamMaps") or [])
        self._set(trainRatio=kwargs.get("trainRatio", 0.75),
                  seed=kwargs.get("seed", 0))

    def setTrainRatio(self, value):
        return self._set(trainRatio=value)

    def getTrainRatio(self):
        return self.getOrDefault(self.trainRatio)

    def _fit(self, dataset) -> "TrainValidationSplitModel":
        self._check_configured()
        ratio = self.getTrainRatio()
        if not 0.0 < ratio < 1.0:
            raise ValueError(f"trainRatio must be in (0, 1), got {ratio}")
        train, val = dataset.randomSplit([ratio, 1.0 - ratio],
                                         seed=self.getSeed())
        metrics = self._fit_and_score(train, val)
        best = self._best_index(metrics)
        best_model = self.estimator.fit(dataset,
                                        self.estimatorParamMaps[best])
        model = TrainValidationSplitModel(best_model, list(metrics), best)
        model._set_parent(self)
        return model

    def copy(self, extra=None):
        that = super().copy(extra)
        that.estimator = self.estimator
        that.evaluator = self.evaluator
        that.estimatorParamMaps = list(self.estimatorParamMaps)
        return that


class _SelectionModel(Model):
    def __init__(self, best_model: Model, metrics: List[float],
                 best_index: int) -> None:
        super().__init__()
        self.bestModel = best_model
        self.bestIndex = best_index

    def _transform(self, dataset):
        return self.bestModel.transform(dataset)


class CrossValidatorModel(_SelectionModel):
    """``bestModel`` + per-map ``avgMetrics`` (fold averages)."""

    def __init__(self, best_model: Model, avg_metrics: List[float],
                 best_index: int) -> None:
        super().__init__(best_model, avg_metrics, best_index)
        self.avgMetrics = avg_metrics


class TrainValidationSplitModel(_SelectionModel):
    """``bestModel`` + per-map ``validationMetrics``."""

    def __init__(self, best_model: Model, metrics: List[float],
                 best_index: int) -> None:
        super().__init__(best_model, metrics, best_index)
        self.validationMetrics = metrics
