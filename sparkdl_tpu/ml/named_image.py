"""DeepImagePredictor / DeepImageFeaturizer — pre-trained named models.

Parity: the reference's ``transformers/named_image.py`` (SURVEY.md §2.1,
§3.1 — the flagship path). There ``DeepImageFeaturizer`` delegated to a
Scala JavaTransformer that ran a frozen graph-def through TensorFrames;
here the named model is a Flax module from the in-repo zoo, weights
resident in HBM, and featurize/predict are one jitted XLA program
(device-side preprocess fused in front, SURVEY.md §7).

``DeepImagePredictor(decodePredictions=True)`` emits top-K
``(class, description, probability)`` rows like the reference's
keras ``decode_predictions``; class names come from a local ImageNet
index if one is available (keras cache), else stable ``class_<i>`` ids —
no network access is assumed anywhere.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

import numpy as np
import pyarrow as pa

logger = logging.getLogger(__name__)

from sparkdl_tpu.ml.base import Transformer
from sparkdl_tpu.ml.image_transformer import TPUImageTransformer
from sparkdl_tpu.models import registry
from sparkdl_tpu.param.base import Param, keyword_only
from sparkdl_tpu.param.converters import SparkDLTypeConverters, TypeConverters
from sparkdl_tpu.param.shared_params import (
    HasBatchSize,
    HasInputCol,
    HasMesh,
    HasOutputCol,
)

SUPPORTED_MODELS = registry.SUPPORTED_MODEL_NAMES


class _NamedImageTransformer(Transformer, HasInputCol, HasOutputCol,
                             HasBatchSize, HasMesh):
    """Shared plumbing: modelName param + cached ModelFunction build."""

    modelName = Param(
        "_NamedImageTransformer", "modelName",
        f"name of the pre-trained model, one of {SUPPORTED_MODELS}",
        typeConverter=SparkDLTypeConverters.supportedNameConverter(
            SUPPORTED_MODELS))
    weights = Param(
        "_NamedImageTransformer", "weights",
        "weight source: 'random' (seeded init), a Flax variables dict, a "
        "Keras model/.h5/.keras file, a msgpack file, or an Orbax dir",
        typeConverter=TypeConverters.identity)
    dtype = Param(
        "_NamedImageTransformer", "dtype",
        "compute dtype on device (e.g. jnp.bfloat16 for the MXU fast path); "
        "None computes in float32",
        typeConverter=TypeConverters.identity)

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(batchSize=64, weights="random", dtype=None)
        self._mf_cache = {}

    def setModelName(self, value: str):
        return self._set(modelName=value)

    def getModelName(self) -> str:
        return self.getOrDefault(self.modelName)

    def setWeights(self, value):
        return self._set(weights=value)

    def getWeights(self):
        return self.getOrDefault(self.weights)

    def setDtype(self, value):
        return self._set(dtype=value)

    def getDtype(self):
        return self.getOrDefault(self.dtype)

    def _model_function(self, kind: str):
        name = self.getModelName()
        weights = self.getWeights()
        dtype = self.getDtype()
        # Cache keyed by (kind, name, dtype) and validated against the exact
        # weights object/path — bounded size, and a new weights value (even
        # one reusing a freed object's address) can never hit a stale entry.
        key = (kind, name, str(dtype))
        cached = self._mf_cache.get(key)
        if cached is not None:
            cached_weights, mf = cached
            if cached_weights is weights or (
                    isinstance(weights, str) and cached_weights == weights):
                return mf
        build = (registry.build_featurizer if kind == "featurize"
                 else registry.build_predictor)
        mf = build(name, weights=weights, dtype=dtype)
        self._mf_cache[key] = (weights, mf)
        return mf

    def copy(self, extra=None):
        # Copies SHARE the built-model cache: entries validate against the
        # exact weights value, so a copy that changes weights rebuilds,
        # while a paramMap copy (e.g. transform(df, {batchSize: 32})) keeps
        # the same built model — essential for ingested names, whose
        # keras init is unseeded (a rebuild would produce DIFFERENT
        # random weights and incompatible features).
        that = super().copy(extra)
        that._mf_cache = dict(self._mf_cache)
        return that

    # -- persistence (SURVEY.md §5.4; see ml/persistence.py) -----------------

    _persist_kind = "featurize"

    def save(self, path: str) -> None:
        from sparkdl_tpu.ml import persistence as P

        os.makedirs(path, exist_ok=True)
        params = P.jsonable_params(self, skip=("mesh", "weights", "dtype"))
        params["dtype"] = P.dtype_name(self.getDtype())
        artifacts = {}
        weights = self.getWeights()
        ingested = registry.is_ingested_model(self.getModelName())
        if (isinstance(weights, str) and weights == "random"
                and not ingested):
            # seeded Flax init: rebuilding with the same marker reproduces
            # it exactly. Ingested models' keras init is NOT seeded, so
            # they fall through and persist the actual weights.
            params["weights"] = "random"
        elif ingested and (hasattr(weights, "layers") or (
                isinstance(weights, str)
                and weights.endswith((".h5", ".keras")))):
            # a user-supplied Keras model/file may be a CUSTOM graph (the
            # role check only validates the output head) — msgpack weights
            # alone could not restore it (the canonical-architecture
            # template wouldn't match), so persist the model itself via
            # Keras serialization; load re-ingests the saved graph.
            artifacts["keras_model"] = P.save_keras_artifact(
                _KerasPayload(weights), path)
        else:
            mf = self._model_function(self._persist_kind)
            # float_source: the pre-bf16-cast model (full-precision
            # weights); the dtype cast re-applies at load (ADVICE r4)
            source = getattr(mf, "float_source", mf)
            artifacts["weights"] = P.save_weights_msgpack(source.variables,
                                                          path)
        P.write_metadata(path, self, params, artifacts)

    @classmethod
    def _load_from(cls, path: str, meta):
        kwargs = dict(meta["params"])
        dtype = kwargs.pop("dtype", None)
        if "weights" in meta["artifacts"]:
            kwargs["weights"] = os.path.join(path, meta["artifacts"]["weights"])
        elif "keras_model" in meta["artifacts"]:
            kwargs["weights"] = os.path.join(path,
                                             meta["artifacts"]["keras_model"])
        inst = cls(**kwargs)
        if dtype is not None:
            inst.setDtype(np.dtype(dtype))
        return inst


class _KerasPayload:
    """Adapter: a weights value (Keras model object or file path) exposed
    through persistence.save_keras_artifact's getModel/getModelFile
    protocol."""

    def __init__(self, weights) -> None:
        self._weights = weights

    def getModel(self):
        return self._weights if hasattr(self._weights, "layers") else None

    def getModelFile(self):
        return self._weights if isinstance(self._weights, str) else None


class DeepImageFeaturizer(_NamedImageTransformer):
    """Headless named CNN → feature-vector column (transfer learning).

    The features feed a downstream cheap learner (e.g. LogisticRegression)
    in a Pipeline — the reference's headline use case.
    """

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelName: Optional[str] = None,
                 weights="random",
                 batchSize: int = 64,
                 dtype=None,
                 mesh=None) -> None:
        super().__init__()
        kwargs = self._input_kwargs
        self.setParams(**kwargs)

    @keyword_only
    def setParams(self, *, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelName: Optional[str] = None,
                  weights="random",
                  batchSize: int = 64,
                  dtype=None,
                  mesh=None) -> "DeepImageFeaturizer":
        return self._set(**self._input_kwargs)

    def _transform(self, dataset):
        mf = self._model_function("featurize")
        inner = TPUImageTransformer(
            inputCol=self.getInputCol(), outputCol=self.getOutputCol(),
            modelFunction=mf, outputMode="vector",
            batchSize=self.getBatchSize(), mesh=self.getMesh())
        return inner.transform(dataset)


class DeepImagePredictor(_NamedImageTransformer):
    """Full named CNN → class-probability column, optionally decoded top-K."""

    _persist_kind = "predict"

    decodePredictions = Param(
        "DeepImagePredictor", "decodePredictions",
        "when true, output a list of top-K (class, description, probability) "
        "structs instead of the raw probability vector",
        typeConverter=TypeConverters.toBoolean)
    topK = Param("DeepImagePredictor", "topK",
                 "how many top classes to keep when decoding",
                 typeConverter=TypeConverters.toInt)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelName: Optional[str] = None,
                 weights="random",
                 decodePredictions: bool = False,
                 topK: int = 5,
                 batchSize: int = 64,
                 dtype=None,
                 mesh=None) -> None:
        super().__init__()
        self._setDefault(decodePredictions=False, topK=5)
        kwargs = self._input_kwargs
        self.setParams(**kwargs)

    @keyword_only
    def setParams(self, *, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelName: Optional[str] = None,
                  weights="random",
                  decodePredictions: bool = False,
                  topK: int = 5,
                  batchSize: int = 64,
                  dtype=None,
                  mesh=None) -> "DeepImagePredictor":
        return self._set(**self._input_kwargs)

    def _transform(self, dataset):
        mf = self._model_function("predict")
        out_col = self.getOutputCol()
        decode = self.getOrDefault(self.decodePredictions)
        raw_col = out_col if not decode else out_col + "__raw"
        inner = TPUImageTransformer(
            inputCol=self.getInputCol(), outputCol=raw_col,
            modelFunction=mf, outputMode="vector",
            batchSize=self.getBatchSize(), mesh=self.getMesh())
        frame = inner.transform(dataset)
        if not decode:
            return frame
        k = self.getOrDefault(self.topK)
        labels = imagenet_labels(
            registry.get_model_spec(self.getModelName()).classes)
        decoded_type = pa.list_(pa.struct([
            pa.field("class", pa.string()),
            pa.field("description", pa.string()),
            pa.field("probability", pa.float32())]))

        def decode_row(probs):
            # Degrade per row, never abort the partition: a null input
            # cell (undecodable image upstream) or a malformed probability
            # vector becomes a null decoded cell (docs/RESILIENCE.md).
            if probs is None:
                return None
            try:
                p = np.asarray(probs, dtype=np.float32)
                top = np.argsort(-p)[:k]
                return [{"class": labels[i][0], "description": labels[i][1],
                         "probability": float(p[i])} for i in top]
            except (ValueError, TypeError, IndexError) as e:
                logger.warning(
                    "DeepImagePredictor: undecodable probability row "
                    "(%s: %s) — emitting null", type(e).__name__, e)
                return None

        frame = frame.withColumn(out_col, decode_row, inputCols=[raw_col],
                                 outputType=decoded_type)
        return frame.drop(raw_col)


def imagenet_labels(n_classes: int = 1000):
    """[(wnid, human_name)] — local keras cache if present, else stable ids.

    The reference relied on keras's ``decode_predictions`` which downloads
    ``imagenet_class_index.json``; this environment has no egress, so a
    cached copy is used when found and a deterministic fallback otherwise.
    """
    candidates = [
        os.path.expanduser("~/.keras/models/imagenet_class_index.json"),
        os.path.expanduser("~/.keras/imagenet_class_index.json"),
    ]
    for path in candidates:
        if os.path.exists(path):
            try:
                with open(path) as f:
                    index = json.load(f)
                return [tuple(index[str(i)]) for i in range(n_classes)]
            except (OSError, KeyError, json.JSONDecodeError):
                break
    return [(f"class_{i}", f"class_{i}") for i in range(n_classes)]
