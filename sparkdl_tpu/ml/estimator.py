"""KerasImageFileEstimator — train a Keras model on an image DataFrame.

Parity (SURVEY.md §3.3): the reference's estimator ran cluster-side
preprocessing, then ``collect()``-ed everything to the driver and called
keras ``model.fit`` locally — the scalability cliff SURVEY.md calls out.
The rebuild keeps the Estimator surface (``fit``, lazy ``fitMultiple``
param-map search, ``CanLoadImage`` host decode) but trains with the
Trainer's jitted step: forward/backward/update in one XLA program, data
sharded over the mesh's ``data`` axis when a mesh is supplied (the
MobileNetV2 fine-tune and ResNet50 DP configs in BASELINE.md).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sparkdl_tpu.core import profiling, telemetry
from sparkdl_tpu.core.model_function import ModelFunction
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml.base import Estimator, Model
from sparkdl_tpu.ml.image_transformer import TPUImageTransformer
from sparkdl_tpu.ml.persistence import ModelFunctionPersistence
from sparkdl_tpu.param.base import Param, keyword_only
from sparkdl_tpu.param.converters import TypeConverters
from sparkdl_tpu.param.shared_params import (
    CanLoadImage,
    HasBatchSize,
    HasInputCol,
    HasKerasLoss,
    HasKerasModel,
    HasKerasOptimizer,
    HasLabelCol,
    HasMesh,
    HasOutputCol,
    HasOutputMode,
)

_LOADED_COL = "__sdl_estimator_image"


class KerasImageFileEstimator(Estimator, HasInputCol, HasOutputCol,
                              HasLabelCol, HasKerasModel, HasKerasOptimizer,
                              HasKerasLoss, CanLoadImage, HasOutputMode,
                              HasBatchSize, HasMesh):
    """Estimator over an image-URI DataFrame, fitted on TPU via Trainer."""

    kerasFitParams = Param(
        "KerasImageFileEstimator", "kerasFitParams",
        "fit options: {'epochs': int, 'batch_size': int, "
        "'learning_rate': float, 'shuffle': bool, 'seed': int, "
        "'streaming': bool, 'mixed_precision': bool, "
        "'shuffle_buffer': int (windowed-shuffle pool depth in batches, "
        "streaming path; default 4), "
        "'validation_data': (X, y) arrays evaluated at each epoch end, "
        "'validation_split': float tail fraction held out (collected "
        "path only), 'verbose': bool (per-step metrics JSONL to stdout), "
        "'log_every': int, 'checkpoint_dir': str (Orbax mid-training "
        "checkpoints + resume), 'checkpoint_every': int steps, "
        "'prefetch': int (async-pipeline staging depth in batches, "
        "0 = serial staging; default 2), 'sync_every': int (steps "
        "between deferred device syncs; default 8 — see docs/PERF.md)}",
        typeConverter=TypeConverters.identity)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 labelCol: Optional[str] = None,
                 modelFile: Optional[str] = None,
                 model=None,
                 imageLoader: Optional[Callable] = None,
                 kerasOptimizer: str = "adam",
                 kerasLoss: str = "categorical_crossentropy",
                 kerasFitParams: Optional[Dict[str, Any]] = None,
                 outputMode: str = "vector",
                 batchSize: int = 64,
                 mesh=None) -> None:
        super().__init__()
        self._setDefault(kerasOptimizer="adam",
                         kerasLoss="categorical_crossentropy",
                         kerasFitParams={"epochs": 1, "batch_size": 32},
                         outputMode="vector", batchSize=64)
        self._mf_cache = None
        kwargs = self._input_kwargs
        self.setParams(**kwargs)

    @keyword_only
    def setParams(self, *, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  labelCol: Optional[str] = None,
                  modelFile: Optional[str] = None,
                  model=None,
                  imageLoader: Optional[Callable] = None,
                  kerasOptimizer: str = "adam",
                  kerasLoss: str = "categorical_crossentropy",
                  kerasFitParams: Optional[Dict[str, Any]] = None,
                  outputMode: str = "vector",
                  batchSize: int = 64,
                  mesh=None) -> "KerasImageFileEstimator":
        kwargs = dict(self._input_kwargs)
        loader = kwargs.pop("imageLoader", None)
        if {"model", "modelFile"} & kwargs.keys():
            self._mf_cache = None
        self._set(**kwargs)
        if loader is not None:
            self.setImageLoader(loader)
        return self

    def setModel(self, value):
        self._mf_cache = None
        return super().setModel(value)

    def setModelFile(self, value):
        self._mf_cache = None
        return super().setModelFile(value)

    def copy(self, extra=None):
        that = super().copy(extra)
        that._mf_cache = None
        return that

    def _model_function(self) -> ModelFunction:
        if self._mf_cache is None:
            self._mf_cache = self.loadKerasModelAsFunction()
        return self._mf_cache

    def setKerasFitParams(self, value: Dict[str, Any]):
        return self._set(kerasFitParams=value)

    def getKerasFitParams(self) -> Dict[str, Any]:
        return dict(self.getOrDefault(self.kerasFitParams))

    @staticmethod
    def _compute_dtype(fit_params: Dict[str, Any]):
        """mixed_precision fit param -> Trainer compute dtype (one policy
        for both the streaming and collected fit paths)."""
        return "bfloat16" if fit_params.get("mixed_precision") else None

    @staticmethod
    def _check_multihost_mesh(mesh, num_proc: int) -> int:
        """Shared multi-host guards for both fit paths; returns the data
        axis size. A model-parallel mesh whose data axis is smaller than
        the process count would make the local share 0 (ZeroDivisionError
        downstream)."""
        from sparkdl_tpu.core.mesh import data_axis_size

        if mesh is None:
            raise ValueError(
                "multi-host fit requires a mesh (the data axis carries "
                "the per-host shards)")
        axis = data_axis_size(mesh)
        if axis % num_proc != 0:
            raise ValueError(
                f"multi-host fit needs the mesh data axis ({axis}) to be "
                f"a multiple of the process count ({num_proc})")
        return axis

    # -- data staging --------------------------------------------------------

    def _loaded_frame(self, dataset):
        """dataset + decoded image column (lazy; decode runs per partition)."""
        mf = self._model_function()
        shape = mf.input_spec.shape
        target_size = ((shape[1], shape[2])
                       if len(shape) == 4 and None not in shape[1:3] else None)
        loaded = self.loadImagesInternal(dataset, self.getInputCol(),
                                         _LOADED_COL, target_size=target_size)
        return loaded, target_size

    def _collect_arrays(self, dataset) -> Tuple[np.ndarray, np.ndarray]:
        """Decode+resize URIs and stack (X, y) host-side.

        The decode runs partition-parallel in the engine (the reference ran
        it as a Spark job); the stacked result is the host staging buffer
        the train loop feeds to the device in fixed-size chunks. Used by
        ``fitMultiple`` (decode once, train many) and by
        ``kerasFitParams={'streaming': False}``; plain ``fit`` streams
        partitions instead (``_fit_streaming`` / ``_PartitionBatchStream``).
        """
        mf = self._model_function()
        loaded, target_size = self._loaded_frame(dataset)
        with telemetry.span(telemetry.SPAN_COLLECT):
            rows = loaded.select(_LOADED_COL, self.getLabelCol()).collect()
        structs = [r[_LOADED_COL] for r in rows]
        labels = [r[self.getLabelCol()] for r in rows]
        keep = [i for i, s in enumerate(structs) if s is not None]
        x = imageIO.imageStructsToBatchArray(
            [structs[i] for i in keep], target_size=target_size,
            dtype=None)
        if x.dtype != np.dtype(mf.input_spec.dtype):
            if (x.dtype == np.uint8
                    and np.dtype(mf.input_spec.dtype) == np.dtype(np.float32)):
                # keep uint8: Trainer.stage_batch transfers raw bytes and
                # casts to float32 on device (exact for 0-255) — same rule
                # as the streaming path (_partition_arrays_inner), so both
                # staging paths feed the device identical programs.
                pass
            else:
                x = x.astype(mf.input_spec.dtype)
        y = np.asarray([labels[i] for i in keep])
        return x, y

    def _label_preparer(self, mf: ModelFunction) -> Callable[[np.ndarray], np.ndarray]:
        """Per-batch label transform; the n_classes probe (a whole-model
        ``eval_shape`` trace) runs at most ONCE even when the streaming
        path prepares labels partition by partition."""
        loss = self.getKerasLoss()
        cache: Dict[str, int] = {}

        def prepare(y: np.ndarray) -> np.ndarray:
            if "sparse" in loss:
                return y.astype(np.int32)
            if y.ndim == 1 and "crossentropy" in loss and "binary" not in loss:
                if "n_classes" not in cache:
                    out = jax.eval_shape(
                        mf.apply_fn, mf.variables,
                        jnp.zeros(mf.input_spec.with_batch(1),
                                  dtype=mf.input_spec.dtype))
                    cache["n_classes"] = out.shape[-1]
                return np.eye(cache["n_classes"],
                              dtype=np.float32)[y.astype(np.int64)]
            return y.astype(np.float32)

        return prepare

    def _prepare_labels(self, y: np.ndarray, mf: ModelFunction) -> np.ndarray:
        return self._label_preparer(mf)(y)

    # -- fitting -------------------------------------------------------------

    def _fit_run(self, trainer, state, batches, fit_params,
                 mf: ModelFunction):
        """Shared train-loop driver for both fit paths: wires validation
        evaluation (keras ``validation_data`` semantics), per-step metrics
        JSONL (``verbose``/``log_every``, SURVEY.md §5.5), and Orbax
        mid-training checkpoints + resume (``checkpoint_dir``/
        ``checkpoint_every``, §5.4) into ``Trainer.fit``. Returns
        ``(state, history)`` — history is keras-History-shaped:
        {'epochs': [...], 'steps': [...]}.
        """
        epochs = int(fit_params.get("epochs", 1))
        history: Dict[str, Any] = {"epochs": [], "steps": []}

        val_batches = None
        if fit_params.get("validation_data") is not None:
            vx, vy = fit_params["validation_data"]
            vx = np.asarray(vx)
            vy = self._prepare_labels(np.asarray(vy), mf)
            vbs = int(fit_params.get("batch_size", 32))
            val_batches = [(vx[i:i + vbs], vy[i:i + vbs])
                           for i in range(0, len(vx), vbs)]

        logger = None
        if fit_params.get("verbose"):
            from sparkdl_tpu.train.metrics import MetricsLogger

            logger = MetricsLogger(every=int(fit_params.get("log_every", 1)))

        checkpoint = None
        if fit_params.get("checkpoint_dir"):
            from sparkdl_tpu.train.checkpoint import CheckpointManager

            checkpoint = CheckpointManager(str(fit_params["checkpoint_dir"]))

        def on_epoch(epoch: int, st) -> None:
            record: Dict[str, Any] = {"epoch": epoch}
            if val_batches is not None:
                record.update(trainer.evaluate(st, val_batches))
            history["epochs"].append(record)
            if fit_params.get("verbose") and len(record) > 1:
                import json as _json

                print(_json.dumps(record, default=float), flush=True)

        state = trainer.fit(
            state, batches, epochs=epochs, metrics_logger=logger,
            checkpoint=checkpoint,
            checkpoint_every=int(fit_params.get("checkpoint_every", 0)),
            on_epoch=on_epoch,
            # async input pipeline knobs (ISSUE 3, docs/PERF.md): staging
            # depth and deferred-sync cadence of the pipelined train loop
            prefetch=int(fit_params.get("prefetch", 2)),
            sync_every=int(fit_params.get("sync_every", 8)))
        if checkpoint is not None:
            checkpoint.wait_until_finished()
            checkpoint.close()
        if logger is not None:
            history["steps"] = logger.history
        return state, history

    def _fit_streaming(self, dataset) -> "KerasImageFileModel":
        """Streaming ``fit``: memory bounded by batch + a few partitions.

        Replaces the reference's driver-side ``collect()`` (SURVEY.md §3.3's
        scalability cliff): partitions decode lazily through the engine and
        flow into fixed-shape train batches without materializing the
        dataset. The whole pull→decode→stage chain runs on ``Trainer.fit``'s
        prefetcher thread (ISSUE 3): partition decode for batch k+1
        overlaps the device's training of batch k. With
        ``EngineConfig.decode_workers > 0`` the partition decode itself
        fans out to the multi-process decode pool (ISSUE 9, docs/PERF.md
        "Parallel host ingest"), so the GIL-bound JPEG decode no longer
        serializes on the staging thread — decode processes, staging,
        and the device step all overlap. With ``shuffle`` rows mix through a windowed shuffle
        buffer across partitions (an EXACT global permutation requires the
        collected path, ``streaming=False``); with ``shuffle=False`` the
        batch sequence is identical to the collected path's.

        Multi-host (SURVEY.md §2.5/§3.5, HorovodRunner parity): when the
        process group spans several hosts, each host streams+decodes ONLY
        its round-robin share of the partitions and emits LOCAL batches of
        ``batch_size / process_count``; ``Trainer.stage_batch`` assembles
        the global sharded array from the per-process shards. Hosts stay
        in lockstep via a per-batch allgather (the epoch ends for everyone
        when the first host runs dry, dropping at most the tail).
        """
        from sparkdl_tpu.core.mesh import data_axis_size, pad_to_multiple
        from sparkdl_tpu.train.trainer import Trainer

        mf = self._model_function()
        fit_params = self.getKerasFitParams()
        batch_size = int(fit_params.get("batch_size", 32))
        shuffle = bool(fit_params.get("shuffle", True))
        seed = int(fit_params.get("seed", 0))
        lr = fit_params.get("learning_rate")
        mesh = self.resolveMesh()
        num_proc = jax.process_count()
        multiple = 1
        if mesh is not None:
            multiple = data_axis_size(mesh)
            batch_size = pad_to_multiple(batch_size, multiple)
        if num_proc > 1:
            self._check_multihost_mesh(mesh, num_proc)
            # validation_data works multi-host: state is replicated, so
            # Trainer.evaluate pulls it host-local and every process
            # computes the exact single-process metrics (r5; the
            # validation_split raise below still applies — it needs the
            # collected path on any topology).
            # every host contributes an equal local slice of each global
            # batch
            batch_size //= num_proc
            multiple //= num_proc
        loaded, target_size = self._loaded_frame(dataset)
        frame = loaded.select(_LOADED_COL, self.getLabelCol())
        if num_proc > 1 and frame.numPartitions < num_proc:
            raise ValueError(
                f"multi-host fit needs at least one partition per process: "
                f"dataset has {frame.numPartitions} partitions for "
                f"{num_proc} processes — repartition the DataFrame")
        stream = _PartitionBatchStream(
            frame, _LOADED_COL, self.getLabelCol(), target_size,
            str(mf.input_spec.dtype), batch_size, multiple, shuffle, seed,
            self._label_preparer(mf),
            shuffle_buffer=int(fit_params.get("shuffle_buffer", 4)),
            process_id=jax.process_index() if num_proc > 1 else None,
            num_processes=num_proc if num_proc > 1 else None)
        if fit_params.get("validation_split"):
            raise ValueError(
                "validation_split needs the whole dataset in memory — use "
                "streaming=False, or pass validation_data arrays instead")
        trainer, state = Trainer.from_model_function(
            mf, loss=self.getKerasLoss(), optimizer=self.getKerasOptimizer(),
            learning_rate=lr, mesh=mesh,
            compute_dtype=self._compute_dtype(fit_params))
        state, history = self._fit_run(trainer, state, stream, fit_params, mf)
        if stream.batches_last_epoch == 0:
            raise ValueError("No decodable training images")
        return self._wrap_trained(mf, state, history)

    def _wrap_trained(self, mf: ModelFunction, state,
                      history: Optional[Dict[str, Any]] = None
                      ) -> "KerasImageFileModel":
        trained = ModelFunction(mf.apply_fn, jax.device_get(state.params),
                                mf.input_spec, name=mf.name + "_trained",
                                trainable_mask=mf.trainable_mask)
        model = KerasImageFileModel(
            inputCol=self.getInputCol(), outputCol=self.getOutputCol(),
            modelFunction=trained, outputMode=self.getOutputMode(),
            batchSize=self.getBatchSize(), mesh=self.getMesh(),
            imageLoader=self.getImageLoader())
        model._set_parent(self)
        # keras-History analog: per-epoch validation metrics + per-step
        # training metrics (when verbose logging was on)
        model.history = history or {"epochs": [], "steps": []}
        return model

    def _fit_on_arrays(self, x: np.ndarray, y: np.ndarray
                       ) -> "KerasImageFileModel":
        from sparkdl_tpu.core.mesh import data_axis_size, pad_to_multiple
        from sparkdl_tpu.train.trainer import Trainer

        mf = self._model_function()
        y = self._prepare_labels(y, mf)
        fit_params = self.getKerasFitParams()
        batch_size = int(fit_params.get("batch_size", 32))
        shuffle = bool(fit_params.get("shuffle", True))
        seed = int(fit_params.get("seed", 0))
        lr = fit_params.get("learning_rate")
        mesh = self.resolveMesh()
        if mesh is not None:
            batch_size = pad_to_multiple(batch_size, data_axis_size(mesh))
        split = float(fit_params.get("validation_split", 0.0) or 0.0)
        if split and fit_params.get("validation_data") is not None:
            # keras precedence: explicit validation_data wins and the
            # split is ignored (no rows held out)
            split = 0.0
        if split:
            # keras semantics: the validation slice is the TAIL of the
            # data as provided, taken BEFORE shuffling
            if not 0.0 < split < 1.0:
                raise ValueError(
                    f"validation_split must be in (0, 1), got {split}")
            n_val = int(len(x) * split)
            if n_val == 0 or n_val == len(x):
                raise ValueError(
                    f"validation_split={split} leaves an empty train or "
                    f"validation set for {len(x)} rows")
            fit_params = dict(fit_params,
                              validation_data=(x[-n_val:], y[-n_val:]))
            x, y = x[:-n_val], y[:-n_val]
        if shuffle:
            perm = np.random.default_rng(seed).permutation(len(x))
            x, y = x[perm], y[perm]
        # fixed-size batches (static XLA shapes); remainder dropped like
        # keras fit with drop_remainder — unless that would drop everything
        n = len(x)
        if n == 0:
            raise ValueError("No decodable training images")
        batch_size = min(batch_size, n)
        if mesh is not None:
            # the clamp above can break divisibility by the data axis; the
            # jitted step's P('data') in_shardings needs every shard equal
            axis = data_axis_size(mesh)
            batch_size = (batch_size // axis) * axis
            if batch_size == 0:
                raise ValueError(
                    f"dataset has {n} usable rows but the mesh data axis "
                    f"spans {axis} devices; need at least {axis} rows")
        usable = (n // batch_size) * batch_size
        batches = [(x[i:i + batch_size], y[i:i + batch_size])
                   for i in range(0, usable, batch_size)]

        # Multi-host collected fit (r5): Trainer.stage_batch assembles the
        # global array from PROCESS-LOCAL shards, so feeding the full
        # batch on every host would silently duplicate the data. Each
        # host takes its contiguous slice of every (host-identical)
        # global batch — shard order matches make_array_from_
        # process_local_data's process-order concatenation, so params
        # equal the single-process fit exactly.
        num_proc = jax.process_count()
        if num_proc > 1:
            self._check_multihost_mesh(mesh, num_proc)
            # One cheap collective up front: every host must have
            # collected the same row count, or (one host dropping an
            # undecodable image) batch counts diverge and the short host
            # exits the loop while the others block in the next
            # collective forever — the collected-path analog of the
            # streaming path's per-batch lockstep.
            from jax.experimental import multihost_utils

            counts = multihost_utils.process_allgather(
                np.asarray([len(x)], dtype=np.int64))
            if int(counts.min()) != int(counts.max()):
                raise ValueError(
                    "multi-host collected fit needs every process to "
                    "decode the same rows; got per-host counts "
                    f"{counts.ravel().tolist()} — check for corrupt or "
                    "host-unreadable images, or use streaming=True "
                    "(lockstep tolerates uneven decode)")
            # batch_size is a multiple of the data axis here, and the
            # axis is a multiple of num_proc, so the slice is exact
            local = batch_size // num_proc
            p = jax.process_index()
            batches = [(bx[p * local:(p + 1) * local],
                        by[p * local:(p + 1) * local])
                       for bx, by in batches]

        trainer, state = Trainer.from_model_function(
            mf, loss=self.getKerasLoss(), optimizer=self.getKerasOptimizer(),
            learning_rate=lr, mesh=mesh,
            compute_dtype=self._compute_dtype(fit_params))
        state, history = self._fit_run(trainer, state, batches, fit_params,
                                       mf)
        return self._wrap_trained(mf, state, history)

    def _fit(self, dataset) -> "KerasImageFileModel":
        # Training NEVER routes through the device execution service
        # (core/executor.py): both fit paths feed Trainer's own step
        # program (donated state threading, deferred sync) — coalescing
        # across training steps would interleave state updates from
        # unrelated streams. EngineConfig.coalesce only affects the
        # fitted model's transform(), which is an inference path.
        streaming = bool(self.getKerasFitParams().get("streaming", True))
        with telemetry.span(telemetry.SPAN_ESTIMATOR_FIT,
                            streaming=streaming):
            if streaming:
                return self._fit_streaming(dataset)
            x, y = self._collect_arrays(dataset)
            return self._fit_on_arrays(x, y)

    # -- persistence (unfitted estimator; VERDICT r3 #6) ---------------------

    def save(self, path: str) -> None:
        """Persist the UNFITTED estimator: params metadata + the Keras
        model artifact (self-contained — an in-memory ``model`` serializes
        via Keras, a ``modelFile`` is copied in). ``load`` then ``fit``
        reproduces the model fitting the original would produce (training
        is deterministic in the fit-param seed)."""
        import os

        from sparkdl_tpu.ml import persistence as P

        P.check_no_custom_loader(self)
        os.makedirs(path, exist_ok=True)
        params = P.jsonable_params(self, skip=("mesh", "model", "modelFile"))
        artifact = P.save_keras_artifact(self, path)
        if artifact is None:
            raise ValueError("set either model or modelFile before save()")
        P.write_metadata(path, self, params, {"keras_model": artifact})

    @classmethod
    def _load_from(cls, path: str, meta):
        import os

        inst = cls(**meta["params"])
        inst.setModelFile(os.path.join(path, meta["artifacts"]["keras_model"]))
        return inst

    def fitMultiple(self, dataset, paramMaps) -> Iterator[Tuple[int, Model]]:
        """Param-map search sharing ONE image decode pass (§3.3 parity:
        the reference collected features once, then looped over maps).

        Decode-sharing policy (VERDICT r3 #7): by default the dataset is
        decoded ONCE into a host cache shared by every map — the fastest
        HPO path, at the §3.3 collect-cliff memory cost. A map (or the
        base estimator) that sets ``kerasFitParams={'streaming': True}``
        opts that fit out of the cache: it streams partitions with bounded
        memory instead (decode repeats per fit+epoch — the explicit
        time-for-memory trade for datasets that don't fit on the host).
        The collect runs lazily, only when the first cache-sharing map
        trains, so an all-streaming search never materializes the dataset.
        """
        estimator = self.copy()

        def _map_streams(param_map) -> bool:
            fp = estimator.copy(param_map).getKerasFitParams()
            return bool(fp.get("streaming", False))

        class _Iter:
            def __init__(self) -> None:
                self._lock = threading.Lock()
                # separate lock: the (long) one-time collect must not block
                # other threads from taking indices / starting streaming
                # fits that need no cache
                self._cache_lock = threading.Lock()
                self._next = 0
                self._cache: Optional[Tuple[np.ndarray, np.ndarray]] = None

            def __iter__(self):
                return self

            def _collected(self):
                with self._cache_lock:
                    if self._cache is None:
                        self._cache = estimator._collect_arrays(dataset)
                    return self._cache

            def __next__(self):
                with self._lock:
                    index = self._next
                    if index >= len(paramMaps):
                        raise StopIteration
                    self._next += 1
                if _map_streams(paramMaps[index]):
                    fitted = estimator.copy(
                        paramMaps[index])._fit_streaming(dataset)
                else:
                    base_x, base_y = self._collected()
                    fitted = estimator.copy(paramMaps[index])._fit_on_arrays(
                        base_x, base_y)
                return index, fitted

        return _Iter()


class _PartitionBatchStream:
    """Reiterable fixed-shape (x, y) batch stream over engine partitions.

    Each iteration (epoch) pulls partitions through
    ``DataFrame.streamPartitions`` — nothing is materialized beyond the
    prefetch window plus the shuffle pool — and decodes the image-struct
    column (Arrow zero-copy fast path, per-row fallback). ``shuffle``
    visits partitions in a fresh per-epoch order and mixes rows through a
    ~4-batch windowed pool (tf.data-style buffer; deterministic in (seed,
    epoch)); without it rows chain across partition boundaries in order,
    matching the collected path's batch sequence exactly. The final
    remainder is dropped (keras ``drop_remainder`` semantics) unless the
    whole epoch would otherwise be empty, in which case one smaller batch
    (rounded down to ``multiple`` for mesh shard divisibility) is yielded.
    """

    def __init__(self, frame, image_col: str, label_col: str,
                 target_size, dtype: str, batch_size: int, multiple: int,
                 shuffle: bool, seed: int,
                 prepare_labels: Callable[[np.ndarray], np.ndarray],
                 shuffle_buffer: int = 4,
                 process_id: Optional[int] = None,
                 num_processes: Optional[int] = None) -> None:
        self._frame = frame
        self._image_col = image_col
        self._label_col = label_col
        self._target_size = target_size
        self._dtype = dtype
        self._batch_size = batch_size
        self._multiple = max(1, multiple)
        self._shuffle = shuffle
        self._seed = seed
        self._prepare_labels = prepare_labels
        self._shuffle_buffer = max(1, shuffle_buffer)
        self._process_id = process_id
        self._num_processes = num_processes
        self._epoch = 0
        self.batches_last_epoch: Optional[int] = None

    @property
    def _multihost(self) -> bool:
        return bool(self._num_processes and self._num_processes > 1)

    def _lockstep(self, gen):
        """Keep hosts emitting the same batch COUNT: before every yield,
        all processes agree (allgather) whether everyone still has a next
        batch; the epoch ends globally when the first host runs dry. One
        tiny host-collective per batch — the analog of the per-step
        barrier Horovod's allreduce imposed anyway (SURVEY.md §3.5)."""
        from jax.experimental import multihost_utils

        it = iter(gen)
        while True:
            try:
                nxt = next(it)
                have = 1
            except StopIteration:
                nxt = None
                have = 0
            counts = multihost_utils.process_allgather(
                np.asarray([have], dtype=np.int32))
            if int(np.min(counts)) == 0:
                return
            yield nxt

    def _partition_arrays(self, part) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        with profiling.annotate("sparkdl.stage", rows=part.num_rows):
            return self._partition_arrays_inner(part)

    def _partition_arrays_inner(self, part
                                ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        idx = part.schema.get_field_index(self._image_col)
        col = part.column(idx)
        labels = part.column(part.schema.get_field_index(self._label_col))
        fast = imageIO.arrowImageBatch(col)
        if fast is not None:
            x, valid_idx = fast
            import pyarrow as pa

            # sparkdl: allow(columnar-hot-path): label column — may hold
            # strings/objects; tiny next to the pixel payload
            y = np.asarray(labels.take(pa.array(valid_idx)).to_pylist())
        else:
            # sparkdl: allow(columnar-hot-path): compatibility fallback —
            # ragged partitions only; uniform columns take arrowImageBatch
            structs = col.to_pylist()
            valid = [i for i, s in enumerate(structs) if s is not None]
            if not valid:
                return None
            x = imageIO.imageStructsToBatchArray(
                [structs[i] for i in valid], target_size=self._target_size,
                dtype=None)
            # sparkdl: allow(columnar-hot-path): label column — may hold
            # strings/objects; tiny next to the pixel payload
            lab = labels.to_pylist()
            y = np.asarray([lab[i] for i in valid])
        if x.shape[0] == 0:
            return None
        if (self._target_size is not None
                and tuple(x.shape[1:3]) != tuple(self._target_size)):
            # custom loaders may emit off-size structs; batch-resize here
            x = imageIO.resizeBatchArray(x, tuple(self._target_size))
        if x.dtype != np.dtype(self._dtype):
            if (x.dtype == np.uint8
                    and np.dtype(self._dtype) == np.dtype(np.float32)):
                # keep uint8: Trainer.stage_batch transfers it raw and
                # casts to FLOAT32 on device (exact for 0-255) — 4x less
                # host->device traffic on the training hot loop. f32 only:
                # other float input dtypes must cast host-side so the
                # staged dtype matches the collected path exactly.
                pass
            else:
                x = x.astype(self._dtype)
        return x, self._prepare_labels(y)

    def __iter__(self):
        if self._multihost:
            # lockstep wrapper counts the GLOBAL epoch length; the local
            # generator's own count is corrected afterwards
            gen = self._lockstep(self._iter_local())
            emitted = 0
            for item in gen:
                emitted += 1
                yield item
            self.batches_last_epoch = emitted
            return
        yield from self._iter_local()

    def _iter_local(self):
        epoch = self._epoch
        self._epoch += 1
        bs = self._batch_size
        emitted = 0
        order = None
        # Windowed shuffle (tf.data-style buffer): partitions are visited
        # in a fresh per-epoch order and rows mix across a pool of
        # ``shuffle_buffer`` batches + 1 partition before each emit —
        # bounded memory, breaks class-clustered partition layouts. Deepen
        # via kerasFitParams['shuffle_buffer'] (VERDICT r3 weak #4); an
        # EXACT global permutation needs the collected path
        # (streaming=False).
        pool_cap = bs * self._shuffle_buffer if self._shuffle else 0
        if self._shuffle:
            order = np.random.default_rng(
                (self._seed, epoch)).permutation(self._frame.numPartitions)
        pool_x: Optional[np.ndarray] = None
        pool_y: Optional[np.ndarray] = None
        flush = 0

        def shuffled_pool():
            nonlocal flush
            rng = np.random.default_rng((self._seed, epoch, flush))
            flush += 1
            perm = rng.permutation(len(pool_x))
            return pool_x[perm], pool_y[perm]

        for part in self._frame.streamPartitions(
                order=order, process_id=self._process_id,
                num_processes=self._num_processes):
            arrays = self._partition_arrays(part)
            if arrays is None:
                continue
            x, y = arrays
            if pool_x is not None:
                x = np.concatenate([pool_x, x])
                y = np.concatenate([pool_y, y])
            pool_x, pool_y = x, y
            if len(pool_x) >= pool_cap + bs:
                if self._shuffle:
                    pool_x, pool_y = shuffled_pool()
                emit = (len(pool_x) - pool_cap) // bs
                for i in range(emit):
                    emitted += 1
                    yield pool_x[i * bs:(i + 1) * bs], pool_y[i * bs:(i + 1) * bs]
                pool_x, pool_y = pool_x[emit * bs:], pool_y[emit * bs:]
        if pool_x is not None and len(pool_x) > 0:
            if self._shuffle:
                pool_x, pool_y = shuffled_pool()
            usable = (len(pool_x) // bs) * bs
            for i in range(0, usable, bs):
                emitted += 1
                yield pool_x[i:i + bs], pool_y[i:i + bs]
            if emitted == 0 and not self._multihost:
                # single-host small-dataset fallback: one sub-batch, rounded
                # to the mesh multiple. Multi-host skips it — unequal host
                # shard shapes can't assemble one global array; the
                # lockstep layer ends the epoch consistently instead.
                n = (len(pool_x) // self._multiple) * self._multiple
                if n == 0:
                    raise ValueError(
                        f"dataset has {len(pool_x)} usable rows but the mesh "
                        f"data axis requires a multiple of {self._multiple}")
                emitted += 1
                yield pool_x[:n], pool_y[:n]
        self.batches_last_epoch = emitted


class KerasImageFileModel(Model, HasInputCol, HasOutputCol, CanLoadImage,
                          HasOutputMode, HasBatchSize, HasMesh,
                          ModelFunctionPersistence):
    """Fitted model: URI column → trained network → predictions column.

    Persistence: the trained net round-trips as StableHLO with weights
    baked in (``ModelFunctionPersistence``).
    """

    _persist_check_loader = True
    _persist_name = "keras_image_file_model"

    modelFunction = Param("KerasImageFileModel", "modelFunction",
                          "trained ModelFunction",
                          typeConverter=TypeConverters.identity)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelFunction=None,
                 outputMode: str = "vector",
                 batchSize: int = 64,
                 mesh=None,
                 imageLoader: Optional[Callable] = None) -> None:
        super().__init__()
        self._setDefault(outputMode="vector", batchSize=64)
        kwargs = dict(self._input_kwargs)
        loader = kwargs.pop("imageLoader", None)
        self._set(**kwargs)
        if loader is not None:
            self.setImageLoader(loader)

    def getModelFunction(self):
        return self.getOrDefault(self.modelFunction)

    def _transform(self, dataset):
        mf = self.getModelFunction()
        shape = mf.input_spec.shape
        target_size = ((shape[1], shape[2])
                       if len(shape) == 4 and None not in shape[1:3] else None)
        loaded = self.loadImagesInternal(dataset, self.getInputCol(),
                                         _LOADED_COL, target_size=target_size)
        inner = TPUImageTransformer(
            inputCol=_LOADED_COL, outputCol=self.getOutputCol(),
            modelFunction=mf, outputMode=self.getOutputMode(),
            batchSize=self.getBatchSize(), mesh=self.getMesh())
        return inner.transform(loaded).drop(_LOADED_COL)
