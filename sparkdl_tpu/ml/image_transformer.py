"""TPUImageTransformer — arbitrary model applied to an image column.

Parity: the reference's workhorse ``TFImageTransformer``
(``transformers/tf_image.py``, SURVEY.md §2.1, §3.2). There the graph
pipeline was assembled by splicing TF graph pieces (``buildSpImageConverter``
in front, flattener behind) and executed per-partition by TensorFrames→JNI.
Here the same pipeline is function composition compiled into ONE XLA
program:

    host: image struct column → contiguous NHWC batch (resize if needed)
    device (one jit): cast → user/device preprocess → model → [flatten]

and execution is the engine's partition-parallel ``withColumnBatch`` — one
``device_put`` per partition chunk, fixed batch shapes via padding so XLA
compiles once per batch size.

Async pipeline (ISSUE 3): within a partition, ``apply_batch`` stages
chunk ``k+1`` (the pad copies) on a background prefetcher thread while
chunk ``k``'s transfer+compute is in flight (``_PREFETCH_DEPTH``), and
the engine's partition pool overlaps one partition's host decode with
another's device work — the featurize-path adoption of the same
``core.pipeline.DevicePrefetcher`` the Trainer uses.

Parallel host ingest (ISSUE 9): the JPEG decode feeding this
transformer (``readImages`` / ``loadImagesInternal`` ops fused into the
same partition task as ``apply_partition``) fans out to the
multi-process decode pool when ``EngineConfig.decode_workers > 0``
(``core/decode_pool.py``, docs/PERF.md "Parallel host ingest"), so the
GIL-bound PIL fallback stops serializing the featurize pipeline:
worker-process decode, prefetcher staging, and device compute all
overlap, and the partition threads here only stack pixels and launch.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

import numpy as np
import pyarrow as pa

logger = logging.getLogger(__name__)

from sparkdl_tpu.core import executor as device_executor
from sparkdl_tpu.core import profiling
from sparkdl_tpu.engine.dataframe import EngineConfig, fixed_size_list_array
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml.base import Transformer
from sparkdl_tpu.ml.persistence import ModelFunctionPersistence
from sparkdl_tpu.param.base import Param, keyword_only
from sparkdl_tpu.param.converters import TypeConverters
from sparkdl_tpu.param.shared_params import (
    HasBatchSize,
    HasInputCol,
    HasMesh,
    HasModelFunction,
    HasOutputCol,
    HasOutputMode,
    HasPriority,
)

OUTPUT_MODES = ("vector", "image")

# Chunk-staging depth of the async input pipeline inside apply_batch
# (core/pipeline.py); 0 falls back to inline serial staging.
_PREFETCH_DEPTH = 2


class TPUImageTransformer(Transformer, HasInputCol, HasOutputCol,
                          HasModelFunction, HasOutputMode, HasBatchSize,
                          HasMesh, HasPriority, ModelFunctionPersistence):
    """Apply a ModelFunction to an image-struct column.

    ``outputMode="vector"`` flattens model output per row into a fixed-size
    float list column (the reference's Spark-ML Vector analog);
    ``outputMode="image"`` re-wraps 3-D HWC output as image structs
    (parity with ``tf_image.py``'s two output modes).
    """

    inputSize = Param(
        "TPUImageTransformer", "inputSize",
        "(H, W) the host resizes images to before staging; None uses the "
        "model input spec's spatial dims",
        typeConverter=TypeConverters.identity)

    @keyword_only
    def __init__(self, *, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None,
                 modelFunction=None,
                 outputMode: str = "vector",
                 batchSize: int = 64,
                 inputSize: Optional[Tuple[int, int]] = None,
                 mesh=None, priority: Optional[str] = None) -> None:
        super().__init__()
        self._setDefault(outputMode="vector", batchSize=64, inputSize=None)
        kwargs = self._input_kwargs
        self.setParams(**kwargs)

    @keyword_only
    def setParams(self, *, inputCol: Optional[str] = None,
                  outputCol: Optional[str] = None,
                  modelFunction=None,
                  outputMode: str = "vector",
                  batchSize: int = 64,
                  inputSize: Optional[Tuple[int, int]] = None,
                  mesh=None,
                  priority: Optional[str] = None) -> "TPUImageTransformer":
        # outputMode validation lives in the param's typeConverter
        # (SparkDLTypeConverters.toOutputMode) so every set path is covered.
        return self._set(**self._input_kwargs)

    def setInputSize(self, value) -> "TPUImageTransformer":
        return self._set(inputSize=value)

    def getInputSize(self):
        return self.getOrDefault(self.inputSize)


    # -- execution -----------------------------------------------------------

    def _target_size(self, model) -> Optional[Tuple[int, int]]:
        size = self.getOrDefault(self.inputSize)
        if size is not None:
            return tuple(size)
        shape = model.input_spec.shape
        if len(shape) == 4 and shape[1] is not None and shape[2] is not None:
            return (shape[1], shape[2])
        return None

    def _transform(self, dataset):
        model = self.getModelFunction()
        if model is None:
            raise ValueError("modelFunction must be set")
        # Multi-host data-parallel inference (SURVEY.md §2.4 row 1): each
        # process transforms only its round-robin partition share; no-op
        # single-process, idempotent across chained transformers. Assembly
        # is opt-in via DataFrame.gatherProcesses (docs/DISTRIBUTED.md).
        dataset = dataset.processShard()
        input_col = self.getInputCol()
        output_col = self.getOutputCol()
        mode = self.getOutputMode()
        batch_size = self.getBatchSize()
        from sparkdl_tpu.core.mesh import host_local_mesh

        mesh = host_local_mesh(self.resolveMesh())
        target_size = self._target_size(model)
        priority = self.getPriority()  # None: EngineConfig default lane
        run = model.flattened() if mode == "vector" else model
        if input_col not in dataset.columns:
            raise KeyError(f"No such column: {input_col!r}")

        def apply_partition(batch: pa.RecordBatch) -> pa.Array:
            idx = batch.schema.get_field_index(input_col)
            col = batch.column(idx)

            # Arrow fast path: uniform-size column → zero-copy NHWC view of
            # the contiguous binary buffer; no to_pylist, no per-row
            # frombuffer. Resize policy in _resize_uniform_batch.
            fast = imageIO.arrowImageBatch(col)
            if fast is not None:
                stacked, valid_np = fast
                valid = valid_np.tolist()
                stacked, run_fast = _resize_uniform_batch(stacked, target_size,
                                                          run)
                with profiling.annotate("sparkdl.device_apply",
                                        rows=len(stacked)):
                    # device entry via the execution-service choke point
                    # (core/executor.py): concurrent partition chunks
                    # against the same compiled fn coalesce into one
                    # launch when EngineConfig.coalesce is on
                    out = device_executor.execute(
                        run_fast, stacked, batch_size=batch_size,
                        mesh=mesh, prefetch=_PREFETCH_DEPTH,
                        priority=priority)
                if mode == "vector":
                    return _vectors_with_nulls(out, valid, batch.num_rows)
                # sparkdl: allow(columnar-hot-path): origin strings — the
                # image-output wrapper needs Python strings per row
                origins = col.field("origin").take(
                    pa.array(valid_np)).to_pylist()
                return _images_with_nulls(out, valid, batch.num_rows, origins)

            # sparkdl: allow(columnar-hot-path): compatibility fallback —
            # only ragged/non-uniform partitions reach here; uniform
            # columns take the zero-copy arrowImageBatch branch above
            structs = col.to_pylist()
            present = [i for i, s in enumerate(structs) if s is not None]
            # dtype=None: uint8 images stage as uint8 (4x fewer DMA bytes);
            # the jitted program casts to the spec dtype on device.
            # Tolerant staging: malformed structs (corrupt bytes, bad mode
            # codes, injected decode_error faults) degrade to null output
            # cells instead of aborting the partition (Spark's
            # corrupt-image convention); the drop count is surfaced below.
            with profiling.annotate("sparkdl.host_stage",
                                    rows=len(present)):
                stacked, kept, dropped = \
                    imageIO.imageStructsToBatchArrayTolerant(
                        [structs[i] for i in present],
                        target_size=target_size, dtype=None)
            if dropped:
                logger.warning(
                    "TPUImageTransformer: dropped %d undecodable image "
                    "row(s) of %d in partition (%r) — emitting null cells",
                    dropped, len(present), input_col)
            valid = [present[j] for j in kept]
            if not valid:
                out_type = (pa.list_(pa.float32()) if mode == "vector"
                            else imageIO.imageSchema)
                return pa.array([None] * batch.num_rows, type=out_type)
            with profiling.annotate("sparkdl.device_apply",
                                    rows=len(stacked)):
                out = device_executor.execute(
                    run, stacked, batch_size=batch_size, mesh=mesh,
                    prefetch=_PREFETCH_DEPTH, priority=priority)
            if mode == "vector":
                return _vectors_with_nulls(out, valid, batch.num_rows)
            return _images_with_nulls(out, valid, batch.num_rows,
                                      [structs[i].get("origin", "") for i in valid])

        out_type = (pa.list_(pa.float32())
                    if mode == "vector" else imageIO.imageSchema)
        return dataset.withColumnBatch(output_col, apply_partition,
                                       outputType=out_type)


def _resize_uniform_batch(stacked: np.ndarray, target_size, run):
    """Resize policy for the uniform (Arrow fast-path) batch.

    Transfers over the host→device link are the pipeline bottleneck
    (~47 MB/s measured under the remote PJRT tunnel; uint8 staging and byte
    minimization are the levers — core/batching.py). So:

    - downscale: resize on HOST via the threaded native batch resizer
      (GIL-free C++), shrinking transfer bytes;
    - upscale / native unavailable: transfer the source and resize ON
      DEVICE inside the model program (``ModelFunction.resized`` — the
      reference's in-graph tf.image.resize, SURVEY.md §3.2).

    Both are pixel-center bilinear without antialiasing; they differ only
    by uint8 rounding. Returns the (possibly resized) batch and the
    (possibly resize-composed) ModelFunction.

    Under ``EngineConfig.fused_preprocess`` (the default; docs/PERF.md
    "Columnar data plane") the host never resizes at all: the raw uint8
    batch ships at source size and resize fuses into the compiled
    program via ``ModelFunction.resized`` — cast/resize/normalize/
    forward become one XLA program, and the host's only per-image work
    is the Arrow wrap. The legacy byte-minimizing host-downscale policy
    below is kept for ``fused_preprocess=False``.
    """
    if target_size is None or tuple(stacked.shape[1:3]) == tuple(target_size):
        return stacked, run
    if EngineConfig.fused_preprocess:
        return stacked, run.resized(stacked.shape[1:3], tuple(target_size))
    src_px = stacked.shape[1] * stacked.shape[2]
    tgt_px = target_size[0] * target_size[1]
    # Byte-minimizing policy, measured (r3): sending the larger source and
    # resizing on device lost to host resize even on a 1-core host (40.8 vs
    # 64 img/s e2e) — the link transfer itself consumes host CPU, so fewer
    # bytes helps twice. Downscales resize on host (native C++ for uint8,
    # vectorized numpy otherwise); upscales transfer the smaller source and
    # resize on device. All three paths share the same pixel-center
    # no-antialias bilinear convention.
    if src_px > tgt_px:
        with profiling.annotate("sparkdl.host_resize"):
            resized = None
            if stacked.dtype == np.uint8:
                from sparkdl_tpu.native import loader as native_loader

                resized = native_loader.resize_batch(stacked,
                                                     tuple(target_size))
            if resized is None:
                resized = imageIO.resizeBatchArray(stacked,
                                                   tuple(target_size))
        return resized, run
    return stacked, run.resized(stacked.shape[1:3], tuple(target_size))


def _vectors_with_nulls(out: np.ndarray, valid, num_rows: int) -> pa.Array:
    out = np.asarray(out, dtype=np.float32).reshape(len(valid), -1)
    if len(valid) == num_rows:
        return fixed_size_list_array(out).cast(pa.list_(pa.float32()))
    values = [None] * num_rows
    for j, i in enumerate(valid):
        values[i] = out[j]
    return pa.array(values, type=pa.list_(pa.float32()))


def _images_with_nulls(out: np.ndarray, valid, num_rows: int,
                       origins) -> pa.Array:
    out = np.asarray(out)
    if out.ndim != 4:
        raise ValueError(
            f"outputMode='image' needs NHWC model output, got shape {out.shape}")
    values = [None] * num_rows
    for j, i in enumerate(valid):
        arr = out[j]
        if arr.dtype not in (np.uint8, np.float32):
            arr = arr.astype(np.float32)
        # sparkdl: allow(columnar-hot-path): output-mode="image" wrapper —
        # null interleaving forces per-row structs; model OUTPUT columns,
        # not the ingest spine
        values[i] = imageIO.imageArrayToStruct(arr, origin=origins[j])
    return pa.array(values, type=imageIO.imageSchema)
