"""ML-Pipeline API layer (L4′) — the user-facing surface.

Parity target (SURVEY.md §1 L4, §2.1): the reference exposed Spark ML
``Transformer``/``Estimator`` subclasses (``DeepImageFeaturizer``,
``DeepImagePredictor``, ``KerasImageFileTransformer``, ``KerasTransformer``,
``TFImageTransformer``, ``TFTransformer``, ``KerasImageFileEstimator``).
This package rebuilds that surface on the in-repo engine with TPU-native
execution underneath (jitted Flax apply instead of TF sessions).
"""

from sparkdl_tpu.ml.base import (
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    Transformer,
)
from sparkdl_tpu.ml.classification import (
    LogisticRegression,
    LogisticRegressionModel,
)
from sparkdl_tpu.ml.estimator import KerasImageFileEstimator, KerasImageFileModel
from sparkdl_tpu.ml.feature import (
    Binarizer,
    Imputer,
    ImputerModel,
    IndexToString,
    MinMaxScaler,
    MinMaxScalerModel,
    Normalizer,
    OneHotEncoder,
    SQLTransformer,
    StandardScaler,
    StandardScalerModel,
    StringIndexer,
    StringIndexerModel,
    VectorAssembler,
)
from sparkdl_tpu.ml.regression import (
    LinearRegression,
    LinearRegressionModel,
)
from sparkdl_tpu.ml.evaluation import (
    BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from sparkdl_tpu.ml.tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
    TrainValidationSplitModel,
)
from sparkdl_tpu.ml.image_transformer import TPUImageTransformer
from sparkdl_tpu.ml.keras_image import KerasImageFileTransformer
from sparkdl_tpu.ml.keras_tensor import KerasTransformer
from sparkdl_tpu.ml.named_image import DeepImageFeaturizer, DeepImagePredictor
from sparkdl_tpu.ml.persistence import load
from sparkdl_tpu.ml.tensor_transformer import TPUTransformer

# Reference-compatible aliases: the reference's names execute TF graphs;
# here the payload is a ModelFunction, but the pipeline role is identical.
TFImageTransformer = TPUImageTransformer
TFTransformer = TPUTransformer

__all__ = [
    "BinaryClassificationEvaluator",
    "CrossValidator",
    "CrossValidatorModel",
    "DeepImageFeaturizer",
    "DeepImagePredictor",
    "Estimator",
    "MulticlassClassificationEvaluator",
    "ParamGridBuilder",
    "RegressionEvaluator",
    "TrainValidationSplit",
    "TrainValidationSplitModel",
    "Binarizer",
    "Imputer",
    "ImputerModel",
    "Normalizer",
    "SQLTransformer",
    "IndexToString",
    "MinMaxScaler",
    "MinMaxScalerModel",
    "KerasImageFileEstimator",
    "KerasImageFileModel",
    "StringIndexer",
    "StringIndexerModel",
    "KerasImageFileTransformer",
    "KerasTransformer",
    "LinearRegression",
    "LinearRegressionModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "StandardScaler",
    "StandardScalerModel",
    "Model",
    "OneHotEncoder",
    "Pipeline",
    "load",
    "PipelineModel",
    "Transformer",
    "TPUImageTransformer",
    "TPUTransformer",
    "VectorAssembler",
    "TFImageTransformer",
    "TFTransformer",
]
