"""Pallas fused TPU kernels behind an accept-if-faster autotune (ISSUE 20).

The dormant probes validated the kernel shapes (``experiments/
pallas_probe.py``: the ``fma9`` VPU ceiling, the ``dw2d`` row-major
layout, the ``sep2d`` one-VMEM-residency fusion); this module is their
production port plus the machinery that makes shipping them SAFE:

- **Fused kernels** — :func:`sep2d` (relu? → 3×3 SAME depthwise → 1×1
  pointwise matmul → folded-BN affine, one VMEM residency, no HBM
  round trip between dw and pw — the Xception ``SeparableConvBN``
  body), :func:`pw1x1` (1×1 conv as an MXU matmul with the BN affine
  and optional relu fused as the epilogue — the InceptionV3 ``ConvBN``
  1×1 stride-1 sites), and :func:`preproc_resize` (uint8 → float cast
  + bilinear resize as two interpolation-matrix matmuls per channel
  plane — the fused-preprocess prologue, one Pallas launch instead of
  N XLA ops). Each has an XLA twin (:func:`xla_sep2d` …) that
  reproduces the exact op order of the Flax layer it would replace.

- **Accept-if-faster autotune** — models never call the kernels
  directly; they call ``route_*`` (via the structural opt-in in
  ``models/layers.py``), and a route only returns the fused
  computation when a per-(kernel, model-family, shape, dtype) verdict
  says the Pallas candidate beat its XLA twin by ≥5% at that exact
  shape AND stayed inside the numeric contract (fp32 exact, bf16
  within :data:`BF16_TOLERANCE`). Verdicts are produced by
  :func:`ensure_autotuned` — hooked into ``ModelFunction``'s
  first-launch-of-a-shape path, so shootouts run at the deployment's
  actual bucket rungs, before the shape's first trace — and persist
  beside the compile cache (``$SPARKDL_COMPILE_CACHE_DIR/
  sparkdl_kernel_verdicts.json``, atomic replace, versioned): a losing
  kernel is never re-auditioned every boot, but because the batch
  dimension is part of the key, a bucket-ladder retune (new rungs →
  new keys) re-auditions automatically. A losing or numerically-off
  kernel NEVER ships — which is what makes defaulting
  ``EngineConfig.pallas_kernels`` to ``"autotune"`` safe: on a backend
  without Mosaic lowering (CPU tests) every audition records a clean
  rejection and the routed program is byte-identical to the XLA one.

Gating: ``EngineConfig.pallas_kernels`` — ``"off"`` (this module is
never imported; subprocess-pinned), ``"autotune"`` (default),
``"force"`` (route every feasible site, no shootout — tests drive it
with :data:`INTERPRET` to exercise kernel numerics on CPU).

Telemetry: ``sparkdl.kernel.autotune_s`` histogram per shootout,
``sparkdl.kernel.adopted``/``rejected`` counters. docs/PERF.md "Fused
kernels & AOT warmup" is the operator story; the ``kernel-gate``
analyzer rule keeps raw ``pallas_call``/kernel entry points from
bypassing this registry anywhere else in the tree.
"""

from __future__ import annotations

import functools
import json
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sparkdl_tpu.core import telemetry

logger = logging.getLogger(__name__)

#: bf16 numeric contract: max |pallas - xla| per element a candidate may
#: show against its XLA twin and still be adopted (the same 0.05 bound
#: docs/PERF.md guarantees for the bf16 inference path as a whole).
#: fp32 candidates must match exactly.
BF16_TOLERANCE = 0.05
#: Accept-if-faster bar: adopted only when pallas_s <= 0.95 * xla_s.
ADOPT_SPEEDUP = 0.95
#: Run every pallas_call in interpreter mode (CPU-executable, slow) —
#: how the test suite exercises kernel numerics and the routing plumbing
#: without a TPU. Flipping it changes the verdict backend tag, so
#: interpreter verdicts never leak into real-hardware stores.
INTERPRET = False

#: Raw kernel builders. Calling these anywhere outside this module
#: bypasses the accept-if-faster gate — flagged by the ``kernel-gate``
#: analyzer rule (docs/ANALYSIS.md); production code goes through the
#: ``route_*`` entry points.
RAW_KERNEL_ENTRY_POINTS = frozenset({"sep2d", "pw1x1", "preproc_resize"})

#: VMEM sizing caps for one grid step's blocks (conservative: Mosaic
#: double-buffers in/out blocks, and the pw weight block is resident
#: across the whole grid).
_BLOCK_LIMIT_BYTES = 1536 * 1024
_WEIGHT_LIMIT_BYTES = 4 * 1024 * 1024

_VERDICT_STORE_BASENAME = "sparkdl_kernel_verdicts.json"
VERDICT_STORE_VERSION = 1


# ---------------------------------------------------------------------------
# Sites and verdicts
# ---------------------------------------------------------------------------


@dataclass(frozen=True, order=True)
class Site:
    """One autotunable kernel site: WHAT would run WHERE.

    ``shape`` carries the full launch geometry including the batch
    dimension — bucket-ladder rungs are therefore distinct sites, which
    is both how the shootout times the deployment's real shapes and how
    a ladder retune re-auditions kernels (new rungs → new keys) without
    any explicit invalidation."""

    kernel: str
    family: str
    shape: Tuple[int, ...]
    dtype: str


def _backend_tag() -> str:
    return "interpret" if INTERPRET else jax.default_backend()


def _site_key(site: Site) -> str:
    return "|".join((site.kernel, site.family,
                     "x".join(str(d) for d in site.shape), site.dtype,
                     _backend_tag()))


def verdict_store_path() -> Optional[str]:
    """Verdict persistence file, beside the persistent compilation cache
    (``$SPARKDL_COMPILE_CACHE_DIR``) — the same placement as the learned
    bucket ladders: a warm process reloads the shootout outcomes
    together with the compiled programs they selected. None when the
    cache dir is not configured (verdicts stay in-process)."""
    from sparkdl_tpu import COMPILE_CACHE_DIR_ENV

    cache_dir = os.environ.get(COMPILE_CACHE_DIR_ENV)
    if not cache_dir:
        return None
    return os.path.join(cache_dir, _VERDICT_STORE_BASENAME)


_verdicts: Dict[str, Dict[str, Any]] = {}
_verdicts_loaded = False
_verdict_lock = threading.Lock()
# per-site single-flight: concurrent callers of the SAME site wait on
# the owner's event (no lock held across the shootout's device work)
_inflight: Dict[str, threading.Event] = {}


def _read_store() -> Dict[str, Dict[str, Any]]:
    """Parse the store file. A corrupt file or a stale ``version`` is
    DISCARDED, never trusted — the worst case is re-auditioning, which
    is exactly what a format change wants."""
    path = verdict_store_path()
    if path is None:
        return {}
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict) \
            or doc.get("version") != VERDICT_STORE_VERSION:
        return {}
    stored = doc.get("verdicts")
    if not isinstance(stored, dict):
        return {}
    return {key: verdict for key, verdict in stored.items()
            if isinstance(key, str) and isinstance(verdict, dict)
            and isinstance(verdict.get("adopted"), bool)}


def _ensure_loaded() -> None:
    """Populate the in-memory verdict map from the store file once per
    process (file I/O outside the lock; a racing double-read merges
    identically via setdefault)."""
    global _verdicts_loaded
    if _verdicts_loaded:
        return
    stored = _read_store()
    with _verdict_lock:
        if _verdicts_loaded:
            return
        for key, verdict in stored.items():
            _verdicts.setdefault(key, verdict)
        _verdicts_loaded = True


def _persist_verdict(key: str, verdict: Dict[str, Any]) -> None:
    """Merge one verdict into the store file (tmp + ``os.replace``
    atomic swap; concurrent writers race whole-file, last wins — the
    store is a cache, not a source of truth)."""
    path = verdict_store_path()
    if path is None:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        doc: Dict[str, Any] = {"version": VERDICT_STORE_VERSION,
                               "verdicts": {}}
        try:
            with open(path) as f:
                loaded = json.load(f)
            if isinstance(loaded, dict) \
                    and loaded.get("version") == VERDICT_STORE_VERSION \
                    and isinstance(loaded.get("verdicts"), dict):
                doc = loaded
        except (OSError, ValueError):
            pass
        doc.setdefault("verdicts", {})[key] = verdict
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError as e:  # persistence is best-effort
        logger.warning("could not persist kernel verdict to %s: %s",
                       path, e)


def verdict_for(site: Site) -> Optional[Dict[str, Any]]:
    """The stored shootout outcome for ``site`` (None = never
    auditioned on this backend)."""
    _ensure_loaded()
    with _verdict_lock:
        return _verdicts.get(_site_key(site))


def verdicts_snapshot() -> Dict[str, Dict[str, Any]]:
    """Every verdict this process knows (bench's per-rung report)."""
    _ensure_loaded()
    with _verdict_lock:
        return {k: dict(v) for k, v in _verdicts.items()}


def reset() -> None:
    """Forget every in-memory verdict (test isolation; the store file,
    if any, is re-read on next use)."""
    global _verdicts_loaded
    with _verdict_lock:
        _verdicts.clear()
        _verdicts_loaded = False


# ---------------------------------------------------------------------------
# Mode + routing decisions
# ---------------------------------------------------------------------------


def kernel_mode() -> str:
    """``EngineConfig.pallas_kernels`` without requiring the engine
    (core stays importable standalone → ``"off"``)."""
    try:
        from sparkdl_tpu.engine.dataframe import EngineConfig
    except Exception:  # sparkdl: allow(broad-retry): layering probe — any
        # import failure means "no engine configured", i.e. kernels off
        return "off"
    return getattr(EngineConfig, "pallas_kernels", "off")


_collect = threading.local()


def _collecting() -> Optional[set]:
    return getattr(_collect, "sites", None)


def _decide(site: Site, feasible: bool) -> bool:
    """Route-time verdict lookup: True = run the Pallas candidate.

    Under a collection scope (:func:`ensure_autotuned`'s abstract
    pass), the site is recorded and the XLA path chosen — collection
    must never launch device work. ``"force"`` routes every feasible
    site (tests); ``"autotune"`` requires an adopted verdict."""
    sites = _collecting()
    if sites is not None:
        sites.add(site)
        return False
    mode = kernel_mode()
    if mode == "force":
        return feasible
    if mode != "autotune" or not feasible:
        return False
    verdict = verdict_for(site)
    return bool(verdict is not None and verdict.get("adopted"))


def ensure_autotuned(fn, x, model: str = "model") -> None:
    """Audition every kernel site ``fn(x)`` would route through, BEFORE
    its first real trace.

    Called by ``ModelFunction._build_jitted``'s first-launch-of-a-shape
    wrapper: an abstract pass (``jax.eval_shape`` under a collection
    scope) discovers the sites at zero device cost, then each missing
    verdict runs one shootout. By the time the real trace happens the
    routes resolve against settled verdicts — a request never blocks on
    a shootout mid-trace."""
    if kernel_mode() != "autotune":
        return
    sites: set = set()
    prev = _collecting()
    _collect.sites = sites
    try:
        jax.eval_shape(fn, x)
    except Exception as e:  # sparkdl: allow(broad-retry): collection is
        # best-effort discovery — a model that cannot abstractly
        # evaluate simply gets no kernels, never a broken launch
        logger.debug("kernel site collection failed for %s: %s", model, e)
    finally:
        _collect.sites = prev
    for site in sorted(sites):
        ensure_verdict(site)


# ---------------------------------------------------------------------------
# Geometry: layout + block sizing (shared by routes and raw builders)
# ---------------------------------------------------------------------------


def _sublane(dtype) -> Optional[int]:
    dtype = jnp.dtype(dtype)
    if dtype == jnp.float32:
        return 8
    if dtype == jnp.bfloat16:
        return 16
    return None


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _sep2d_geometry(b: int, h: int, w: int, cin: int, cout: int,
                    dtype) -> Optional[Tuple[int, int]]:
    """(P_PAD, BT) for the row-major sep2d layout, or None when the
    site cannot fit the VMEM block budget (route falls back to XLA)."""
    sub = _sublane(dtype)
    if sub is None or h < 3 or w < 3 or b < 1:
        return None
    p_pad = _round_up(h * w, sub)
    item = jnp.dtype(dtype).itemsize
    if cin * cout * item > _WEIGHT_LIMIT_BYTES:
        return None
    row_bytes = p_pad * max(cin, cout) * item
    if row_bytes > _BLOCK_LIMIT_BYTES:
        return None
    cap = _BLOCK_LIMIT_BYTES // row_bytes
    bt = 1
    for d in range(1, min(b, cap) + 1):
        if b % d == 0:
            bt = d
    return p_pad, bt


def _pw1x1_geometry(n: int, cin: int, cout: int,
                    dtype) -> Optional[Tuple[int, int]]:
    """(rows per block, padded row count) for the flattened 1×1 matmul
    layout, or None when infeasible."""
    sub = _sublane(dtype)
    if sub is None or n < 1:
        return None
    item = jnp.dtype(dtype).itemsize
    if cin * cout * item > _WEIGHT_LIMIT_BYTES:
        return None
    r_blk = None
    for r in (1024, 512, 256, 128, 64, 32, 16, 8):
        if r % sub:
            continue
        if r * max(cin, cout) * item <= _BLOCK_LIMIT_BYTES:
            r_blk = r
            break
    if r_blk is None:
        return None
    return r_blk, _round_up(n, r_blk)


def _preproc_geometry(h: int, w: int, th: int, tw: int) -> bool:
    return (h * w * 4 <= _BLOCK_LIMIT_BYTES
            and th * tw * 4 <= _BLOCK_LIMIT_BYTES
            and max(th * h, tw * w) * 4 <= _BLOCK_LIMIT_BYTES)


def _pad_rows(x, p_pad: int):
    """(B, H, W, C) → (B·P_PAD, C): image positions row-major, each
    image zero-padded to P_PAD rows so every BT block is
    sublane-aligned (device-side: reshape + pad fuse into the
    surrounding program)."""
    b, h, w, c = x.shape
    flat = x.reshape(b, h * w, c)
    flat = jnp.pad(flat, ((0, 0), (0, p_pad - h * w), (0, 0)))
    return flat.reshape(b * p_pad, c)


def _unpad_rows(y, b: int, h: int, w: int, cout: int, p_pad: int):
    return y.reshape(b, p_pad, cout)[:, :h * w].reshape(b, h, w, cout)


# ---------------------------------------------------------------------------
# The kernels (production ports of experiments/pallas_probe.py)
# ---------------------------------------------------------------------------


def _row_coords(r: int, w: int, p_pad: int):
    # 2D iota only (Mosaic rejects 1D); (r, 1) broadcasts against (r, C)
    rows = jax.lax.broadcasted_iota(jnp.int32, (r, 1), 0)
    p = rows % p_pad
    return p // w, p % w  # h, w per row (p >= H*W: dead pad rows)


def _dw_rows(x, k_ref, h: int, w: int, p_pad: int, relu_in: bool):
    """3×3 SAME depthwise on a (R, C) block holding BT images of (h, w)
    positions row-major. One combined row shift per tap (w·dy + dx):
    row-major positions make the (dy, dx) neighbor a fixed row offset;
    masks computed from the row index kill rows whose source crossed an
    image/H/W edge (including the dead pad rows — any p ≥ h·w source
    reaching a live dest is edge-masked). Keeps live VMEM to ~3 tiles."""
    if relu_in:
        x = jnp.maximum(x, 0)
    rows = x.shape[0]
    hh, ww = _row_coords(rows, w, p_pad)
    zero = jnp.zeros((), x.dtype)

    def shift_rows(a, s):
        # a[r] <- a[r+s], zero-filled (Mosaic bf16 has no rotate; static
        # slice+concat lowers to sublane relayout copies)
        if s == 0:
            return a
        pad = jnp.zeros((abs(s), a.shape[1]), a.dtype)
        if s > 0:
            return jnp.concatenate([a[s:], pad], axis=0)
        return jnp.concatenate([pad, a[:s]], axis=0)

    acc = None
    for j, (dy, dx) in enumerate(
            (dy, dx) for dy in (-1, 0, 1) for dx in (-1, 0, 1)):
        valid = ((hh + dy >= 0) & (hh + dy <= h - 1)
                 & (ww + dx >= 0) & (ww + dx <= w - 1))
        t = jnp.where(valid, shift_rows(x, w * dy + dx),
                      zero) * k_ref[j:j + 1, :]
        acc = t if acc is None else acc + t
    return acc


def _sep2d_kernel(x_ref, k_ref, pw_ref, sc_ref, sh_ref, o_ref, *,
                  h: int, w: int, p_pad: int, relu_in: bool):
    t = _dw_rows(x_ref[:], k_ref, h, w, p_pad, relu_in)
    y = jnp.dot(t, pw_ref[:], preferred_element_type=jnp.float32)
    y = y * sc_ref[0:1, :] + sh_ref[0:1, :]
    o_ref[:] = y.astype(o_ref.dtype)


def sep2d(x, dw9, pw, scale, shift, *, relu_in: bool = False,
          interpret: Optional[bool] = None):
    """Fused relu? → 3×3 SAME stride-1 depthwise → 1×1 pointwise → BN
    affine: ``(B, H, W, Cin) → (B, H, W, Cout)`` in ONE VMEM residency
    (the depthwise result feeds the pointwise MXU matmul without an HBM
    round trip — the ``sep2d`` probe shape productionized).

    ``dw9`` is the depthwise kernel as (9, Cin) tap-major; ``pw``
    (Cin, Cout); ``scale``/``shift`` the folded BN affine as (1, Cout)
    float32. Raw entry point — production code routes through
    :func:`route_sep2d` (``kernel-gate`` enforces this)."""
    b, h, w, cin = x.shape
    cout = pw.shape[-1]
    geom = _sep2d_geometry(b, h, w, cin, cout, x.dtype)
    if geom is None:
        raise ValueError(
            f"sep2d site b{b} {h}x{w}x{cin}->{cout} {jnp.dtype(x.dtype)} "
            "exceeds the VMEM block budget")
    p_pad, bt = geom
    r = bt * p_pad
    grid = b // bt
    x2 = _pad_rows(x, p_pad)
    p = h * w
    kernel = functools.partial(_sep2d_kernel, h=h, w=w, p_pad=p_pad,
                               relu_in=relu_in)
    y2 = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((r, cin), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((9, cin), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((cin, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, cout), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * p_pad, cout), x.dtype),
        cost_estimate=pl.CostEstimate(
            flops=b * (p * cin * 9 * 2 + p * cin * cout * 2),
            bytes_accessed=(x2.size + b * p_pad * cout)
            * jnp.dtype(x.dtype).itemsize,
            transcendentals=0,
        ),
        interpret=INTERPRET if interpret is None else interpret,
    )(x2, dw9, pw, scale, shift)
    return _unpad_rows(y2, b, h, w, cout, p_pad)


def _pw1x1_kernel(x_ref, w_ref, sc_ref, sh_ref, o_ref, *, relu: bool):
    y = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)
    y = y * sc_ref[0:1, :] + sh_ref[0:1, :]
    if relu:
        y = jnp.maximum(y, 0)
    o_ref[:] = y.astype(o_ref.dtype)


def pw1x1(x, w2, scale, shift, *, relu: bool = False,
          interpret: Optional[bool] = None):
    """Fused 1×1 conv (an MXU matmul over flattened positions) + folded
    BN affine + optional relu: ``(B, H, W, Cin) → (B, H, W, Cout)``.
    Raw entry point — production code routes through
    :func:`route_pw1x1`."""
    b, h, w, cin = x.shape
    cout = w2.shape[-1]
    n = b * h * w
    geom = _pw1x1_geometry(n, cin, cout, x.dtype)
    if geom is None:
        raise ValueError(
            f"pw1x1 site b{b} {h}x{w}x{cin}->{cout} {jnp.dtype(x.dtype)} "
            "exceeds the VMEM block budget")
    r_blk, n_pad = geom
    x2 = x.reshape(n, cin)
    if n_pad > n:
        x2 = jnp.pad(x2, ((0, n_pad - n), (0, 0)))
    grid = n_pad // r_blk
    y2 = pl.pallas_call(
        functools.partial(_pw1x1_kernel, relu=relu),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((r_blk, cin), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((cin, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r_blk, cout), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, cout), x.dtype),
        cost_estimate=pl.CostEstimate(
            flops=n * cin * cout * 2,
            bytes_accessed=(n_pad * (cin + cout))
            * jnp.dtype(x.dtype).itemsize,
            transcendentals=0,
        ),
        interpret=INTERPRET if interpret is None else interpret,
    )(x2, w2, scale, shift)
    return y2[:n].reshape(b, h, w, cout)


def _resize_matrix(src: int, dst: int) -> np.ndarray:
    """(dst, src) bilinear interpolation weights reproducing
    ``jax.image.resize(method="bilinear", antialias=False)`` semantics
    (half-pixel centers: src coord = (t + 0.5)·src/dst − 0.5, triangle
    kernel, edge-clamped) — host-computed once per (src, dst) pair so
    the resize becomes two matmuls."""
    scale = src / dst
    out = np.zeros((dst, src), np.float32)
    for t in range(dst):
        s = (t + 0.5) * scale - 0.5
        lo = int(np.floor(s))
        frac = s - lo
        for tap, wgt in ((lo, 1.0 - frac), (lo + 1, frac)):
            out[t, min(max(tap, 0), src - 1)] += wgt
    return out


def _preproc_kernel(x_ref, wh_ref, wwt_ref, o_ref):
    x = x_ref[0].astype(jnp.float32)  # (H, W) — uint8 casts in VMEM
    t = jnp.dot(wh_ref[:], x, preferred_element_type=jnp.float32)
    y = jnp.dot(t, wwt_ref[:], preferred_element_type=jnp.float32)
    o_ref[0] = y.astype(o_ref.dtype)


def preproc_resize(x, target_hw: Tuple[int, int], out_dtype,
                   *, interpret: Optional[bool] = None):
    """Fused cast + bilinear resize, one launch: ``(B, H, W, C)`` any
    dtype (uint8 on the columnar plane) → ``(B, th, tw, C)``
    ``out_dtype``. Channel-planar layout: each grid step resizes one
    (H, W) plane as two interpolation-matrix matmuls (Wh @ X @ WwT).
    Raw entry point — production code routes through
    :func:`route_preproc`."""
    b, h, w, c = x.shape
    th, tw = int(target_hw[0]), int(target_hw[1])
    if not _preproc_geometry(h, w, th, tw):
        raise ValueError(
            f"preproc site {h}x{w}->{th}x{tw} exceeds the VMEM block "
            "budget")
    xp = jnp.transpose(x, (0, 3, 1, 2)).reshape(b * c, h, w)
    wh = jnp.asarray(_resize_matrix(h, th))
    wwt = jnp.asarray(_resize_matrix(w, tw).T)
    y = pl.pallas_call(
        _preproc_kernel,
        grid=(b * c,),
        in_specs=[
            pl.BlockSpec((1, h, w), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((th, h), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((w, tw), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, th, tw), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * c, th, tw),
                                       jnp.dtype(out_dtype)),
        cost_estimate=pl.CostEstimate(
            flops=b * c * (th * h * w + th * tw * w) * 2,
            bytes_accessed=x.size * jnp.dtype(x.dtype).itemsize
            + b * c * th * tw * jnp.dtype(out_dtype).itemsize,
            transcendentals=0,
        ),
        interpret=INTERPRET if interpret is None else interpret,
    )(xp, wh, wwt)
    return jnp.transpose(y.reshape(b, c, th, tw), (0, 2, 3, 1))


# ---------------------------------------------------------------------------
# XLA twins — the exact op order of the Flax layers the kernels replace
# ---------------------------------------------------------------------------

_DIMS = ("NHWC", "HWIO", "NHWC")


def _bn_reference(y, gamma, beta, mean, var, eps):
    # flax.linen.BatchNorm inference order: (x - mean) * (scale *
    # rsqrt(var + eps)) + bias — NOT the folded affine; fp32 exactness
    # of a candidate is judged against THIS.
    mul = jax.lax.rsqrt(var + jnp.asarray(eps, var.dtype))
    if gamma is not None:
        mul = mul * gamma
    return (y - mean) * mul + beta


def xla_sep2d(x, dw4, pw4, gamma, beta, mean, var, eps,
              relu_in: bool = False):
    """XLA twin of :func:`sep2d` (grouped conv → 1×1 conv → BN)."""
    cin = x.shape[-1]
    if relu_in:
        x = jnp.maximum(x, 0)
    t = jax.lax.conv_general_dilated(
        x, dw4, (1, 1), "SAME", dimension_numbers=_DIMS,
        feature_group_count=cin)
    y = jax.lax.conv_general_dilated(
        t, pw4, (1, 1), "SAME", dimension_numbers=_DIMS)
    return _bn_reference(y, gamma, beta, mean, var, eps)


def xla_pw1x1(x, w4, gamma, beta, mean, var, eps, relu: bool = False):
    """XLA twin of :func:`pw1x1` (1×1 conv → BN → relu?)."""
    y = jax.lax.conv_general_dilated(
        x, w4, (1, 1), "SAME", dimension_numbers=_DIMS)
    y = _bn_reference(y, gamma, beta, mean, var, eps)
    return jnp.maximum(y, 0) if relu else y


def xla_preproc(x, target_hw: Tuple[int, int], out_dtype):
    """XLA twin of :func:`preproc_resize` (cast → jax.image.resize)."""
    th, tw = int(target_hw[0]), int(target_hw[1])
    xf = x.astype(jnp.dtype(out_dtype))
    return jax.image.resize(xf, (x.shape[0], th, tw, x.shape[3]),
                            method="bilinear", antialias=False)


def _fold_bn(gamma, beta, mean, var, eps, cout: int):
    """BN → per-channel affine (float32): scale = γ·rsqrt(var + eps),
    shift = β − mean·scale, shaped (1, Cout) for the kernel epilogue."""
    var32 = var.astype(jnp.float32)
    scale = jax.lax.rsqrt(var32 + jnp.float32(eps))
    if gamma is not None:
        scale = scale * gamma.astype(jnp.float32)
    shift = beta.astype(jnp.float32) - mean.astype(jnp.float32) * scale
    return scale.reshape(1, cout), shift.reshape(1, cout)


# ---------------------------------------------------------------------------
# Routes — the ONLY entry points models use
# ---------------------------------------------------------------------------


def route_sep2d(x, dw_kernel, pw_kernel, bn_scale, bn_bias, bn_mean,
                bn_var, eps, *, family: str):
    """The fused sepconv body for this site, or None (caller keeps its
    XLA path — byte-identical program when nothing is adopted)."""
    b, h, w, cin = x.shape
    cout = pw_kernel.shape[-1]
    site = Site("sep2d", family, (b, h, w, cin, cout), str(x.dtype))
    feasible = _sep2d_geometry(b, h, w, cin, cout, x.dtype) is not None
    if not _decide(site, feasible):
        return None
    dw9 = dw_kernel.reshape(9, cin).astype(x.dtype)
    pw2 = pw_kernel.reshape(cin, cout).astype(x.dtype)
    scale, shift = _fold_bn(bn_scale, bn_bias, bn_mean, bn_var, eps, cout)
    return sep2d(x, dw9, pw2, scale, shift)


def route_pw1x1(x, kernel, bn_scale, bn_bias, bn_mean, bn_var, eps,
                *, relu: bool, family: str):
    """The fused 1×1 ConvBN body for this site, or None."""
    b, h, w, cin = x.shape
    cout = kernel.shape[-1]
    variant = "pw1x1_relu" if relu else "pw1x1"
    site = Site(variant, family, (b, h, w, cin, cout), str(x.dtype))
    feasible = _pw1x1_geometry(b * h * w, cin, cout, x.dtype) is not None
    if not _decide(site, feasible):
        return None
    w2 = kernel.reshape(cin, cout).astype(x.dtype)
    scale, shift = _fold_bn(bn_scale, bn_bias, bn_mean, bn_var, eps, cout)
    return pw1x1(x, w2, scale, shift, relu=relu)


def route_preproc(x, target_hw: Tuple[int, int], out_dtype,
                  *, family: str):
    """The fused cast+resize prologue for this site, or None."""
    b, h, w, c = x.shape
    th, tw = int(target_hw[0]), int(target_hw[1])
    site = Site("preproc", family, (b, h, w, c, th, tw),
                f"{jnp.dtype(x.dtype)}->{jnp.dtype(out_dtype)}")
    if not _decide(site, _preproc_geometry(h, w, th, tw)):
        return None
    return preproc_resize(x, (th, tw), out_dtype)


# ---------------------------------------------------------------------------
# The shootout (accept-if-faster + numeric contract)
# ---------------------------------------------------------------------------


class _Unsupported(RuntimeError):
    pass


def _backend_supported() -> bool:
    return INTERPRET or jax.default_backend() == "tpu"


_AUDITION_EPS = 1e-3  # keras BN default; verdict-neutral (not keyed)


def _build_shootout(site: Site):
    """(pallas_fn, xla_fn, x) at the site's exact shape with synthetic
    O(1)-magnitude operands (so the bf16 tolerance bound is
    meaningful). Parameters close over the functions as constants —
    only the activation is a traced argument."""
    rng = np.random.default_rng(0)
    if site.kernel == "preproc":
        b, h, w, c, th, tw = site.shape
        in_dt, out_dt = site.dtype.split("->")
        x = rng.integers(0, 256, size=(b, h, w, c)).astype(in_dt) \
            if np.dtype(in_dt) == np.uint8 \
            else rng.normal(size=(b, h, w, c)).astype(np.float32) \
            .astype(in_dt)
        return (lambda a: preproc_resize(a, (th, tw), out_dt),
                lambda a: xla_preproc(a, (th, tw), out_dt),
                jnp.asarray(x))
    b, h, w, cin, cout = site.shape
    dt = jnp.dtype(site.dtype.replace("pw1x1_relu", "")
                   if "->" not in site.dtype else "float32")
    x = jnp.asarray(rng.normal(size=(b, h, w, cin)).astype(np.float32),
                    dt)
    gamma = jnp.asarray(
        (np.abs(rng.normal(size=cout)) + 0.5).astype(np.float32))
    beta = jnp.asarray((rng.normal(size=cout) * 0.1).astype(np.float32))
    mean = jnp.asarray((rng.normal(size=cout) * 0.1).astype(np.float32))
    var = jnp.asarray(
        (np.abs(rng.normal(size=cout)) + 1.0).astype(np.float32))
    if site.kernel == "sep2d":
        dw = (rng.normal(size=(3, 3, 1, cin)) * 0.2).astype(np.float32)
        pw = (rng.normal(size=(1, 1, cin, cout))
              * (1.0 / np.sqrt(cin))).astype(np.float32)
        dw4, pw4 = jnp.asarray(dw, dt), jnp.asarray(pw, dt)
        scale, shift = _fold_bn(gamma, beta, mean, var, _AUDITION_EPS,
                                cout)
        dw9 = dw4.reshape(9, cin)
        pw2 = pw4.reshape(cin, cout)
        return (lambda a: sep2d(a, dw9, pw2, scale, shift),
                lambda a: xla_sep2d(a, dw4, pw4, gamma.astype(dt),
                                    beta.astype(dt), mean.astype(dt),
                                    var.astype(dt), _AUDITION_EPS),
                x)
    # pw1x1 / pw1x1_relu
    relu = site.kernel == "pw1x1_relu"
    w4 = jnp.asarray((rng.normal(size=(1, 1, cin, cout))
                      * (1.0 / np.sqrt(cin))).astype(np.float32), dt)
    scale, shift = _fold_bn(gamma, beta, mean, var, _AUDITION_EPS, cout)
    w2 = w4.reshape(cin, cout)
    return (lambda a: pw1x1(a, w2, scale, shift, relu=relu),
            lambda a: xla_pw1x1(a, w4, gamma.astype(dt), beta.astype(dt),
                                mean.astype(dt), var.astype(dt),
                                _AUDITION_EPS, relu=relu),
            x)


def _time_jitted(fn, x, repeats: int = 5, inner: int = 3) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = None
        for _ in range(inner):
            out = fn(x)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / inner)
    return best


def _audition(site: Site) -> Dict[str, Any]:
    """One shootout: build both candidates at the site's shape, check
    the numeric contract, time both, decide. Every exception path —
    including "this backend has no Mosaic lowering" (the CPU test
    suite) — lands as a clean rejected verdict, never a crash."""
    t0 = time.perf_counter()
    verdict: Dict[str, Any] = {"adopted": False, "backend": _backend_tag()}
    try:
        if not _backend_supported():
            raise _Unsupported(
                f"backend {jax.default_backend()!r} has no Mosaic "
                "lowering (set kernels.INTERPRET for interpreter-mode "
                "tests)")
        pallas_fn, xla_fn, x = _build_shootout(site)
        jp, jx = jax.jit(pallas_fn), jax.jit(xla_fn)
        y_x = jax.block_until_ready(jx(x))
        y_p = jax.block_until_ready(jp(x))  # raises if it cannot lower
        a = np.asarray(jnp.asarray(y_p, jnp.float32))
        b = np.asarray(jnp.asarray(y_x, jnp.float32))
        err = float(np.max(np.abs(a - b))) if a.size else 0.0
        verdict["max_abs_err"] = err
        out_dt = np.asarray(y_x).dtype
        if out_dt == np.float32:
            numeric_ok = bool(np.array_equal(a, b))
            contract = "fp32-exact"
        else:
            numeric_ok = err <= BF16_TOLERANCE
            contract = f"max-abs<={BF16_TOLERANCE}"
        xla_s = _time_jitted(jx, x)
        pallas_s = _time_jitted(jp, x)
        verdict["xla_s"] = xla_s
        verdict["pallas_s"] = pallas_s
        if not numeric_ok:
            verdict["reason"] = (f"numeric contract violated "
                                 f"({contract}, err={err:.3g})")
        elif pallas_s > ADOPT_SPEEDUP * xla_s:
            verdict["reason"] = (f"not faster (pallas {pallas_s * 1e6:.0f}"
                                 f"us vs xla {xla_s * 1e6:.0f}us, needs "
                                 f"<= {ADOPT_SPEEDUP:.2f}x)")
        else:
            verdict["adopted"] = True
            verdict["reason"] = (f"{xla_s / max(pallas_s, 1e-12):.2f}x "
                                 "speedup, numerics in contract")
    except Exception as e:  # sparkdl: allow(broad-retry): ANY audition
        # failure (no Mosaic, lowering error, OOM) must become a clean
        # rejected verdict — the XLA path always remains shippable
        verdict["reason"] = f"{type(e).__name__}: {e}"
    dt = time.perf_counter() - t0
    verdict["audition_s"] = dt
    if telemetry.active() is not None:
        telemetry.observe(telemetry.M_KERNEL_AUTOTUNE_S, dt)
        telemetry.count(telemetry.M_KERNEL_ADOPTED if verdict["adopted"]
                        else telemetry.M_KERNEL_REJECTED)
    logger.info("kernel audition %s: %s — %s", _site_key(site),
                "ADOPTED" if verdict["adopted"] else "rejected",
                verdict["reason"])
    return verdict


def ensure_verdict(site: Site) -> Dict[str, Any]:
    """The settled verdict for ``site``, running the shootout once if
    this (site, backend) was never auditioned. Single-flight per site:
    a concurrent caller of the same site waits for the owner's verdict
    instead of double-timing the hardware."""
    key = _site_key(site)
    while True:
        found = verdict_for(site)
        if found is not None:
            return found
        with _verdict_lock:
            event = _inflight.get(key)
            if event is None:
                event = threading.Event()
                _inflight[key] = event
                owner = True
            else:
                owner = False
        if not owner:
            event.wait()
            continue  # owner settled (or died trying) — re-read
        try:
            verdict = _audition(site)
            with _verdict_lock:
                _verdicts[key] = verdict
            _persist_verdict(key, verdict)
            return verdict
        finally:
            with _verdict_lock:
                _inflight.pop(key, None)
            event.set()
